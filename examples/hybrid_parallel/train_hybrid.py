#!/usr/bin/env python
"""Hybrid DP×TP training over a 2-D ('data','model') mesh.

Reference parity: SURVEY.md §2.8 "Hybrid DP×MP" — the reference composed
2-D layouts by hand with ``CommunicatorBase.split`` sub-communicators [uv].
TPU-native the layout is one mesh and ONE jitted step: the model dimension
of the MLP weights is sharded over 'model' (tensor parallelism, psum over
ICI inside the layer), the batch over 'data' (gradient mean inserted by
autodiff), and XLA schedules both collectives inside the step.

Run:  python examples/hybrid_parallel/train_hybrid.py --devices 8 --tp 2
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    parser = argparse.ArgumentParser(description="ChainerMN-TPU example: hybrid DP x TP")
    parser.add_argument("--devices", type=int, default=0,
                        help="fake an N-device CPU mesh (0 = real chips)")
    parser.add_argument("--tp", type=int, default=2, help="model-axis size")
    parser.add_argument("--d-model", type=int, default=256)
    parser.add_argument("--d-hidden", type=int, default=1024)
    parser.add_argument("--batchsize", type=int, default=64, help="global batch")
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--lr", type=float, default=1e-2)
    args = parser.parse_args()

    if args.devices:
        import jax
        jax.config.update("jax_platforms", "cpu")
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import chainermn_tpu as mn
    from chainermn_tpu.parallel import (
        init_tp_mlp_params, make_hybrid_shard_map_step, shard_pytree,
        state_specs_like, tp_mlp, tp_mlp_specs)

    n = len(jax.devices())
    if n % args.tp:
        raise SystemExit(f"device count {n} not divisible by --tp {args.tp}")
    dp = n // args.tp
    mesh = mn.make_nd_mesh(("data", "model"), (dp, args.tp))
    print(f"mesh {dp}x{args.tp} (data x model)  global_batch={args.batchsize}")

    params = init_tp_mlp_params(
        jax.random.PRNGKey(0), args.d_model, args.d_hidden)
    specs = tp_mlp_specs("model")
    optimizer = optax.adam(args.lr)

    def loss_fn(p, batch):
        y = tp_mlp(batch[0], p, axis_name="model")
        return jnp.mean((y - batch[1]) ** 2)

    step = make_hybrid_shard_map_step(
        loss_fn, optimizer, mesh, params, specs)
    p = shard_pytree(params, mesh, specs)
    st = shard_pytree(optimizer.init(params), mesh,
                      state_specs_like(optimizer, params, specs))

    rng = np.random.RandomState(0)
    xs = rng.randn(args.batchsize, args.d_model).astype(np.float32)
    w_true = rng.randn(args.d_model, args.d_model).astype(np.float32) / args.d_model
    batch = (jax.device_put(xs, NamedSharding(mesh, P("data"))),
             jax.device_put(xs @ w_true, NamedSharding(mesh, P("data"))))

    p, st, loss = step(p, st, batch)  # compile
    t0 = time.time()
    for i in range(args.steps):
        p, st, loss = step(p, st, batch)
        if (i + 1) % 10 == 0:
            print(f"step {i + 1}  loss {float(loss):.6f}")
    dt = time.time() - t0
    print(f"{args.steps / dt:.1f} steps/sec  final loss {float(loss):.6f}")


if __name__ == "__main__":
    main()
