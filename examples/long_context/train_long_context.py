#!/usr/bin/env python
"""Long-context LM training with sequence parallelism (ring attention).

The rebuild brief's long-context pillar, end-to-end (the 2017 reference
predates all of this — SURVEY.md §5): the SEQUENCE is sharded across the
mesh, each chip holds ``S/P`` tokens of every layer's activations and
``S/P`` keys/values, and K/V blocks rotate the ICI ring inside one jitted
step (``parallel.ring_attention``, flash local blocks on TPU).  Params are
replicated; gradient sync is the same AD-inserted psum as data parallelism.
Max trainable context grows LINEARLY with chips at constant per-chip HBM.

Run:  python examples/long_context/train_long_context.py --devices 8 --seq-len 512
      python examples/long_context/train_long_context.py --devices 8 --seq-len 2048 --attn-impl xla
"""

import argparse
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    parser = argparse.ArgumentParser(
        description="ChainerMN-TPU example: sequence-parallel long-context LM")
    parser.add_argument("--devices", type=int, default=0,
                        help="fake an N-device CPU mesh (0 = real chips)")
    parser.add_argument("--vocab", type=int, default=256)
    parser.add_argument("--d-model", type=int, default=64)
    parser.add_argument("--n-heads", type=int, default=4)
    parser.add_argument("--n-layers", type=int, default=2)
    parser.add_argument("--seq-len", type=int, default=512)
    parser.add_argument("--batchsize", type=int, default=2)
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--lr", type=float, default=1e-2)
    parser.add_argument("--attn-impl", default="xla", choices=["xla", "flash"],
                        help="flash = Pallas kernel (TPU); xla is exact too")
    args = parser.parse_args()

    if args.devices:
        import jax
        jax.config.update("jax_platforms", "cpu")
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")

    import jax
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import chainermn_tpu as mn
    from chainermn_tpu._compat import shard_map
    from chainermn_tpu.parallel import (
        init_tp_transformer_lm, sp_transformer_lm_loss)

    n = len(jax.devices())
    if args.seq_len % n:
        raise SystemExit(f"--seq-len {args.seq_len} not divisible by {n} chips")
    mesh = mn.make_mesh(axis_name="sp")
    print(f"{n} chips, {args.seq_len} tokens → {args.seq_len // n} "
          f"tokens/chip  attn={args.attn_impl}")

    params = init_tp_transformer_lm(
        jax.random.PRNGKey(0), args.vocab, args.d_model, args.n_heads,
        args.n_layers, max_len=args.seq_len)
    optimizer = optax.adam(args.lr)
    loss_fn = partial(sp_transformer_lm_loss,
                      head_dim=args.d_model // args.n_heads,
                      axis_name="sp", attn_impl=args.attn_impl)

    def spmd(p, opt_state, batch):
        def global_loss(pp):
            return jax.lax.pmean(loss_fn(pp, batch), "sp")

        loss, grads = jax.value_and_grad(global_loss)(p)
        updates, opt_state = optimizer.update(grads, opt_state, p)
        return optax.apply_updates(p, updates), opt_state, loss

    seq_spec = (P(None, "sp"), P(None, "sp"))
    # Interpreted (off-TPU) Pallas flash can't propagate varying-axes;
    # the compiled TPU path keeps the check (same policy as the factories).
    interpreted_flash = (args.attn_impl == "flash"
                         and jax.default_backend() != "tpu")
    step = jax.jit(shard_map(
        spmd, mesh=mesh,
        in_specs=(P(), P(), seq_spec), out_specs=(P(), P(), P()),
        check_vma=not interpreted_flash))

    p = mn.replicate(params, mesh)
    st = mn.replicate(optimizer.init(params), mesh)

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, args.vocab,
                         (args.batchsize, args.seq_len + 1)).astype(np.int32)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]  # shift BEFORE sharding
    batch = tuple(jax.device_put(t, NamedSharding(mesh, P(None, "sp")))
                  for t in (inputs, targets))

    p, st, loss = step(p, st, batch)  # compile
    print(f"initial loss {float(loss):.4f}  (log V = {np.log(args.vocab):.4f})")
    t0 = time.time()
    for i in range(args.steps):
        p, st, loss = step(p, st, batch)
        if (i + 1) % 10 == 0:
            print(f"step {i + 1}  loss {float(loss):.4f}")
    dt = time.time() - t0
    tok_s = args.steps * args.batchsize * args.seq_len / dt
    print(f"{tok_s:,.0f} tokens/sec  final loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
