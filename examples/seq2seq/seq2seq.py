#!/usr/bin/env python
"""Distributed seq2seq translation — BASELINE config #3.

Reference parity: ``examples/seq2seq/seq2seq.py`` [uv] (SURVEY.md §2.9):
rank 0 loads the corpus and vocabularies → ``bcast_obj`` the vocab →
``scatter_dataset`` the pairs → multi-node optimizer → per-epoch multi-node
evaluation → greedy translation samples.  The reference trained En→Fr
WMT under mpiexec; with no corpus on disk a synthetic reversal
"translation" corpus exercises the identical pipeline (ragged pairs,
object broadcast, scatter, padded buckets).

Run:  python examples/seq2seq/seq2seq.py --devices 8     (virtual CPU mesh)
      python examples/seq2seq/seq2seq.py                 (real chips)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def make_corpus(n, vocab, seed, min_len=2, max_len=10):
    """Ragged (source, reversed-source) token pairs, ids >= N_SPECIAL."""
    import numpy as np
    from chainermn_tpu.models.seq2seq import N_SPECIAL

    rng = np.random.RandomState(seed)
    pairs = []
    for _ in range(n):
        k = rng.randint(min_len, max_len + 1)
        s = rng.randint(N_SPECIAL, vocab, size=k).tolist()
        pairs.append((s, s[::-1]))
    return pairs


def main():
    parser = argparse.ArgumentParser(description="ChainerMN-TPU example: seq2seq")
    parser.add_argument("--communicator", type=str, default="xla")
    parser.add_argument("--devices", type=int, default=0,
                        help="fake an N-device CPU mesh (0 = real chips)")
    parser.add_argument("--batchsize", type=int, default=64, help="global batch")
    parser.add_argument("--epoch", type=int, default=8)
    parser.add_argument("--unit", type=int, default=128)
    parser.add_argument("--layer", type=int, default=2)
    parser.add_argument("--lr", type=float, default=3e-3)
    parser.add_argument("--vocab", type=int, default=32)
    parser.add_argument("--n-train", type=int, default=4096)
    parser.add_argument("--n-val", type=int, default=256)
    parser.add_argument("--bucket", type=int, default=12, help="padded length")
    args = parser.parse_args()

    if args.devices:
        import jax
        jax.config.update("jax_platforms", "cpu")
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import chainermn_tpu as mn
    from chainermn_tpu.iterators import SerialIterator
    from chainermn_tpu.models.seq2seq import (
        PAD, EOS, Seq2seq, encode_pairs, masked_cross_entropy, token_accuracy)
    from chainermn_tpu.training import StandardUpdater, Trainer, extensions

    comm = mn.create_communicator(args.communicator)
    print(f"communicator={args.communicator} size={comm.size} "
          f"backend={jax.default_backend()}")

    # Rank 0 owns the corpus + vocab; everyone else receives them over the
    # object lane (reference: bcast of the vocabularies [uv]).
    if comm.owns_rank(0):
        vocab = {"size": args.vocab}
        train_pairs = make_corpus(args.n_train, args.vocab, seed=1)
        val_pairs = make_corpus(args.n_val, args.vocab, seed=2)
    else:
        vocab, train_pairs, val_pairs = None, None, None
    vocab = comm.bcast_obj(vocab, root=0)
    train_scattered = mn.scatter_dataset(
        comm.bcast_obj(train_pairs, root=0), comm, shuffle=True, seed=0)
    val_pairs = comm.bcast_obj(val_pairs, root=0)

    model = Seq2seq(vocab["size"], vocab["size"], n_units=args.unit,
                    n_layers=args.layer,
                    dtype=jnp.bfloat16 if jax.default_backend() == "tpu"
                    else jnp.float32)
    src0, tin0, _ = encode_pairs(train_pairs[:2] if train_pairs else
                                 make_corpus(2, vocab["size"], 9),
                                 args.bucket, args.bucket)
    params = model.init(jax.random.PRNGKey(0), src0, tin0)
    opt = mn.create_multi_node_optimizer(optax.adam(args.lr), comm)

    def loss_fn(p, batch):
        src, tin, tout = batch
        logits = model.apply(p, src, tin)
        return masked_cross_entropy(logits, tout), token_accuracy(logits, tout)

    raw_step = mn.make_train_step(loss_fn, opt, has_aux=True, donate=False)

    def step_fn(state, batch):
        p, s = state
        p, s, loss, acc = raw_step(p, s, batch)
        return (p, s), {"main/loss": loss, "main/accuracy": acc}

    def converter(batch):
        return encode_pairs(batch, args.bucket, args.bucket)

    # Global-batch iterator over the union of shards: single-controller owns
    # all ranks, so iterate the whole (scattered) dataset and let shard_batch
    # split it across the mesh — each chip sees exactly its scattered shard's
    # share of every global batch.
    flat = [shard[i] for r in range(comm.size)
            for shard in [train_scattered.shard(r)]
            for i in range(len(shard))]
    it = SerialIterator(flat, args.batchsize, shuffle=True, seed=0)
    state = (mn.replicate(params), mn.replicate(opt.init(params)))
    updater = StandardUpdater(it, step_fn, state, converter=converter)
    trainer = Trainer(updater, (args.epoch, "epoch"), out="result_seq2seq")

    vsrc, vtin, vtout = encode_pairs(val_pairs, args.bucket, args.bucket)

    @jax.jit
    def eval_batch(p, src, tin, tout):
        logits = model.apply(p, src, tin)
        return masked_cross_entropy(logits, tout), token_accuracy(logits, tout)

    def evaluate(_):
        p = updater.state[0]
        loss, acc = eval_batch(p, vsrc, vtin, vtout)
        return {"loss": float(loss), "accuracy": float(acc)}

    log = extensions.LogReport(trigger=(1, "epoch"))
    trainer.extend(extensions.EvaluatorExtension(evaluate, None, trigger=(1, "epoch")))
    trainer.extend(log)
    trainer.extend(extensions.PrintReport(
        ["epoch", "iteration", "main/loss", "main/accuracy",
         "validation/loss", "validation/accuracy", "elapsed_time"], log))
    trainer.run()

    # Greedy translation samples (reference printed example translations).
    toks = np.asarray(model.apply(
        updater.state[0], vsrc[:4], max_len=args.bucket,
        method=Seq2seq.translate))
    for i in range(4):
        src_toks = [int(t) for t in vsrc[i] if t != PAD]
        out_toks = [int(t) for t in toks[i] if t not in (PAD, EOS)]
        ok = out_toks == src_toks[::-1]
        print(f"src={src_toks} → out={out_toks} {'✓' if ok else '✗'}")

    # Corpus BLEU over the whole validation set (reference parity: the
    # reference's seq2seq scored its translations with BLEU).
    def translate_fn(srcs):
        src_arr, _, _ = encode_pairs(
            [(list(s), list(s)) for s in srcs], args.bucket, args.bucket)
        out = np.asarray(model.apply(
            updater.state[0], src_arr, max_len=args.bucket,
            method=Seq2seq.translate))
        return [[int(t) for t in row if t not in (PAD, EOS)] for row in out]

    # val_pairs already holds the ragged (source, reversed-source) examples.
    # Multi-controller: each process scores only its strided slice (plain
    # lists are treated as LOCAL shards; the evaluator pools the counts),
    # so BLEU is identical for any host count and nothing decodes P times.
    if comm.inter_size > 1:
        owned = [r for r in range(comm.size) if comm.owns_rank(r)]
        local_pairs = [ex for i, ex in enumerate(val_pairs)
                       if i % comm.size in owned]
    else:
        local_pairs = val_pairs
    bleu_eval = mn.bleu_evaluator(translate_fn, comm)
    print(f"validation BLEU: {bleu_eval([local_pairs])['bleu']:.4f}")


if __name__ == "__main__":
    main()
