#!/usr/bin/env python
"""Model-parallel MLP split across chips — BASELINE config #5.

Reference parity: ``examples/model_parallel/`` [uv] (SURVEY.md §2.9): an
MLP split over two ranks with ``chainermn.functions.send/recv`` inside
``MultiNodeChainList``, plus ``create_empty_dataset`` feeding the
non-input rank.

Two faces are demonstrated:
1. MultiNodeChainList — the reference-shaped graph container (one jitted
   differentiable program).
2. Raw SPMD send/recv — the same split written with
   ``chainermn_tpu.functions`` inside shard_map, activations crossing chips
   over ICI with autodiff routing gradients back (reference §3.5 semantics).

Run:  python examples/model_parallel/train_model_parallel.py --devices 8
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    parser = argparse.ArgumentParser(description="ChainerMN-TPU: model parallel")
    parser.add_argument("--devices", type=int, default=0)
    parser.add_argument("--steps", type=int, default=40)
    parser.add_argument("--hidden", type=int, default=32)
    args = parser.parse_args()

    if args.devices:
        import jax
        jax.config.update("jax_platforms", "cpu")
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    import chainermn_tpu as mn
    from chainermn_tpu import functions as F
    from chainermn_tpu.links import MultiNodeChainList

    comm = mn.create_communicator("xla")
    mesh = comm.mesh
    print(f"chips: {comm.size}")
    if comm.size < 2:
        raise SystemExit(
            "model parallelism needs at least 2 ranks to place stages on; "
            "run with --devices 2 (or more) to fake a multi-chip mesh on "
            "one host")

    rng = np.random.RandomState(0)
    xs = rng.randn(64, 16).astype(np.float32)
    ys = (np.sin(xs.sum(axis=1, keepdims=True)) > 0).astype(np.float32)
    # non-input ranks iterate a placeholder of the same length (reference:
    # create_empty_dataset feeding rank 1)
    empty = mn.create_empty_dataset(list(range(len(xs))))
    assert len(empty) == len(xs)

    def dense(key, n_in, n_out):
        k = jax.random.PRNGKey(key)
        return {"w": jax.random.normal(k, (n_in, n_out)) * 0.3,
                "b": jnp.zeros((n_out,))}

    def stage0(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    def stage1(p, h):
        return h @ p["w"] + p["b"]

    # ---- face 1: MultiNodeChainList ----
    mnc = MultiNodeChainList(comm)
    mnc.add_link(stage0, dense(0, 16, args.hidden), rank=0,
                 rank_in=None, rank_out=1)
    mnc.add_link(stage1, dense(1, args.hidden, 1), rank=1,
                 rank_in=0, rank_out=None)

    def loss_chain(plist):
        logits = mnc(jnp.asarray(xs), params=plist)
        return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, ys))

    opt = optax.adam(1e-2)
    # Fused-jit face: one jit argument → the default uncommitted params()
    # (params(placed=True) would pin each stage's pytree to its chip).
    plist = mnc.params()
    state = opt.init(plist)

    @jax.jit
    def step_chain(pl, st):
        l, g = jax.value_and_grad(loss_chain)(pl)
        up, st = opt.update(g, st, pl)
        return optax.apply_updates(pl, up), st, l

    for i in range(args.steps):
        plist, state, loss = step_chain(plist, state)
        loss.block_until_ready()
        if i in (0, args.steps - 1):
            print(f"[chain-list] step {i}  loss {float(loss):.4f}")

    # ---- face 2: raw SPMD send/recv over ICI ----
    # Stage parameters are stacked over the mesh axis: rank 0's slab holds
    # stage-0 weights, rank 1's slab stage-1 weights (padded), other ranks
    # idle — the minimal faithful port of the reference's 2-process MLP.
    w0, w1 = dense(0, 16, args.hidden), dense(1, args.hidden, 1)

    def spmd_fwd(w0_, b0_, w1_, b1_, x):
        h = jnp.tanh(x @ w0_[0] + b0_[0])          # rank 0 computes...
        h = F.send(h, dest=1, source=0)            # ...ships over ICI...
        logits = h @ w1_[0] + b1_[0]               # ...rank 1 finishes
        out = F.send(logits, dest=0, source=1)     # result home to rank 0
        return out

    def spmd_loss(w0_, b0_, w1_, b1_, x, y):
        out = spmd_fwd(w0_, b0_, w1_, b1_, x)
        per = optax.sigmoid_binary_cross_entropy(out, y)
        idx = jax.lax.axis_index("mn")
        valid = jnp.where(idx == 0, per.mean(), 0.0)
        return jax.lax.psum(valid, "mn")

    smapped = jax.jit(jax.shard_map(
        jax.value_and_grad(spmd_loss, argnums=(0, 1, 2, 3)),
        mesh=mesh,
        in_specs=(P("mn"), P("mn"), P("mn"), P("mn"), P(), P()),
        out_specs=(P(), (P("mn"), P("mn"), P("mn"), P("mn")))))

    n = comm.size
    stack = lambda a: jnp.broadcast_to(a[None], (n,) + a.shape)
    w0s, b0s = stack(w0["w"]), stack(w0["b"])
    w1s, b1s = stack(w1["w"]), stack(w1["b"])
    for i in range(args.steps):
        loss, grads = smapped(w0s, b0s, w1s, b1s, jnp.asarray(xs), jnp.asarray(ys))
        w0s, b0s, w1s, b1s = (
            a - 0.05 * g for a, g in zip((w0s, b0s, w1s, b1s), grads))
        float(loss)
        if i in (0, args.steps - 1):
            print(f"[spmd p2p]   step {i}  loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
