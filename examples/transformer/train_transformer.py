#!/usr/bin/env python
"""Decoder-only transformer LM with DP×TP over a ('data','model') mesh.

Beyond-reference workload (SURVEY.md §2.8: the reference could only express
TP "manually"; it had no transformer): Megatron-style sharding — heads and
MLP columns over the model axis, vocab-parallel embedding + loss (the full
logits never materialize), flash attention optional — composed with data
parallelism in ONE jitted step via make_hybrid_shard_map_step.

Run:  python examples/transformer/train_transformer.py --devices 8 --tp 2
      python examples/transformer/train_transformer.py --devices 8 --tp 4 --attn-impl flash
"""

import argparse
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    parser = argparse.ArgumentParser(
        description="ChainerMN-TPU example: DP x TP transformer LM")
    parser.add_argument("--devices", type=int, default=0,
                        help="fake an N-device CPU mesh (0 = real chips)")
    parser.add_argument("--tp", type=int, default=2, help="model-axis size")
    parser.add_argument("--vocab", type=int, default=256)
    parser.add_argument("--d-model", type=int, default=64)
    parser.add_argument("--n-heads", type=int, default=4)
    parser.add_argument("--kv-heads", type=int, default=None,
                        help="GQA: fewer KV heads than Q heads (must stay "
                             "divisible by --tp)")
    parser.add_argument("--n-layers", type=int, default=2)
    parser.add_argument("--seq-len", type=int, default=32)
    parser.add_argument("--batchsize", type=int, default=32, help="global batch")
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--lr", type=float, default=1e-2)
    parser.add_argument("--attn-impl", default="auto",
                        choices=["auto", "xla", "flash"])
    parser.add_argument("--ce-impl", default="auto",
                        choices=["auto", "xla", "fused"],
                        help="LM-head loss path; 'fused' = the Pallas "
                             "online-softmax kernels (big-vocab heads)")
    args = parser.parse_args()

    if args.devices:
        import jax
        jax.config.update("jax_platforms", "cpu")
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")

    import jax
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import chainermn_tpu as mn
    from chainermn_tpu.parallel import (
        init_tp_transformer_lm, make_hybrid_shard_map_step, shard_pytree,
        state_specs_like, tp_transformer_lm_loss, transformer_lm_specs)

    n = len(jax.devices())
    if n % args.tp:
        raise SystemExit(f"device count {n} not divisible by --tp {args.tp}")
    dp = n // args.tp
    mesh = mn.make_nd_mesh(("data", "model"), (dp, args.tp))
    print(f"mesh {dp}x{args.tp} (data x model)  "
          f"LM: V={args.vocab} D={args.d_model} H={args.n_heads} "
          f"L={args.n_layers} S={args.seq_len}  attn={args.attn_impl}")

    params = init_tp_transformer_lm(
        jax.random.PRNGKey(0), args.vocab, args.d_model, args.n_heads,
        args.n_layers, max_len=args.seq_len, n_kv_heads=args.kv_heads)
    specs = transformer_lm_specs(params, "model")
    optimizer = optax.adam(args.lr)
    loss_fn = partial(tp_transformer_lm_loss,
                      head_dim=args.d_model // args.n_heads,
                      axis_name="model", attn_impl=args.attn_impl,
                      ce_impl=args.ce_impl)

    step = make_hybrid_shard_map_step(loss_fn, optimizer, mesh, params, specs)
    p = shard_pytree(params, mesh, specs)
    st = shard_pytree(optimizer.init(params), mesh,
                      state_specs_like(optimizer, params, specs))

    # tiny synthetic corpus: fixed random token sequences to memorize
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, args.vocab,
                         (args.batchsize, args.seq_len + 1)).astype(np.int32)
    batch = (jax.device_put(tokens, NamedSharding(mesh, P("data"))),)

    p, st, loss = step(p, st, batch)  # compile
    print(f"initial loss {float(loss):.4f}  (log V = {np.log(args.vocab):.4f})")
    t0 = time.time()
    for i in range(args.steps):
        p, st, loss = step(p, st, batch)
        if (i + 1) % 20 == 0:
            print(f"step {i + 1}  loss {float(loss):.4f}")
    dt = time.time() - t0
    tok_s = args.steps * args.batchsize * args.seq_len / dt
    print(f"{tok_s:,.0f} tokens/sec  final loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
