#!/usr/bin/env python
"""Train a tiny LM on a toy corpus, then decode from it with the KV cache.

Beyond-reference workload: the reference's only generation was seq2seq
greedy translate; this demonstrates the decoding stack end-to-end —
DP×TP training (make_hybrid_shard_map_step) into TP-sharded KV-cache
incremental decoding (make_lm_generator), with RoPE/GQA options.

The toy corpus is deterministic arithmetic-progression sequences, so a
properly trained model + a CORRECT cache produce visibly right
continuations (each token = previous + step mod V) — an eyeball check on
top of the exactness tests.

Run:  python examples/generate/generate.py --devices 8 --tp 2
      python examples/generate/generate.py --devices 8 --tp 2 --pos-impl rope --kv-heads 2 --temperature 0.7
"""

import argparse
import os
import sys
from functools import partial

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def make_corpus(rng, n, seq_len, vocab):
    """Arithmetic progressions mod vocab: fully learnable structure."""
    import numpy as np

    starts = rng.randint(0, vocab, n)
    steps = rng.randint(1, 4, n)
    pos = np.arange(seq_len + 1)
    return ((starts[:, None] + steps[:, None] * pos[None]) % vocab
            ).astype("int32")


def main():
    parser = argparse.ArgumentParser(
        description="ChainerMN-TPU example: LM training + KV-cache decoding")
    parser.add_argument("--devices", type=int, default=0)
    parser.add_argument("--tp", type=int, default=2)
    parser.add_argument("--vocab", type=int, default=64)
    parser.add_argument("--d-model", type=int, default=64)
    parser.add_argument("--n-heads", type=int, default=4)
    parser.add_argument("--kv-heads", type=int, default=None)
    parser.add_argument("--n-layers", type=int, default=2)
    parser.add_argument("--seq-len", type=int, default=24)
    parser.add_argument("--pos-impl", default="learned",
                        choices=["learned", "rope"])
    parser.add_argument("--steps", type=int, default=150)
    parser.add_argument("--lr", type=float, default=1e-2)
    parser.add_argument("--prompt-len", type=int, default=6)
    parser.add_argument("--max-new-tokens", type=int, default=10)
    parser.add_argument("--temperature", type=float, default=0.0)
    args = parser.parse_args()

    if args.devices:
        import jax
        jax.config.update("jax_platforms", "cpu")
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")

    import jax
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import chainermn_tpu as mn
    from chainermn_tpu.parallel import (
        init_tp_transformer_lm, make_hybrid_shard_map_step, make_lm_generator,
        shard_pytree, state_specs_like, tp_transformer_lm_loss,
        transformer_lm_specs)

    n = len(jax.devices())
    dp = n // args.tp
    mesh = mn.make_nd_mesh(("data", "model"), (dp, args.tp))
    head_dim = args.d_model // args.n_heads

    params = init_tp_transformer_lm(
        jax.random.PRNGKey(0), args.vocab, args.d_model, args.n_heads,
        args.n_layers, max_len=max(args.seq_len,
                                   args.prompt_len + args.max_new_tokens),
        pos_impl=args.pos_impl, n_kv_heads=args.kv_heads)
    specs = transformer_lm_specs(params, "model")
    optimizer = optax.adam(args.lr)
    loss_fn = partial(tp_transformer_lm_loss, head_dim=head_dim,
                      axis_name="model")
    step = make_hybrid_shard_map_step(loss_fn, optimizer, mesh, params, specs,
                                      donate=False)
    p = shard_pytree(params, mesh, specs)
    st = shard_pytree(optimizer.init(params), mesh,
                      state_specs_like(optimizer, params, specs))

    rng = np.random.RandomState(0)
    for i in range(args.steps):
        tokens = make_corpus(rng, 8 * dp, args.seq_len, args.vocab)
        batch = (jax.device_put(tokens, NamedSharding(mesh, P("data"))),)
        p, st, loss = step(p, st, batch)
        if i % 30 == 0 or i == args.steps - 1:
            print(f"step {i:3d}  loss {float(loss):.4f}")

    gen = make_lm_generator(mesh, "model", head_dim=head_dim,
                            max_new_tokens=args.max_new_tokens,
                            temperature=args.temperature)
    # Eval corpus long enough to hold prompt + continuation (the model's
    # max_len already covers it).
    eval_len = max(args.seq_len, args.prompt_len + args.max_new_tokens)
    test = make_corpus(np.random.RandomState(99), 4, eval_len, args.vocab)
    prompts = test[:, : args.prompt_len]
    want = test[:, args.prompt_len: args.prompt_len + args.max_new_tokens]
    out = np.asarray(gen(p, prompts, jax.random.PRNGKey(1)))
    correct = (out == want).mean()
    for i in range(len(prompts)):
        print(f"prompt {prompts[i].tolist()} -> {out[i].tolist()} "
              f"(true continuation {want[i].tolist()})")
    print(f"continuation accuracy: {correct:.2f}"
          + ("  (sampled; exactness not expected)" if args.temperature > 0
             else ""))


if __name__ == "__main__":
    main()
