#!/usr/bin/env python
"""MNIST with fault-tolerant checkpoint/auto-resume.

Reference parity: ``examples/mnist/train_mnist_checkpoint.py`` [uv]
(SURVEY.md §2.9) — the checkpointer-exercising MNIST variant: snapshots
every epoch, and a SIGKILL'd/restarted job resumes from the newest
gang-consistent generation with identical training state (params, optimizer
momentum, data order).

Demo the resume end-to-end in one command with ``--kill-at-epoch``: the
run "crashes" mid-training, then a fresh process resumes and finishes:

    python examples/mnist/train_mnist_checkpoint.py --devices 8 --kill-at-epoch 2
    python examples/mnist/train_mnist_checkpoint.py --devices 8   # resumes
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from train_mnist import make_synthetic_mnist  # noqa: E402


def main():
    parser = argparse.ArgumentParser(
        description="ChainerMN-TPU example: MNIST with checkpoint/resume")
    parser.add_argument("--devices", type=int, default=0)
    parser.add_argument("--batchsize", type=int, default=128)
    parser.add_argument("--epoch", type=int, default=4)
    parser.add_argument("--unit", type=int, default=256)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--out", default="result_mnist_ckpt")
    parser.add_argument("--kill-at-epoch", type=int, default=0,
                        help="simulate a crash after this many epochs (0=off)")
    args = parser.parse_args()

    if args.devices:
        import jax
        jax.config.update("jax_platforms", "cpu")
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")

    import jax
    import jax.numpy as jnp
    import optax

    import chainermn_tpu as mn
    from chainermn_tpu.models import MLP, accuracy, cross_entropy_loss
    from chainermn_tpu.training import StandardUpdater, Trainer, extensions

    mn.init_distributed()
    comm = mn.create_communicator("xla")
    mesh = comm.mesh

    # The updater shards each global batch across the mesh itself, so the
    # iterator runs over the full dataset (scatter_dataset is exercised by
    # the base train_mnist.py); shuffle order across restarts comes from
    # the iterator's CHECKPOINTED rng state, not the seed alone.
    train = make_synthetic_mnist(4096, seed=0)
    it = mn.SerialIterator(train, args.batchsize * comm.size,
                           shuffle=True, seed=1)

    model = MLP(n_units=args.unit)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 784)))
    optimizer = mn.create_multi_node_optimizer(optax.adam(args.lr), comm)

    def loss_fn(p, batch):
        xs, ys = batch
        logits = model.apply(p, xs)
        return cross_entropy_loss(logits, ys), accuracy(logits, ys)

    raw_step = mn.make_train_step(loss_fn, optimizer, mesh=mesh,
                                  has_aux=True, donate=False)

    def step_fn(state, batch):
        p, st = state
        p, st, loss, acc = raw_step(p, st, batch)
        return (p, st), {"main/loss": loss, "main/acc": acc}

    state = (mn.replicate(params, mesh),
             mn.replicate(optimizer.init(params), mesh))
    trainer = Trainer(StandardUpdater(it, step_fn, state),
                      (args.epoch, "epoch"), out=args.out)
    log = extensions.LogReport(trigger=(1, "epoch"))
    trainer.extend(log)
    trainer.extend(extensions.PrintReport(
        ["epoch", "iteration", "main/loss", "main/acc"], log))

    ckpt = mn.create_multi_node_checkpointer(
        "mnist", comm, path=os.path.join(args.out, "checkpoints"), keep=2)
    trainer.extend(ckpt, trigger=(1, "epoch"))

    # ---- auto-resume (reference: maybe_load after restart [uv]) ----
    snap, resumed_iter = ckpt.maybe_load()
    if resumed_iter is not None:
        trainer.load_checkpoint_state(snap)
        if comm.rank == 0:
            print(f"resumed from iteration {resumed_iter} "
                  f"(epoch {trainer.epoch})")

    if args.kill_at_epoch:
        class _Killer:
            trigger = (args.kill_at_epoch, "epoch")

            def __call__(self, trainer):
                print(f"simulating crash at epoch {trainer.epoch} "
                      f"(checkpoints retained)", flush=True)
                os._exit(99)

        trainer.extend(_Killer(), name="killer")

    trainer.run()
    if comm.rank == 0:
        print(f"done: epoch {trainer.epoch}, "
              f"final loss {log.log[-1]['main/loss']:.4f}")


if __name__ == "__main__":
    main()
