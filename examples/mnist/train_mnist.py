#!/usr/bin/env python
"""Data-parallel MNIST MLP — BASELINE config #1.

Reference parity: ``examples/mnist/train_mnist.py`` [uv] (SURVEY.md §2.9):
create_communicator → scatter_dataset → multi-node optimizer → train →
multi-node evaluator.  The reference ran one MPI process per GPU under
``mpiexec``; here one process drives every chip of the slice through a
single jitted SPMD step.

With no dataset on disk a synthetic, *learnable* MNIST stand-in is
generated (labels are a linear function of the image), so loss/accuracy
trends demonstrate end-to-end correctness without network access.
Run:  python examples/mnist/train_mnist.py --devices 8   (virtual CPU mesh)
      python examples/mnist/train_mnist.py               (real chips)
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def make_synthetic_mnist(n, seed=0):
    """Learnable stand-in: zero-mean images, labels from one fixed linear
    map shared by every split (so train/val measure the same task)."""
    import numpy as np
    w_true = np.random.RandomState(42).randn(784, 10).astype(np.float32)
    xs = np.random.RandomState(seed).randn(n, 784).astype(np.float32)
    ys = (xs @ w_true).argmax(-1).astype(np.int32)
    return list(zip(xs, ys))


def main():
    parser = argparse.ArgumentParser(description="ChainerMN-TPU example: MNIST")
    parser.add_argument("--communicator", type=str, default="xla",
                        help="xla | pure_nccl | hierarchical | ... | naive")
    parser.add_argument("--devices", type=int, default=0,
                        help="fake an N-device CPU mesh (0 = use real chips)")
    parser.add_argument("--batchsize", type=int, default=128, help="per-rank batch")
    parser.add_argument("--epoch", type=int, default=3)
    parser.add_argument("--unit", type=int, default=256)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--n-train", type=int, default=8192)
    parser.add_argument("--n-val", type=int, default=1024)
    parser.add_argument("--double-buffering", action="store_true")
    args = parser.parse_args()

    if args.devices:
        import jax
        jax.config.update("jax_platforms", "cpu")
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import chainermn_tpu as mn
    from chainermn_tpu.models import MLP, accuracy, cross_entropy_loss

    mn.init_distributed()
    comm = mn.create_communicator(args.communicator)
    if comm.rank == 0:
        print(f"communicator: {type(comm).__name__}  size: {comm.size}")

    train = make_synthetic_mnist(args.n_train, seed=0)
    val = make_synthetic_mnist(args.n_val, seed=1)
    scattered = mn.scatter_dataset(train, comm, shuffle=True, seed=0)

    model = MLP(n_units=args.unit)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 784)))
    optimizer = mn.create_multi_node_optimizer(
        optax.adam(args.lr), comm, double_buffering=args.double_buffering)

    mesh = getattr(comm, "mesh", None) or mn.make_mesh()

    def loss_fn(params, batch):
        xs, ys = batch
        logits = model.apply(params, xs)
        return cross_entropy_loss(logits, ys), accuracy(logits, ys)

    step = mn.make_train_step(loss_fn, optimizer, mesh=mesh, has_aux=True)
    params = mn.replicate(params, mesh)
    opt_state = mn.replicate(optimizer.init(params), mesh)

    shard_len = len(scattered.shard(0))
    steps_per_epoch = max(shard_len // args.batchsize, 1)
    t0 = time.time()
    for epoch in range(args.epoch):
        for it in range(steps_per_epoch):
            # global batch = concatenation of each rank's local batch
            xs, ys = [], []
            for r in range(comm.size):
                shard = scattered.shard(r)
                idx = [(it * args.batchsize + j) % len(shard)
                       for j in range(args.batchsize)]
                items = [shard[i] for i in idx]
                xs.append(np.stack([x for x, _ in items]))
                ys.append(np.asarray([y for _, y in items]))
            batch = mn.shard_batch(
                (np.concatenate(xs), np.concatenate(ys)), mesh)
            params, opt_state, loss, acc = step(params, opt_state, batch)
            # keep virtual devices in lockstep on thin hosts (see tests);
            # real-chip throughput runs use bench.py's async pipeline instead
            loss.block_until_ready()
        if comm.rank == 0:
            print(f"epoch {epoch}  loss {float(loss):.4f}  acc {float(acc):.3f}  "
                  f"({time.time() - t0:.1f}s)")

    evaluator = mn.create_multi_node_evaluator(
        mn.accuracy_evaluator(lambda xs: model.apply(params, jnp.asarray(xs))), comm)
    # eval shards stay unequal (no wrap padding) — the evaluator's
    # example-weighted mean handles that; padding would double-count
    metrics = evaluator(mn.scatter_dataset(val, comm, force_equal_length=False))
    if comm.rank == 0:
        print({k: round(v, 4) for k, v in metrics.items()})


if __name__ == "__main__":
    main()
