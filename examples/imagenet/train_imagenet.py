#!/usr/bin/env python
"""Data-parallel ImageNet ResNet training — BASELINE configs #2/#4.

Reference parity: ``examples/imagenet/train_imagenet.py`` [uv]
(SURVEY.md §2.9): the headline DP throughput workload.  The reference ran
one MPI process per GPU with a MultiprocessIterator + pure_nccl bucketed
allreduce; here the whole slice is driven by one jitted SPMD step (bf16
MXU compute, gradient mean over ICI fused into the step) and the input
pipeline is a host-side prefetch thread.

Without /imagenet on disk, synthetic data runs the identical compute graph
(what throughput benchmarks measure anyway).
Run:  python examples/imagenet/train_imagenet.py --arch resnet50 --steps 30
      python examples/imagenet/train_imagenet.py --devices 8 --image-size 32
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    parser = argparse.ArgumentParser(description="ChainerMN-TPU example: ImageNet")
    # Kept as a literal (not ARCHS.keys()): the registry import pulls in
    # jax, which must wait until --devices is applied.  A consistency
    # assert below catches drift.
    parser.add_argument("--arch", default="resnet50",
                        choices=["resnet18", "resnet34", "resnet50",
                                 "resnet101", "resnet152",
                                 "nf_resnet50", "nf_resnet101",
                                 "nf_resnet152",
                                 "alex", "googlenet", "vgg16",
                                 "vit_ti16", "vit_s16", "vit_b16"])
    parser.add_argument("--devices", type=int, default=0,
                        help="fake an N-device CPU mesh (0 = real chips)")
    parser.add_argument("--batchsize", type=int, default=64, help="per-chip batch")
    parser.add_argument("--dataset-size", type=int, default=512,
                        help="synthetic records held in the prefetch buffer")
    parser.add_argument("--data-dir", default=None,
                        help="train from an on-disk record dataset "
                             "(write_file_dataset layout); materialized "
                             "with synthetic records if absent")
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--momentum", type=float, default=0.9)
    parser.add_argument("--weight-decay", type=float, default=1e-4)
    parser.add_argument("--double-buffering", action="store_true")
    parser.add_argument("--optimizer", default="sgd",
                        choices=["sgd", "lars", "lamb"],
                        help="lars/lamb are the large-batch scaling "
                             "optimizers (layerwise adaptive LR) for pushing "
                             "global batch past ~8k images")
    parser.add_argument("--warmup-steps", type=int, default=0,
                        help="linear LR warmup (large-batch recipe)")
    parser.add_argument("--allreduce-grad-dtype", default=None,
                        choices=["bfloat16", "float16", "float32", "int8"],
                        help="wire dtype for the cross-chip gradient mean "
                             "(reference: pure_nccl allreduce_grad_dtype; "
                             "int8 = quantized ring, beyond-reference)")
    parser.add_argument("--conv-impl", default="xla",
                        choices=["xla", "pallas"],
                        help="3x3/1x1 conv backward impl. 'pallas' is the "
                             "measured-SLOWER opt-in kernel path kept for "
                             "the record (docs/PERF.md 'Conv backward: why "
                             "the Pallas kernels lost'); default XLA runs "
                             "at the HBM floor")
    parser.add_argument("--norm", default="bn",
                        choices=["bn", "stalebn", "affine"],
                        help="ResNet norm layer. For the MEASURED BN-free "
                             "fast path use --arch nf_resnet50 instead "
                             "(+20%% step throughput on v5e, docs/PERF.md); "
                             "'stalebn'/'affine' are perf-probe knobs — "
                             "stalebn DIVERGES in training "
                             "(docs/evidence_stalebn_divergence.json)")
    parser.add_argument("--agc", type=float, default=0.0,
                        help="adaptive gradient clipping threshold (0 = "
                             "off). The NF-ResNet large-batch ingredient "
                             "(use ~0.01 from global batch ~4096, Brock "
                             "et al. 2021); composes optax.adaptive_grad_"
                             "clip ahead of the optimizer")
    parser.add_argument("--communicator", default="xla")
    parser.add_argument("--fsdp", action="store_true",
                        help="ZeRO-3: params, grads and optimizer state all "
                             "sharded 1/P (BatchNorm-free archs only — use "
                             "a ViT, e.g. --arch vit_s16)")
    args = parser.parse_args()

    # Flag-combination checks that need nothing from jax: fail fast,
    # before device config / distributed init.
    arch_kw = {"norm": args.norm} if args.norm != "bn" else {}
    if arch_kw and not args.arch.startswith("resnet"):
        parser.error("--norm applies to the resnet archs only")
    if args.conv_impl != "xla":
        if "resnet" not in args.arch:
            parser.error("--conv-impl applies to the (nf_)resnet archs only")
        arch_kw["conv_impl"] = args.conv_impl
    if args.agc < 0:
        # optax.adaptive_grad_clip(-x) silently negates every update
        # (gradient ascent) — reject rather than diverge.
        parser.error("--agc must be >= 0")

    if args.devices:
        import jax
        jax.config.update("jax_platforms", "cpu")
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import chainermn_tpu as mn
    from chainermn_tpu.models.mlp import cross_entropy_loss
    from chainermn_tpu.models.resnet import ARCHS

    # Drift guard over the FULL choices list (not just the picked arch),
    # with a real raise — an assert is stripped under python -O.
    missing = [c for c in parser._option_string_actions["--arch"].choices
               if c not in ARCHS]
    if missing:
        parser.error(f"--arch choices drifted from the model registry: "
                     f"{missing} not in {sorted(ARCHS)}")
    mn.init_distributed()
    comm = mn.create_communicator(args.communicator)
    mesh = getattr(comm, "mesh", None) or mn.make_mesh()
    n_chips = comm.size
    global_batch = args.batchsize * n_chips
    if comm.rank == 0:
        print(f"{args.arch}  chips={n_chips}  global_batch={global_batch}  "
              f"image={args.image_size}")

    model = ARCHS[args.arch](num_classes=args.num_classes,
                             stem_strides=2 if args.image_size >= 64 else 1,
                             **arch_kw)
    rng = jax.random.PRNGKey(0)
    variables = dict(model.init(
        rng, jnp.zeros((1, args.image_size, args.image_size, 3)), train=False))
    # step contract is {'params', 'batch_stats'}; norm='affine' models
    # (and the ViTs) init without the stats collection
    variables.setdefault("batch_stats", {})

    lr = args.lr
    if args.warmup_steps:
        lr = optax.linear_schedule(0.0, args.lr, args.warmup_steps)
    if args.optimizer == "lars":
        inner = optax.lars(lr, weight_decay=args.weight_decay,
                           momentum=args.momentum)
    elif args.optimizer == "lamb":
        inner = optax.lamb(lr, weight_decay=args.weight_decay)
    else:
        inner = optax.chain(
            optax.add_decayed_weights(args.weight_decay),
            optax.sgd(lr, momentum=args.momentum),
        )
    if args.agc:
        # NF-ResNet's large-batch ingredient (Brock et al.: needed from
        # batch ~4096): per-unit ratio clip BEFORE the optimizer, after
        # the gradient mean (create_multi_node_optimizer wraps the whole
        # chain, so the clip sees synchronized gradients).
        inner = optax.chain(optax.adaptive_grad_clip(args.agc), inner)
    if not args.fsdp:
        optimizer = mn.create_multi_node_optimizer(
            inner,
            comm, double_buffering=args.double_buffering,
            allreduce_grad_dtype=args.allreduce_grad_dtype)
    elif args.allreduce_grad_dtype or args.double_buffering:
        # These knobs live in the replicated-DP wrapper; silently dropping
        # them would mislabel a benchmark run.
        raise SystemExit(
            "--fsdp handles gradient reduction itself (GSPMD "
            "reduce-scatter); --allreduce-grad-dtype/--double-buffering "
            "do not apply")

    def loss_and_metrics(logits, batch):
        _, labels = batch
        loss = cross_entropy_loss(logits, labels)
        acc = (logits.argmax(-1) == labels).mean()
        return loss, {"accuracy": acc}

    def normalize_on_chip(batch):
        # uint8 corpora (scripts/ingest_images.py preserves uint8: 4x
        # fewer host->device bytes) cast+normalize ON CHIP, fused into
        # the first conv's prologue; float corpora pass through.  The
        # dtype is static at trace time, so this is a free trace-time
        # branch (docs/PERF.md round-5 data path).
        images, labels = batch
        if images.dtype == jnp.uint8:
            images = images.astype(jnp.float32) / 255.0 - 0.5
        return images, labels

    if args.fsdp:
        # ZeRO-3 path: GSPMD inserts per-use weight all-gathers and
        # gradient reduce-scatters from the 1/P shardings alone.  BN's
        # mutable running stats don't fit the pure-loss contract — the ViT
        # archs (stat-free) are the fit.
        from chainermn_tpu.parallel import (init_fsdp_params,
                                            init_fsdp_state,
                                            make_fsdp_train_step)

        if "batch_stats" in variables:
            raise SystemExit(
                f"--fsdp needs a BatchNorm-free arch (got {args.arch}); "
                f"try --arch vit_s16")

        def fsdp_loss(p, batch):
            batch = normalize_on_chip(batch)
            logits = model.apply({"params": p}, batch[0], train=True)
            loss, metrics = loss_and_metrics(logits, batch)
            return loss, metrics

        fsdp_params = init_fsdp_params(dict(variables)["params"], mesh)
        opt_state = init_fsdp_state(inner, fsdp_params, mesh)
        raw = make_fsdp_train_step(fsdp_loss, inner, mesh, has_aux=True)

        def step(v, st, batch):
            p, st, loss, metrics = raw(v["params"], st, batch)
            return {"params": p}, st, loss, metrics

        variables = {"params": fsdp_params}
    else:
        step = mn.make_flax_train_step(
            model, loss_and_metrics, optimizer, mesh=mesh,
            allreduce_grad_dtype=args.allreduce_grad_dtype,
            preprocess=normalize_on_chip)
        variables = mn.replicate(dict(variables), mesh)
        opt_state = mn.replicate(optimizer.init(variables["params"]), mesh)

    # Input pipeline: the native C++ prefetcher assembles batches in worker
    # threads (GIL-free) while the previous step computes — the reference's
    # MultiprocessIterator role (SURVEY.md §2.9).  With --data-dir the
    # records come OFF DISK (pread-ing C++ workers; the reference example's
    # defining job); otherwise synthetic in-memory records run the identical
    # path.  An empty/missing --data-dir is materialized first, standing in
    # for an ImageNet conversion step when /imagenet is absent.
    data_rng = np.random.RandomState(0)
    n_records = max(args.dataset_size, global_batch)
    if args.data_dir:
        meta = os.path.join(args.data_dir, "meta.json")
        # Rank 0 alone decides whether to materialize (a per-rank exists()
        # check would race with the write and leave ranks disagreeing on
        # whether to enter the barrier); the bcast is UNCONDITIONAL so it
        # is the same collective on every process.
        if comm.owns_rank(0) and not os.path.exists(meta):
            records = data_rng.randn(
                n_records, args.image_size, args.image_size, 3
            ).astype(np.float32)
            labels = data_rng.randint(
                0, args.num_classes, n_records).astype(np.int32)
            mn.write_file_dataset(args.data_dir, [records, labels])
            print(f"materialized {n_records} records to {args.data_dir}")
        comm.bcast_obj(None)  # barrier: dataset visible before readers
        dataset = mn.FileDataset(args.data_dir)
    else:
        records = data_rng.randn(n_records, args.image_size, args.image_size,
                                 3).astype(np.float32)
        labels = data_rng.randint(0, args.num_classes, n_records
                                  ).astype(np.int32)
        dataset = (records, labels)
    # copy=True: device_put is async on real chips, and without the copy the
    # prefetch ring could recycle the slot under a still-running H2D DMA.
    it = mn.PrefetchIterator(dataset, batch_size=global_batch,
                             shuffle=True, seed=1, copy=True)
    if comm.rank == 0 and not mn.runtime.native_available():
        print("note: native prefetcher unavailable, python fallback in use")

    # warmup/compile
    batch = mn.shard_batch(it.next(), mesh)
    variables, opt_state, loss, metrics = step(variables, opt_state, batch)
    loss.block_until_ready()
    t0 = time.time()
    for i in range(args.steps):
        batch = mn.shard_batch(it.next(), mesh)
        variables, opt_state, loss, metrics = step(variables, opt_state, batch)
        if args.devices:  # lockstep on thin hosts; async on real chips
            loss.block_until_ready()
    loss.block_until_ready()
    dt = time.time() - t0
    if comm.rank == 0:
        ips = args.steps * global_batch / dt
        print(f"loss {float(loss):.4f}  acc {float(metrics['accuracy']):.4f}")
        print(f"throughput: {ips:.1f} images/sec total, "
              f"{ips / n_chips:.1f} images/sec/chip")


if __name__ == "__main__":
    main()
