#!/usr/bin/env python
"""Expert-parallel (MoE) training end-to-end: Switch-style top-1 routing.

Beyond-reference workload (SURVEY.md §2.8: EP "absent" — the reference only
shipped the ``alltoall`` substrate): a classifier whose middle layer is a
top-1 mixture-of-experts MLP, experts sharded one-per-device, tokens riding
TWO ``all_to_all`` collectives per step, trained in ONE jitted SPMD step.

The same mesh axis carries data parallelism (tokens sharded) AND expert
parallelism (expert weights sharded) — the composition falls out of
``make_hybrid_shard_map_step``: expert-sharded params are axis-varying so
autodiff leaves their gradients local (each device owns its experts), while
replicated params get the AD-inserted cross-rank psum.

The load-balance auxiliary loss (Switch eq. 4) is what keeps routing from
collapsing onto one expert — run with ``--aux-weight 0`` to watch it
collapse (max expert fraction → 1), the failure mode the loss exists for.

Run:  python examples/moe/train_moe.py --devices 8
      python examples/moe/train_moe.py --devices 8 --aux-weight 0
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def make_dataset(rng, n, d_in, num_classes):
    """Clustered synthetic data: class = nearest of C random centroids, so
    a router has real structure to specialize experts on."""
    centroids = rng.randn(num_classes, d_in).astype("float32") * 2.0
    labels = rng.randint(0, num_classes, n)
    xs = centroids[labels] + rng.randn(n, d_in).astype("float32")
    return xs.astype("float32"), labels.astype("int32")


def main():
    parser = argparse.ArgumentParser(
        description="ChainerMN-TPU example: expert-parallel MoE training")
    parser.add_argument("--devices", type=int, default=0,
                        help="fake an N-device CPU mesh (0 = real chips)")
    parser.add_argument("--d-in", type=int, default=16)
    parser.add_argument("--d-model", type=int, default=32)
    parser.add_argument("--d-hidden", type=int, default=64)
    parser.add_argument("--num-classes", type=int, default=8)
    parser.add_argument("--experts-per-device", type=int, default=1)
    parser.add_argument("--batchsize", type=int, default=256,
                        help="global tokens per step")
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--lr", type=float, default=3e-2)
    parser.add_argument("--aux-weight", type=float, default=0.01)
    parser.add_argument("--capacity-factor", type=float, default=1.5)
    parser.add_argument("--router-topk", type=int, default=1,
                        choices=[1, 2],
                        help="1 = Switch top-1, 2 = GShard top-2 routing")
    args = parser.parse_args()

    if args.devices:
        import jax
        jax.config.update("jax_platforms", "cpu")
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import chainermn_tpu as mn
    from chainermn_tpu.parallel import (
        init_moe_mlp_params, make_hybrid_shard_map_step, moe_mlp,
        moe_mlp_specs, shard_pytree, state_specs_like)

    comm = mn.create_communicator("xla")
    mesh, ax = comm.mesh, comm.axis_name
    n_dev = comm.size
    e = args.experts_per_device * n_dev

    rng = jax.random.PRNGKey(0)
    k_in, k_moe, k_head = jax.random.split(rng, 3)
    params = {
        "w_in": jax.random.normal(k_in, (args.d_in, args.d_model)) * 0.3,
        "moe": init_moe_mlp_params(k_moe, args.d_model, args.d_hidden, e),
        "w_head": jax.random.normal(k_head, (args.d_model, args.num_classes))
                  * 0.3,
    }
    specs = {"w_in": P(), "moe": moe_mlp_specs(ax), "w_head": P()}

    def loss_fn(p, batch):
        xs, ys = batch
        h = jnp.tanh(xs @ p["w_in"])
        y, aux = moe_mlp(h, p["moe"], axis_name=ax, num_experts=e,
                         capacity_factor=args.capacity_factor,
                         router_topk=args.router_topk)
        logits = y @ p["w_head"]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        ce = -jnp.mean(jnp.take_along_axis(logp, ys[:, None], 1))
        acc = (logits.argmax(-1) == ys).mean()
        # routing fractions for observability (max fraction → collapse)
        probs = jax.nn.softmax(
            (h @ p["moe"]["router"]).astype(jnp.float32), -1)
        frac = jax.lax.pmean(
            jnp.mean(jax.nn.one_hot(probs.argmax(-1), e), 0), ax)
        return ce + args.aux_weight * aux, {
            "ce": ce, "aux": aux, "accuracy": acc, "max_frac": frac.max()}

    optimizer = optax.adam(args.lr)
    step = make_hybrid_shard_map_step(
        loss_fn, optimizer, mesh, params, specs, data_axis=ax,
        batch_spec=P(ax), has_aux=True, donate=False)

    p = shard_pytree(params, mesh, specs)
    st = shard_pytree(optimizer.init(params),
                      mesh, state_specs_like(optimizer, params, specs))

    data_rng = np.random.RandomState(0)
    xs, ys = make_dataset(data_rng, args.batchsize * 4, args.d_in,
                          args.num_classes)
    t0 = time.time()
    for i in range(args.steps):
        lo = (i * args.batchsize) % (len(xs) - args.batchsize + 1)
        batch = tuple(
            jax.device_put(a[lo:lo + args.batchsize],
                           NamedSharding(mesh, P(ax)))
            for a in (xs, ys))
        p, st, loss, aux = step(p, st, batch)
        if comm.rank == 0 and (i % 10 == 0 or i == args.steps - 1):
            print(f"step {i:3d}  loss {float(loss):.4f}  "
                  f"ce {float(aux['ce']):.4f}  acc {float(aux['accuracy']):.3f}  "
                  f"aux {float(aux['aux']):.3f}  "
                  f"max_expert_frac {float(aux['max_frac']):.3f}")
    if comm.rank == 0:
        print(f"{e} experts on {n_dev} devices, "
              f"{args.steps} steps in {time.time() - t0:.1f}s")


if __name__ == "__main__":
    main()
