"""Abstract communicator interface.

Reference parity: ``chainermn/communicators/communicator_base.py ::
CommunicatorBase`` [uv] (SURVEY.md §2.1) — properties ``rank, size,
intra_rank, intra_size, inter_rank, inter_size``; collectives ``send, recv,
bcast, gather, allgather, alltoall, scatter, allreduce``; object variants
``send_obj, recv_obj, bcast_obj, gather_obj, allreduce_obj``; model helpers
``broadcast_data`` and ``multi_node_mean_grad`` (older name
``allreduce_grad``); ``split`` and ``finalize``.

Eager data model — **rank-major arrays** instead of per-process arrays
------------------------------------------------------------------------
ChainerMN is multi-process SPMD: every rank calls ``comm.allreduce(x)`` with
its own ``x`` and receives its own result.  JAX on TPU is single-controller
per host with *global* arrays, so the eager parity face here operates on
**rank-major stacked arrays**: an input of logical per-rank shape ``s`` is
passed as one global array of shape ``(size, *s)`` whose slab ``[r]`` is rank
``r``'s value, sharded over the communicator mesh so slab ``r`` physically
lives on chip ``r``.  Every collective returns the rank-major stack of what
each rank would have received:

    ``allreduce``: out[r] = reduce(x[0..size-1])          (same for all r)
    ``bcast``:     out[r] = x[root]
    ``gather``:    out    = x  (the full stack; meaningful at root)
    ``allgather``: out[r] = x  (i.e. out has shape (size, size, *s))
    ``alltoall``:  out[r][s] = x[s][r]  (transpose of the two rank axes)
    ``scatter``:   out[r] = x_root[r]   (root's (size, *s) array split up)
    ``send/recv``: ppermute-style shifts of slabs between ranks

Why this shape: it keeps the whole test matrix runnable in ONE process over N
devices (real chips or ``--xla_force_host_platform_device_count``), exactly
mirroring how the reference fakes multi-node with single-node MPI
(SURVEY.md §4), while the *in-jit* face (``chainermn_tpu.ops``) is what the
hot path uses inside a single compiled SPMD program.

This eager face is for tests, setup, and debugging; training steps should go
through ``create_multi_node_optimizer`` which fuses the mean-gradient
collective into the jitted step (SURVEY.md §3.2 "TPU mapping").
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np


# ---------------------------------------------------------------------------
# Hardened DCN lanes (ISSUE 8): retry/timeout/backoff with GANG-CONSISTENT
# failure classification for the object-transport side channels
# (allgather_obj / bcast_obj / the jax.distributed KV store).  A transient
# lane fault (coordinator blip, connection reset) degrades gracefully via
# exponential backoff; a permanent one dies loudly with the lane NAMED in
# the flight ring and the raised error — never a silent hang.
# ---------------------------------------------------------------------------

class DcnLaneError(RuntimeError):
    """Permanent (or retries-exhausted) failure of a named DCN lane.

    Deliberately NOT caught anywhere in the package: it propagates to the
    global except hook, which dumps a flight bundle (the ring's
    ``dcn_lane_fault`` event names the lane) and aborts the gang — the
    bounded loud death the chaos tests assert.
    """

    def __init__(self, lane: str, attempts: int, cause: BaseException):
        self.lane = lane
        self.attempts = attempts
        self.cause = cause
        super().__init__(
            f"DCN lane '{lane}' failed after {attempts} attempt(s): "
            f"{type(cause).__name__}: {cause}")


class LaneConfig:
    """Retry policy for one process's DCN lanes.

    Every field reads an env override so a launcher can tune the whole
    gang uniformly (classification AND policy must be gang-consistent —
    per-rank divergence here could leave half the gang retrying while
    the other half dies):

    * ``CHAINERMN_TPU_LANE_RETRIES``       (default 4 transient retries)
    * ``CHAINERMN_TPU_LANE_BACKOFF_S``     (base, default 0.05; doubles
      per retry up to ``CHAINERMN_TPU_LANE_BACKOFF_MAX_S``, default 2.0)
    * ``CHAINERMN_TPU_LANE_TIMEOUT_MS``    (blocking KV get, default
      300000)
    """

    def __init__(self,
                 max_retries: Optional[int] = None,
                 backoff_base_s: Optional[float] = None,
                 backoff_max_s: Optional[float] = None,
                 timeout_ms: Optional[int] = None):
        env = os.environ.get
        self.max_retries = int(
            env("CHAINERMN_TPU_LANE_RETRIES", 4)
            if max_retries is None else max_retries)
        self.backoff_base_s = float(
            env("CHAINERMN_TPU_LANE_BACKOFF_S", 0.05)
            if backoff_base_s is None else backoff_base_s)
        self.backoff_max_s = float(
            env("CHAINERMN_TPU_LANE_BACKOFF_MAX_S", 2.0)
            if backoff_max_s is None else backoff_max_s)
        self.timeout_ms = int(
            env("CHAINERMN_TPU_LANE_TIMEOUT_MS", 300_000)
            if timeout_ms is None else timeout_ms)


#: Deterministic message fingerprints of TRANSIENT faults.  Classification
#: keys on error TEXT, not type, so every rank seeing the same fault makes
#: the same retry-vs-die call (the ``_mp_compute_unavailable`` discipline);
#: anything not matching is PERMANENT — retrying an unknown error could
#: desync lane sequence numbers across the gang.
TRANSIENT_LANE_PATTERNS = (
    "deadline exceeded",
    "deadline_exceeded",
    "unavailable",
    "connection reset",
    "connection refused",
    "timed out",
    "injected transient",        # the chaos harness's marker
)


def classify_lane_error(e: BaseException) -> str:
    """``"transient"`` or ``"permanent"`` — total and deterministic."""
    msg = str(e).lower()
    if any(p in msg for p in TRANSIENT_LANE_PATTERNS):
        return "transient"
    return "permanent"


#: Test/chaos fault injection: ``fn(lane, attempt)`` raising to simulate a
#: fault, or None.  ``CHAINERMN_TPU_LANE_FAULT=<lane_pattern>:<transient|
#: permanent>:<count>[:after=N]`` arms an env-driven injector for
#: subprocess gangs.  ``lane_pattern`` is a substring match, or an
#: ``fnmatch`` glob when it contains ``*``/``?``/``[`` (matched against
#: the FULL lane name); ``after=N`` lets the first N matching calls pass
#: clean before the fault budget starts burning — per-op targeting, so a
#: chaos drill can kill a SPECIFIC collective step deterministically
#: ("gang/*/x/step7/*:permanent:1:after=0") instead of whichever lane op
#: happens to run first (ISSUE 13).
_FAULT_INJECTOR: Optional[Callable[[str, int], None]] = None
_ENV_FAULT: Optional[Dict[str, Any]] = None


def set_lane_fault_injector(fn: Optional[Callable[[str, int], None]]) -> None:
    global _FAULT_INJECTOR
    _FAULT_INJECTOR = fn


def _lane_matches(pattern: str, lane: str) -> bool:
    """Substring match, upgraded to an fnmatch glob over the FULL lane
    name when the pattern carries glob metacharacters."""
    if any(c in pattern for c in "*?["):
        import fnmatch
        return fnmatch.fnmatchcase(lane, pattern)
    return pattern in lane


def _env_fault_state() -> Optional[Dict[str, Any]]:
    global _ENV_FAULT
    spec = os.environ.get("CHAINERMN_TPU_LANE_FAULT")
    if not spec:
        return None
    if _ENV_FAULT is None or _ENV_FAULT.get("spec") != spec:
        body, skip = spec, 0
        if ":after=" in spec:
            body, after = spec.rsplit(":after=", 1)
            skip = int(after)
        lane_pattern, kind, count = body.rsplit(":", 2)
        if kind not in ("transient", "permanent"):
            raise ValueError(
                f"CHAINERMN_TPU_LANE_FAULT kind must be transient|"
                f"permanent, got {kind!r} in {spec!r}")
        _ENV_FAULT = {"spec": spec, "lane": lane_pattern, "kind": kind,
                      "remaining": int(count), "skip": skip}
    return _ENV_FAULT


def _maybe_inject_fault(lane: str, attempt: int) -> None:
    if _FAULT_INJECTOR is not None:
        _FAULT_INJECTOR(lane, attempt)
    st = _env_fault_state()
    if st and st["remaining"] > 0 and _lane_matches(st["lane"], lane):
        if st.get("skip", 0) > 0:
            st["skip"] -= 1   # fire-after-N: this matching call passes
            return
        st["remaining"] -= 1
        if st["kind"] == "transient":
            raise RuntimeError(
                f"injected transient lane fault on '{lane}' (chaos)")
        raise RuntimeError(
            f"injected permanent lane fault on '{lane}' (chaos)")


def lane_call(lane: str, fn: Callable[[], Any],
              config: Optional[LaneConfig] = None) -> Any:
    """Run one DCN-lane operation under the hardened retry discipline.

    Transient faults (see :func:`classify_lane_error`) retry with
    exponential backoff up to ``config.max_retries`` times, each retry
    recorded in the flight ring (``dcn_lane_retry``); a permanent fault
    or exhausted retries raises :class:`DcnLaneError` after recording
    ``dcn_lane_fault`` — so the crash bundle always names the lane.

    Retries are additionally bounded by TOTAL elapsed wall time
    (``config.timeout_ms``): a blocking get that already waited the
    full KV window gave the peer its whole budget — re-waiting it
    ``max_retries`` more times would turn one 5-minute dead-peer
    detection into 25 minutes of wedged accelerator, so a
    timeout-classified fault past the budget dies loudly instead.
    Fast-failing transients (connection refused/reset) are unaffected.
    """
    cfg = config or LaneConfig()
    from ..observability import flight as _flight

    attempt = 0
    t_start = time.monotonic()
    while True:
        try:
            _maybe_inject_fault(lane, attempt)
            return fn()
        except DcnLaneError:
            raise
        except Exception as e:  # noqa: BLE001 — classified below
            kind = classify_lane_error(e)
            attempt += 1
            budget_spent = (time.monotonic() - t_start
                            >= cfg.timeout_ms / 1000.0)
            if kind == "permanent" or attempt > cfg.max_retries \
                    or budget_spent:
                _flight.note("dcn_lane_fault", lane=lane, attempts=attempt,
                             classification=kind, error=repr(e))
                import sys as _sys
                print(f"[chainermn_tpu lanes] DCN lane '{lane}' "
                      f"{'permanent fault' if kind == 'permanent' else 'transient fault persisted'}"
                      f" after {attempt} attempt(s): {e!r}",
                      file=_sys.stderr, flush=True)
                raise DcnLaneError(lane, attempt, e) from e
            delay = min(cfg.backoff_base_s * (2 ** (attempt - 1)),
                        cfg.backoff_max_s)
            _flight.note("dcn_lane_retry", lane=lane, attempt=attempt,
                         backoff_s=round(delay, 4), error=repr(e))
            time.sleep(delay)


#: Concrete collectives auto-wrapped with observability accounting when a
#: backend defines them (op name, payload bytes, host latency — see
#: observability/comm.py).  Object-lane transport is deliberately absent:
#: it is a setup path, and pickled payload sizes say nothing about wire
#: collectives.
_ACCOUNTED_OPS = (
    "allreduce", "bcast", "gather", "allgather", "alltoall", "scatter",
    "send", "recv", "broadcast_data", "multi_node_mean_grad",
)


class CommunicatorBase:
    """API contract shared by every communicator backend."""

    def __init_subclass__(cls, **kwargs):
        # Every backend (naive, xla, future ones) gets comm accounting on
        # its eager collectives without per-backend boilerplate; the
        # wrapper is one attribute read when tracing is disabled.
        super().__init_subclass__(**kwargs)
        from ..observability.comm import accounted_method
        for name in _ACCOUNTED_OPS:
            fn = cls.__dict__.get(name)
            if callable(fn) and not getattr(fn, "_obs_wrapped", False):
                setattr(cls, name, accounted_method(name)(fn))

    # ---- topology properties (reference: communicator_base.py [uv]) ----
    @property
    def rank(self) -> int:
        """This *process*'s first rank (host-level under multi-controller)."""
        raise NotImplementedError

    @property
    def size(self) -> int:
        raise NotImplementedError

    @property
    def intra_rank(self) -> int:
        raise NotImplementedError

    @property
    def intra_size(self) -> int:
        raise NotImplementedError

    @property
    def inter_rank(self) -> int:
        raise NotImplementedError

    @property
    def inter_size(self) -> int:
        raise NotImplementedError

    # ---- array collectives over rank-major stacks ----
    def allreduce(self, x, op: str = "sum"):
        raise NotImplementedError

    def bcast(self, x, root: int = 0):
        raise NotImplementedError

    def gather(self, x, root: int = 0):
        raise NotImplementedError

    def allgather(self, x):
        raise NotImplementedError

    def alltoall(self, x):
        raise NotImplementedError

    def scatter(self, x, root: int = 0):
        raise NotImplementedError

    def send(self, x, dest: int, source: int):
        """Move rank ``source``'s slab to rank ``dest`` (one-shot p2p)."""
        raise NotImplementedError

    def recv(self, x, source: int, dest: int):
        raise NotImplementedError

    # ---- object (pickle) transport — setup path only, never hot ----
    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        raise NotImplementedError

    def gather_obj(self, obj: Any, root: int = 0) -> Optional[List[Any]]:
        raise NotImplementedError

    def allgather_obj(self, obj: Any) -> List[Any]:
        raise NotImplementedError

    def allgather_obj_eventual(self, tag: str, obj: Any,
                               timeout_s: float = 10.0,
                               discard_tag: Optional[str] = None
                               ) -> Dict[int, Any]:
        """Bounded best-effort per-PROCESS gather — deliberately NOT a
        gang collective.  Each calling process publishes ``obj`` under a
        caller-unique ``tag`` (include every identity the exchange is
        scoped by — name, iteration, world size) and collects whatever
        its peers published within ``timeout_s`` TOTAL (shared across
        all peers, so a dead gang costs the budget once, not n-1
        times); ``timeout_s <= 0`` publishes without reading any peer.
        A peer that never calls (crashed, preempted, or simply skipping
        this generation) is ABSENT from the returned
        ``{process_index: obj}`` dict
        instead of wedging the gang.  Safe to call from any subset of
        processes in any order — the checkpoint manifest's checksum
        exchange rides this so ``save()`` stays a LOCAL operation
        (a dead peer degrades verification, never liveness).
        ``discard_tag`` garbage-collects this process's entry from a
        previous exchange.  Single-process backends: trivially complete.
        """
        del tag, timeout_s, discard_tag
        import jax as _jax
        return {_jax.process_index(): obj}

    def kv_lane_transport(self):
        """Object-lane transport (``put(tag, bytes)`` / ``get(tag,
        timeout_s)`` / ``delete(tag)``) for bulk payloads addressed by
        TAG rather than gathered by gang — the serving KV-transfer
        plane's wire (ISSUE 9: a prefill worker publishes a finished
        slab, exactly one decode worker consumes it; a gang collective
        is the wrong shape).  Callers wrap every operation in
        :func:`lane_call`, so faults ride the hardened retry/
        classification discipline and the flight ring NAMES the lane.
        Single-controller backends loop back through one in-process
        store; multi-controller backends override with the
        jax.distributed KV store.  NOTE the gang-membership caveat
        (ISSUE 10): the jax.distributed store requires every process
        inside ONE fixed-size runtime — an ELASTIC serving fleet whose
        members die, drain, and join independently uses
        ``chainermn_tpu.serving.lanes.FileLaneStore`` instead (same
        put/get/delete face over a shared directory), keeping this
        transport for gangs that already share a coordinator."""
        store = getattr(self, "_kv_lane_store", None)
        if store is None:
            from ..serving.transfer import InProcessLaneStore
            store = self._kv_lane_store = InProcessLaneStore()
        return store

    def gang_lease_store(self):
        """The rank health plane's store (ISSUE 13): this communicator's
        KV side channel adapted to the lease-store face —
        ``SelfHealingGang`` publishes heartbeat leases, consensus
        proposals, and shard leases through it.  Absent tags surface as
        ``TimeoutError`` (the ``FileLaneStore`` contract) so non-blocking
        lease polls read absence as absence, not as a retryable fault."""
        from ..health import KvLeaseStore
        return KvLeaseStore(self.kv_lane_transport())

    def allreduce_obj(self, obj: Any, op: Callable = None) -> Any:
        raise NotImplementedError

    def send_obj(self, obj: Any, dest: int) -> None:
        raise NotImplementedError

    def recv_obj(self, source: int) -> Any:
        raise NotImplementedError

    # ---- placement ----
    def device_of(self, rank: int):
        """The chip that owns ``rank``, or None when the communicator has no
        physical devices (the naive loopback).  Consumers
        (``MultiNodeChainList``) use it to pin per-rank state and emit real
        cross-chip copies — the reference's "rank → intra_rank-th GPU"
        binding (SURVEY.md §1)."""
        return None

    # ---- model helpers ----
    def broadcast_data(self, params):
        """Replicate a parameter pytree to every chip (reference:
        ``CommunicatorBase.broadcast_data(model)`` [uv] — MPI bcast of every
        param from rank 0).  TPU-native: device_put with a fully-replicated
        sharding over the communicator mesh; XLA broadcasts over ICI."""
        raise NotImplementedError

    def multi_node_mean_grad(self, grads):
        """Mean a rank-major stacked gradient pytree across ranks (reference:
        ``multi_node_mean_grad`` / older ``allreduce_grad`` [uv])."""
        raise NotImplementedError

    # Backwards-compatible alias, as in the reference.
    def allreduce_grad(self, grads):
        return self.multi_node_mean_grad(grads)

    # ---- structure ----
    def split(self, color, key: int = 0):
        """Partition ranks into sub-communicators (reference:
        ``mpi_comm.Split(color, key)`` [uv]).

        In MPI every rank passes *its own* scalar color; a single-controller
        process owns all ranks at once, so the color argument is either

        * a sequence of per-rank colors → returns ``{color: communicator}``
          over the matching device subsets (all groups at once), or
        * a scalar → every rank has the same color, which in MPI semantics
          yields one group containing the whole world → returns a single
          communicator over all devices.

        ``key`` (MPI's rank-reordering knob) is accepted for parity but
        ignored: device order inside a group follows global rank order.
        """
        raise NotImplementedError

    def finalize(self) -> None:
        pass

    def owns_rank(self, r: int) -> bool:
        """Whether THIS controller process owns logical rank ``r`` (always
        true single-controller; device-ownership check under
        multi-controller).  Used by host-side components (iterators,
        checkpointing) to run rank-specific work on exactly one process."""
        return True

    # ---- conveniences shared by all backends ----
    def stack(self, per_rank: Sequence[Any]):
        """Build a rank-major stacked array from a list of per-rank arrays."""
        if len(per_rank) != self.size:
            raise ValueError(f"need {self.size} per-rank arrays, got {len(per_rank)}")
        return self._place(np.stack([np.asarray(a) for a in per_rank]))

    def unstack(self, x) -> List[np.ndarray]:
        """Split a rank-major stacked array back into per-rank numpy arrays."""
        x = np.asarray(jax.device_get(x))
        return [x[r] for r in range(x.shape[0])]

    def _place(self, x):
        """Backend hook: put a host array into the backend's native layout."""
        return x

    def _check_leading(self, x):
        """Validate the rank-major contract: leading dim == size."""
        if x.shape[0] != self.size:
            raise ValueError(
                f"rank-major stack must have leading dim {self.size}, got {x.shape}")
        return x

    def _check_alltoall(self, x):
        self._check_leading(x)
        if x.ndim < 2 or x.shape[1] != self.size:
            raise ValueError(
                f"alltoall needs shape (size, size, ...), got {x.shape}")
        return x
