"""Abstract communicator interface.

Reference parity: ``chainermn/communicators/communicator_base.py ::
CommunicatorBase`` [uv] (SURVEY.md §2.1) — properties ``rank, size,
intra_rank, intra_size, inter_rank, inter_size``; collectives ``send, recv,
bcast, gather, allgather, alltoall, scatter, allreduce``; object variants
``send_obj, recv_obj, bcast_obj, gather_obj, allreduce_obj``; model helpers
``broadcast_data`` and ``multi_node_mean_grad`` (older name
``allreduce_grad``); ``split`` and ``finalize``.

Eager data model — **rank-major arrays** instead of per-process arrays
------------------------------------------------------------------------
ChainerMN is multi-process SPMD: every rank calls ``comm.allreduce(x)`` with
its own ``x`` and receives its own result.  JAX on TPU is single-controller
per host with *global* arrays, so the eager parity face here operates on
**rank-major stacked arrays**: an input of logical per-rank shape ``s`` is
passed as one global array of shape ``(size, *s)`` whose slab ``[r]`` is rank
``r``'s value, sharded over the communicator mesh so slab ``r`` physically
lives on chip ``r``.  Every collective returns the rank-major stack of what
each rank would have received:

    ``allreduce``: out[r] = reduce(x[0..size-1])          (same for all r)
    ``bcast``:     out[r] = x[root]
    ``gather``:    out    = x  (the full stack; meaningful at root)
    ``allgather``: out[r] = x  (i.e. out has shape (size, size, *s))
    ``alltoall``:  out[r][s] = x[s][r]  (transpose of the two rank axes)
    ``scatter``:   out[r] = x_root[r]   (root's (size, *s) array split up)
    ``send/recv``: ppermute-style shifts of slabs between ranks

Why this shape: it keeps the whole test matrix runnable in ONE process over N
devices (real chips or ``--xla_force_host_platform_device_count``), exactly
mirroring how the reference fakes multi-node with single-node MPI
(SURVEY.md §4), while the *in-jit* face (``chainermn_tpu.ops``) is what the
hot path uses inside a single compiled SPMD program.

This eager face is for tests, setup, and debugging; training steps should go
through ``create_multi_node_optimizer`` which fuses the mean-gradient
collective into the jitted step (SURVEY.md §3.2 "TPU mapping").
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax
import numpy as np


#: Concrete collectives auto-wrapped with observability accounting when a
#: backend defines them (op name, payload bytes, host latency — see
#: observability/comm.py).  Object-lane transport is deliberately absent:
#: it is a setup path, and pickled payload sizes say nothing about wire
#: collectives.
_ACCOUNTED_OPS = (
    "allreduce", "bcast", "gather", "allgather", "alltoall", "scatter",
    "send", "recv", "broadcast_data", "multi_node_mean_grad",
)


class CommunicatorBase:
    """API contract shared by every communicator backend."""

    def __init_subclass__(cls, **kwargs):
        # Every backend (naive, xla, future ones) gets comm accounting on
        # its eager collectives without per-backend boilerplate; the
        # wrapper is one attribute read when tracing is disabled.
        super().__init_subclass__(**kwargs)
        from ..observability.comm import accounted_method
        for name in _ACCOUNTED_OPS:
            fn = cls.__dict__.get(name)
            if callable(fn) and not getattr(fn, "_obs_wrapped", False):
                setattr(cls, name, accounted_method(name)(fn))

    # ---- topology properties (reference: communicator_base.py [uv]) ----
    @property
    def rank(self) -> int:
        """This *process*'s first rank (host-level under multi-controller)."""
        raise NotImplementedError

    @property
    def size(self) -> int:
        raise NotImplementedError

    @property
    def intra_rank(self) -> int:
        raise NotImplementedError

    @property
    def intra_size(self) -> int:
        raise NotImplementedError

    @property
    def inter_rank(self) -> int:
        raise NotImplementedError

    @property
    def inter_size(self) -> int:
        raise NotImplementedError

    # ---- array collectives over rank-major stacks ----
    def allreduce(self, x, op: str = "sum"):
        raise NotImplementedError

    def bcast(self, x, root: int = 0):
        raise NotImplementedError

    def gather(self, x, root: int = 0):
        raise NotImplementedError

    def allgather(self, x):
        raise NotImplementedError

    def alltoall(self, x):
        raise NotImplementedError

    def scatter(self, x, root: int = 0):
        raise NotImplementedError

    def send(self, x, dest: int, source: int):
        """Move rank ``source``'s slab to rank ``dest`` (one-shot p2p)."""
        raise NotImplementedError

    def recv(self, x, source: int, dest: int):
        raise NotImplementedError

    # ---- object (pickle) transport — setup path only, never hot ----
    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        raise NotImplementedError

    def gather_obj(self, obj: Any, root: int = 0) -> Optional[List[Any]]:
        raise NotImplementedError

    def allgather_obj(self, obj: Any) -> List[Any]:
        raise NotImplementedError

    def allreduce_obj(self, obj: Any, op: Callable = None) -> Any:
        raise NotImplementedError

    def send_obj(self, obj: Any, dest: int) -> None:
        raise NotImplementedError

    def recv_obj(self, source: int) -> Any:
        raise NotImplementedError

    # ---- placement ----
    def device_of(self, rank: int):
        """The chip that owns ``rank``, or None when the communicator has no
        physical devices (the naive loopback).  Consumers
        (``MultiNodeChainList``) use it to pin per-rank state and emit real
        cross-chip copies — the reference's "rank → intra_rank-th GPU"
        binding (SURVEY.md §1)."""
        return None

    # ---- model helpers ----
    def broadcast_data(self, params):
        """Replicate a parameter pytree to every chip (reference:
        ``CommunicatorBase.broadcast_data(model)`` [uv] — MPI bcast of every
        param from rank 0).  TPU-native: device_put with a fully-replicated
        sharding over the communicator mesh; XLA broadcasts over ICI."""
        raise NotImplementedError

    def multi_node_mean_grad(self, grads):
        """Mean a rank-major stacked gradient pytree across ranks (reference:
        ``multi_node_mean_grad`` / older ``allreduce_grad`` [uv])."""
        raise NotImplementedError

    # Backwards-compatible alias, as in the reference.
    def allreduce_grad(self, grads):
        return self.multi_node_mean_grad(grads)

    # ---- structure ----
    def split(self, color, key: int = 0):
        """Partition ranks into sub-communicators (reference:
        ``mpi_comm.Split(color, key)`` [uv]).

        In MPI every rank passes *its own* scalar color; a single-controller
        process owns all ranks at once, so the color argument is either

        * a sequence of per-rank colors → returns ``{color: communicator}``
          over the matching device subsets (all groups at once), or
        * a scalar → every rank has the same color, which in MPI semantics
          yields one group containing the whole world → returns a single
          communicator over all devices.

        ``key`` (MPI's rank-reordering knob) is accepted for parity but
        ignored: device order inside a group follows global rank order.
        """
        raise NotImplementedError

    def finalize(self) -> None:
        pass

    def owns_rank(self, r: int) -> bool:
        """Whether THIS controller process owns logical rank ``r`` (always
        true single-controller; device-ownership check under
        multi-controller).  Used by host-side components (iterators,
        checkpointing) to run rank-specific work on exactly one process."""
        return True

    # ---- conveniences shared by all backends ----
    def stack(self, per_rank: Sequence[Any]):
        """Build a rank-major stacked array from a list of per-rank arrays."""
        if len(per_rank) != self.size:
            raise ValueError(f"need {self.size} per-rank arrays, got {len(per_rank)}")
        return self._place(np.stack([np.asarray(a) for a in per_rank]))

    def unstack(self, x) -> List[np.ndarray]:
        """Split a rank-major stacked array back into per-rank numpy arrays."""
        x = np.asarray(jax.device_get(x))
        return [x[r] for r in range(x.shape[0])]

    def _place(self, x):
        """Backend hook: put a host array into the backend's native layout."""
        return x

    def _check_leading(self, x):
        """Validate the rank-major contract: leading dim == size."""
        if x.shape[0] != self.size:
            raise ValueError(
                f"rank-major stack must have leading dim {self.size}, got {x.shape}")
        return x

    def _check_alltoall(self, x):
        self._check_leading(x)
        if x.ndim < 2 or x.shape[1] != self.size:
            raise ValueError(
                f"alltoall needs shape (size, size, ...), got {x.shape}")
        return x
