"""The XLA/ICI communicator — the performance backend.

Reference parity: ``chainermn/communicators/pure_nccl_communicator.py ::
PureNcclCommunicator`` [uv] plus the MPI plumbing of
``mpi_communicator_base.py`` [uv] (SURVEY.md §2.1, §3.1).  Where the
reference lazily builds an NCCL ring (unique-id bcast over MPI →
``ncclCommInitRank``), here the "ring" already exists: the TPU slice's ICI
fabric, addressed through a ``jax.sharding.Mesh``.  Every collective is a
small SPMD program (``shard_map`` over the mesh, ``jax.lax`` collective
inside) compiled once per (op, shape, dtype) and cached — the analog of the
reference caching its NCCL communicator after ``_init_comms``.

There is no pack/unpack gradient bucketing (`_memory_utility.py` [uv]):
XLA fuses and schedules collectives itself, and on the hot path the
mean-gradient reduction lives *inside* the jitted train step
(`chainermn_tpu.optimizers`), so the eager face below is for tests, setup
and debugging — mirroring how the reference's eager allreduce was its hot
path but ours is compiled.

Data model: rank-major stacked global arrays — see ``base.py`` docstring.
"""

from __future__ import annotations

import pickle
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .._compat import shard_map
from ..topology import DEFAULT_AXIS_NAME, Topology, make_mesh
from .base import CommunicatorBase, LaneConfig, lane_call


class XlaCommunicator(CommunicatorBase):
    """Collectives lowered to XLA over ICI/DCN (the ``pure_nccl`` analog)."""

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        axis_name: str = DEFAULT_AXIS_NAME,
        devices: Optional[Sequence[jax.Device]] = None,
    ):
        if mesh is None:
            mesh = make_mesh(devices, axis_name)
        if len(mesh.axis_names) != 1:
            raise ValueError(
                "XlaCommunicator wants a 1-D mesh; build hybrid layouts with "
                "topology.make_nd_mesh and slice per-axis communicators via "
                "sub-meshes (reference analog: CommunicatorBase.split).")
        self.mesh = mesh
        self.axis_name = mesh.axis_names[0]
        self._devices = list(mesh.devices.ravel())
        self._topo = Topology.detect(self._devices)
        self._stack_sharding = NamedSharding(mesh, P(self.axis_name))
        self._replicated = NamedSharding(mesh, P())
        self._progs: Dict[Any, Callable] = {}
        self._obj_mailbox: List[bytes] = []
        self._obj_seq: Dict[Any, int] = {}
        # Sticky capability flag: True once the backend has proven it
        # cannot run multiprocess computations (CPU backend).  The
        # object-lane collectives then go straight to the KV fallback
        # instead of re-running a failing multihost attempt per call.
        self._mp_compute_off = False
        # Hardened-lane retry policy (env-tunable, gang-uniform): every
        # KV-store operation below rides ``lane_call`` — transient faults
        # back off and retry, permanent ones die loudly naming the lane.
        self.lane_config = LaneConfig()

    # ---- topology ----
    @property
    def rank(self) -> int:
        # First global rank owned by this process (host-level in
        # multi-controller; 0 in single-controller where we own all ranks).
        for i, d in enumerate(self._devices):
            if d.process_index == jax.process_index():
                return i
        return 0

    @property
    def size(self) -> int:
        return self._topo.size

    @property
    def intra_rank(self) -> int:
        return self._topo.intra_rank_of(self.rank)

    @property
    def intra_size(self) -> int:
        return self._topo.intra_size

    @property
    def inter_rank(self) -> int:
        return self._topo.inter_rank

    @property
    def inter_size(self) -> int:
        return self._topo.inter_size

    def owns_rank(self, r: int) -> bool:
        return self._devices[r].process_index == jax.process_index()

    def _ranks_by_process(self) -> Dict[int, List[int]]:
        """process_index → its ranks in mesh order (gather/scatter routing)."""
        ranks_of: Dict[int, List[int]] = {}
        for r, d in enumerate(self._devices):
            ranks_of.setdefault(d.process_index, []).append(r)
        return ranks_of

    def device_of(self, rank: int):
        return self._devices[rank]

    # ---- compiled-program cache ----
    def _program(self, key, fn, in_specs=None, out_specs=None):
        if key not in self._progs:
            ax = self.axis_name
            smapped = shard_map(
                fn,
                mesh=self.mesh,
                in_specs=in_specs if in_specs is not None else P(ax),
                out_specs=out_specs if out_specs is not None else P(ax),
            )
            self._progs[key] = jax.jit(smapped)
        return self._progs[key]

    def _place(self, x):
        return jax.device_put(jnp.asarray(x), self._stack_sharding)

    def _check(self, x):
        self._check_leading(x)
        return self._place(x) if not self._is_placed(x) else x

    def _is_placed(self, x) -> bool:
        return isinstance(x, jax.Array) and x.sharding == self._stack_sharding

    # ---- array collectives ----
    def allreduce(self, x, op: str = "sum"):
        x = self._check(jnp.asarray(x))
        ax = self.axis_name
        if op == "sum":
            fn = lambda b: jax.lax.psum(b, ax)
        elif op == "mean":
            fn = lambda b: jax.lax.pmean(b, ax)
        elif op == "max":
            fn = lambda b: jax.lax.pmax(b, ax)
        elif op == "min":
            fn = lambda b: jax.lax.pmin(b, ax)
        elif op == "prod":
            fn = lambda b: jnp.prod(
                jax.lax.all_gather(b, ax, axis=0, tiled=True), axis=0, keepdims=True)
        else:
            raise ValueError(f"unknown reduce op {op!r}")
        return self._program(("allreduce", op), fn)(x)

    def bcast(self, x, root: int = 0):
        x = self._check(jnp.asarray(x))
        ax = self.axis_name

        def fn(b):
            g = jax.lax.all_gather(b, ax, axis=0, tiled=True)
            return jax.lax.dynamic_slice_in_dim(g, root, 1, axis=0)

        return self._program(("bcast", root), fn)(x)

    def gather(self, x, root: int = 0):
        """Materialize the full rank-major stack ON root's process.

        Reference contract (``mpi_communicator_base.py :: gather`` [uv]):
        the payload is meaningful only at root; other ranks receive None.
        Single-controller (one process owns every rank): the stack already
        IS the gathered array — returned directly.  Multi-controller: each
        non-root process sends ONLY its local rows to root over the
        KV-store lane (the exact mirror of :meth:`scatter` — a
        ``process_allgather`` would land the full stack on EVERY host,
        moving P× the needed bytes over DCN); root assembles the stack in
        rank order and returns it, every other process returns None.
        """
        x = self._check(jnp.asarray(x))
        if not self._multiprocess():
            return x
        me = jax.process_index()
        ranks_of = self._ranks_by_process()
        # x is the rank-major global stack; each process can address only
        # its own shards, so pull the local rows out via addressable data.
        local = {}
        for shard in x.addressable_shards:
            r = shard.index[0].start if isinstance(shard.index, tuple) else 0
            local[r if r is not None else 0] = np.asarray(shard.data)
        if self.owns_rank(root):
            rows = dict(local)
            for proc, ranks in ranks_of.items():
                if proc == me:
                    continue
                payload = self.recv_obj(source=ranks[0])
                rows.update(payload)
            return np.concatenate([rows[r] for r in sorted(rows)], axis=0)
        self.send_obj(local, dest=root)
        return None

    def allgather(self, x):
        x = self._check(jnp.asarray(x))
        ax = self.axis_name

        def fn(b):
            return jax.lax.all_gather(b, ax, axis=0, tiled=True)[None]

        return self._program(("allgather",), fn)(x)

    def alltoall(self, x):
        x = self._check(self._check_alltoall(jnp.asarray(x)))
        ax = self.axis_name

        def fn(b):  # block: (1, size, *s)
            y = jax.lax.all_to_all(b, ax, split_axis=1, concat_axis=0, tiled=True)
            return jnp.swapaxes(y, 0, 1)  # (1, size, *s); out[0][s] = x[s][r]

        return self._program(("alltoall",), fn)(x)

    def scatter(self, x, root: int = 0):
        """Distribute root's ``(size, *s)`` payload so each rank holds its
        row (reference: ``scatter`` [uv] — only root's buffer matters).

        Single-controller: placing the rank-major stack IS the scatter.
        Multi-controller: non-root processes may pass ``x=None``; root
        sends each process ONLY its rows over the KV-store lane (a bcast
        of the whole stack would move P× the necessary bytes over DCN),
        and every process installs its block into the stack sharding.
        """
        if not self._multiprocess():
            return self._check(jnp.asarray(x))
        from jax.experimental import multihost_utils

        me = jax.process_index()
        ranks_of = self._ranks_by_process()
        if self.owns_rank(root):
            x = np.asarray(x)
            self._check_leading(x)
            for proc, ranks in ranks_of.items():
                if proc == me:
                    continue
                self.send_obj(x[np.asarray(ranks)], dest=ranks[0])
            local = x[np.asarray(ranks_of[me])]
        else:
            local = np.asarray(self.recv_obj(source=root))
        # Local rows are ordered by this process's ranks in mesh order,
        # exactly the layout host_local_array_to_global_array expects.
        return multihost_utils.host_local_array_to_global_array(
            local, self.mesh, P(self.axis_name))

    def send(self, x, dest: int, source: int):
        x = self._check(jnp.asarray(x))
        ax = self.axis_name

        def fn(b):
            moved = jax.lax.ppermute(b, ax, perm=[(source, dest)])
            idx = jax.lax.axis_index(ax)
            return jnp.where(idx == dest, moved, b)

        return self._program(("send", source, dest), fn)(x)

    def recv(self, x, source: int, dest: int):
        return self.send(x, dest=dest, source=source)

    # ---- object transport (setup path; DCN KV-store under multi-controller) ----
    #
    # Reference analog: pickled `*_obj` comms over MPI
    # (mpi_communicator_base.py [uv]).  Multi-controller note: one process per
    # host means object transport is HOST-level (the reference had one process
    # per GPU).  Collective results are expanded to one entry per rank by
    # mapping each rank to its host's entry, which matches what each reference
    # rank on that host would have contributed for host-resident state.

    def _multiprocess(self) -> bool:
        return jax.process_count() > 1

    def _kv_client(self):
        """The jax.distributed KV store — our DCN side channel (the analog of
        the reference's MPI object lane).  Internal API, but the only
        process-to-process transport JAX exposes; gated so single-process
        never touches it."""
        from jax._src import distributed
        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "jax.distributed is not initialized; call "
                "chainermn_tpu.init_distributed(coordinator_address=...) first")
        return client

    def _kv_exchange_obj(self, tag: str, payload: Optional[bytes],
                         src_procs: Optional[List[int]] = None
                         ) -> Dict[int, bytes]:
        """Generic object exchange over the jax.distributed KV store: each
        process in ``src_procs`` (default: all) publishes ``payload``
        under a fresh generation key; every process reads every
        publisher's entry.  The fallback transport for backends whose
        compute fabric cannot run multiprocess programs (this container's
        CPU backend: ``multihost_utils`` collectives raise
        INVALID_ARGUMENT) — the KV store is plain gRPC to the
        coordinator, always available once jax.distributed is up."""
        me = jax.process_index()
        if src_procs is None:
            src_procs = list(range(jax.process_count()))
        gen = self._obj_seq.setdefault(("kv_exchange", tag), 0)
        self._obj_seq[("kv_exchange", tag)] = gen + 1
        client = self._kv_client()
        if me in src_procs:
            lane_call(
                f"kv_store/set/{tag}",
                lambda: self._kv_set_overwrite(
                    client,
                    f"chainermn_tpu_xchg/{tag}/{gen}/{me}", payload or b""),
                self.lane_config)
            # GC: these exchanges are collective calls made in the same
            # order by every process, so by the time ANY process publishes
            # generation g every process has finished READING g-2 (it
            # published g-1, which required completing g-2) — our own g-2
            # key is dead.  Without this the per-iteration
            # ObservationAggregator would grow the coordinator's KV store
            # without bound.
            if gen >= 2:
                try:
                    client.key_value_delete(
                        f"chainermn_tpu_xchg/{tag}/{gen - 2}/{me}")
                except Exception:
                    pass  # older jaxlib without delete: leak, don't fail
        return {
            p: lane_call(
                f"kv_store/get/{tag}",
                lambda p=p: client.blocking_key_value_get_bytes(
                    f"chainermn_tpu_xchg/{tag}/{gen}/{p}",
                    self.lane_config.timeout_ms),
                self.lane_config)
            for p in src_procs
        }

    @staticmethod
    def _kv_set_overwrite(client, key: str, payload: bytes) -> None:
        """KV set that stays IDEMPOTENT under lane retries: a transient
        fault can strike after the coordinator applied the set but before
        the client saw the reply, so the retry hits the same key — some
        jaxlib versions refuse overwrite, which would misclassify the
        recovered fault as permanent.  Delete-then-set absorbs it."""
        try:
            client.key_value_set_bytes(key, payload)
        except Exception as set_err:
            try:
                client.key_value_delete(key)
            except Exception:
                # older jaxlib without delete (or delete itself faulted):
                # surface the ORIGINAL set fault so lane_call classifies
                # the real failure, not a masking AttributeError
                raise set_err
            client.key_value_set_bytes(key, payload)

    def _mp_compute_unavailable(self, e: Exception) -> bool:
        """True for the DETERMINISTIC backend-capability error ("…aren't
        implemented on the CPU backend") — identical on every process and
        every call, so all ranks switch to the KV fallback in lockstep.
        Transient runtime errors (network blip, preemption) do NOT match
        and propagate: a per-call fallback on those could split-brain the
        transport (some ranks on the KV lane, some not) and desync the
        generation counters."""
        if "implemented" in str(e).lower():
            self._mp_compute_off = True
            return True
        return False

    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        if self._multiprocess():
            root_proc = self._devices[root].process_index
            is_src = jax.process_index() == root_proc
            if not self._mp_compute_off:
                try:
                    from jax.experimental import multihost_utils
                    payload = np.frombuffer(pickle.dumps(obj),
                                            dtype=np.uint8)
                    n = int(multihost_utils.broadcast_one_to_all(
                        np.asarray(payload.size, np.int64),
                        is_source=is_src))
                    buf = payload if is_src else np.zeros(n, np.uint8)
                    out = multihost_utils.broadcast_one_to_all(
                        buf, is_source=is_src)
                    return pickle.loads(np.asarray(out).tobytes())
                except jax.errors.JaxRuntimeError as e:
                    if not self._mp_compute_unavailable(e):
                        raise
            got = self._kv_exchange_obj(
                "bcast", pickle.dumps(obj) if is_src else None,
                src_procs=[root_proc])
            return pickle.loads(got[root_proc])
        return pickle.loads(pickle.dumps(obj))

    def gather_obj(self, obj: Any, root: int = 0) -> Optional[List[Any]]:
        if self._multiprocess():
            per_proc = None
            if not self._mp_compute_off:
                try:
                    # Variable-length payloads: gather lengths first
                    # (fixed shape), pad to the max, then trim per entry.
                    from jax.experimental import multihost_utils
                    payload = np.frombuffer(pickle.dumps(obj),
                                            dtype=np.uint8)
                    lengths = multihost_utils.process_allgather(
                        np.asarray([payload.size], np.int64))
                    lengths = np.asarray(lengths).reshape(-1)
                    buf = np.zeros(int(lengths.max()), np.uint8)
                    buf[: payload.size] = payload
                    stacked = np.asarray(
                        multihost_utils.process_allgather(buf))
                    per_proc = [
                        pickle.loads(stacked[p, : int(lengths[p])].tobytes())
                        for p in range(stacked.shape[0])
                    ]
                except jax.errors.JaxRuntimeError as e:
                    if not self._mp_compute_unavailable(e):
                        raise
            if per_proc is None:
                # CPU backend: ride the KV-store lane instead (see
                # _kv_exchange_obj) — same all-processes-participate
                # contract, so the fallback is collective-safe
                got = self._kv_exchange_obj("gather", pickle.dumps(obj))
                per_proc = [pickle.loads(got[p]) for p in sorted(got)]
            # one entry per RANK: each rank maps to its owning host's object
            return [per_proc[self._devices[r].process_index] for r in range(self.size)]
        return [pickle.loads(pickle.dumps(obj)) for _ in range(self.size)]

    def allgather_obj(self, obj: Any) -> List[Any]:
        return self.gather_obj(obj)

    def allgather_obj_eventual(self, tag: str, obj: Any,
                               timeout_s: float = 10.0,
                               discard_tag: Optional[str] = None
                               ) -> Dict[int, Any]:
        """Bounded best-effort gather over the KV store (base contract).

        Unlike ``_kv_exchange_obj`` there are NO generation counters —
        keys are unique per (tag, process), so any subset of processes
        may call, in any order, without desyncing the lockstep lanes.
        The publish rides ``lane_call`` (``_kv_set_overwrite`` keeps the
        retry idempotent — a re-publish of the same tag, e.g. a
        preemption final save re-saving the periodic generation, is
        legal); ``timeout_s`` is the TOTAL read budget shared across all
        peers, so a gang of absent peers costs ``timeout_s`` once — not
        per peer — and can never eat a preemption grace window n-1
        times over.  ``timeout_s <= 0`` means publish-only: no peer
        reads at all (the non-owner side of the manifest exchange).
        """
        me = jax.process_index()
        if not self._multiprocess():
            return {me: obj}
        client = self._kv_client()
        key = f"chainermn_tpu_evt/{tag}/{me}"
        payload = pickle.dumps(obj)

        lane_call(f"kv_store/evt_set/{tag}",
                  lambda: self._kv_set_overwrite(client, key, payload),
                  self.lane_config)
        if discard_tag is not None and discard_tag != tag:
            try:
                client.key_value_delete(
                    f"chainermn_tpu_evt/{discard_tag}/{me}")
            except Exception:
                pass  # GC best-effort; older jaxlib without delete
        out = {me: obj}
        if timeout_s <= 0:
            return out
        # Round-robin SHORT-SLICE polling, not one full-budget get per
        # peer in index order: a published key returns instantly, so a
        # dead low-index peer burns one slice per round instead of the
        # whole budget — live higher-index peers' entries are still
        # collected within the bound.
        deadline = time.monotonic() + timeout_s
        poll_ms = max(50, min(500, int(timeout_s * 1000) // 8))
        pending = [p for p in range(jax.process_count()) if p != me]
        while pending:
            for p in list(pending):
                remaining_ms = int((deadline - time.monotonic()) * 1000)
                if remaining_ms <= 0:
                    return out  # budget spent — whatever we have, degraded
                try:
                    data = client.blocking_key_value_get_bytes(
                        f"chainermn_tpu_evt/{tag}/{p}",
                        min(poll_ms, remaining_ms))
                    out[p] = pickle.loads(data)
                    pending.remove(p)
                except Exception:
                    pass  # absent this round — degraded, never wedged
        return out

    def kv_lane_transport(self):
        """The serving KV-transfer plane's wire over the jax.distributed
        KV store (ISSUE 9): tag-addressed put/get/delete with the same
        idempotent-set discipline the checkpoint lanes use.  The raw
        store ops raise freely — the transfer plane wraps each call in
        ``lane_call``, which classifies, retries, and names the lane.
        Single-process falls back to the in-process loopback store.
        Fleets that must outlive their members (workers SIGKILLed,
        drained, re-admitted — ISSUE 10) use the coordinator-free
        ``serving.lanes.FileLaneStore`` with the same face instead:
        this store dies with the jax.distributed coordinator."""
        if not self._multiprocess():
            return super().kv_lane_transport()
        comm = self

        class _KvStoreLane:
            def put(self, tag: str, payload: bytes) -> None:
                comm._kv_set_overwrite(comm._kv_client(),
                                       f"chainermn_tpu_kvxfer/{tag}",
                                       bytes(payload))

            def get(self, tag: str, timeout_s: float = 10.0) -> bytes:
                return comm._kv_client().blocking_key_value_get_bytes(
                    f"chainermn_tpu_kvxfer/{tag}",
                    max(int(float(timeout_s) * 1000), 1))

            def delete(self, tag: str) -> None:
                comm._kv_client().key_value_delete(
                    f"chainermn_tpu_kvxfer/{tag}")

        return _KvStoreLane()

    def allreduce_obj(self, obj: Any, op: Callable = None) -> Any:
        op = op or (lambda a, b: a + b)
        gathered = self.allgather_obj(obj)
        out = gathered[0]
        for o in gathered[1:]:
            out = op(out, o)
        return out

    def send_obj(self, obj: Any, dest: int) -> None:
        """P2p object send.  Cross-process: the pickled payload rides the
        jax.distributed KV store keyed by (src_proc, dest_proc, seq) — the
        DCN analog of the reference's tagged MPI send [uv]."""
        dest_proc = self._devices[dest].process_index
        if self._multiprocess() and dest_proc != jax.process_index():
            src = jax.process_index()
            seq = self._obj_seq.setdefault(("send", src, dest_proc), 0)
            self._obj_seq[("send", src, dest_proc)] = seq + 1
            key = f"chainermn_tpu_obj/{src}/{dest_proc}/{seq}"
            payload = pickle.dumps(obj)
            lane_call(
                "kv_store/send_obj",
                lambda: self._kv_set_overwrite(
                    self._kv_client(), key, payload),
                self.lane_config)
            return
        self._obj_mailbox.append(pickle.dumps(obj))

    def recv_obj(self, source: int, timeout_ms: Optional[int] = None) -> Any:
        src_proc = self._devices[source].process_index
        if self._multiprocess() and src_proc != jax.process_index():
            me = jax.process_index()
            seq = self._obj_seq.setdefault(("recv", src_proc, me), 0)
            self._obj_seq[("recv", src_proc, me)] = seq + 1
            key = f"chainermn_tpu_obj/{src_proc}/{me}/{seq}"
            ms = self.lane_config.timeout_ms if timeout_ms is None \
                else timeout_ms
            data = lane_call(
                "kv_store/recv_obj",
                lambda: self._kv_client().blocking_key_value_get_bytes(
                    key, ms),
                self.lane_config)
            return pickle.loads(data)
        return pickle.loads(self._obj_mailbox.pop(0))

    # ---- model helpers ----
    def broadcast_data(self, params):
        """Replicate a pytree onto every chip of the mesh (ICI broadcast)."""
        return jax.device_put(params, self._replicated)

    def multi_node_mean_grad(self, grads):
        return jax.tree_util.tree_map(lambda g: self.allreduce(g, op="mean"), grads)

    # ---- structure ----
    def split(self, color: Union[int, Sequence[int]], key: int = 0):
        """Partition the mesh into per-color sub-communicators.

        See :meth:`CommunicatorBase.split` for the single-controller
        adaptation of MPI's per-rank-color contract: a per-rank color
        sequence returns ``{color: XlaCommunicator}`` over the matching
        device subsets; a scalar color means "every rank chose the same
        color", i.e. one group containing the whole world.
        """
        if isinstance(color, int):
            return XlaCommunicator(devices=self._devices, axis_name=self.axis_name)
        if len(color) != self.size:
            raise ValueError(f"need {self.size} colors, got {len(color)}")
        groups: Dict[int, List[jax.Device]] = {}
        for r, c in enumerate(color):
            groups.setdefault(int(c), []).append(self._devices[r])
        return {
            c: XlaCommunicator(devices=devs, axis_name=self.axis_name)
            for c, devs in sorted(groups.items())
        }
