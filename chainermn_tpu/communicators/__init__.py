"""Communicator factory.

Reference parity: ``chainermn/communicators/__init__.py ::
create_communicator(communicator_name='hierarchical', ...)`` [uv]
(SURVEY.md §2.1).  The reference dispatches over seven NCCL/MPI topology
variants (``pure_nccl``, ``hierarchical``, ``two_dimensional``, ``flat``,
``naive``, ``single_node``, ``non_cuda_aware``) because GPU clusters expose
a two-tier fabric (NVLink intra-node, IB/Ethernet inter-node) that software
must orchestrate.  A TPU slice has ONE fabric (ICI) orchestrated by XLA, so
every accelerated variant maps to the same backend; the historical names are
accepted as aliases so reference users' ``--communicator`` flags keep
working, each alias documented with what it used to mean.
"""

from __future__ import annotations

from typing import Optional

from .base import CommunicatorBase
from .naive import NaiveCommunicator
from .xla import XlaCommunicator

# name → (class, note) — aliases preserve the reference's CLI surface.
_ALIASES = {
    "xla": "the TPU-native backend (ICI collectives via XLA)",
    "pure_nccl": "reference's NCCL-everywhere path → XLA/ICI",
    "hierarchical": "reference's NCCL-intra + MPI-inter → XLA/ICI (single fabric)",
    "two_dimensional": "reference's 2-D reduce-scatter/allgather → XLA/ICI",
    "flat": "reference's flat CUDA-aware-MPI path → XLA/ICI",
    "single_node": "reference's single-node NCCL path → XLA/ICI",
    "non_cuda_aware": "reference's host-staged path → XLA/ICI (no host staging on TPU)",
}


def create_communicator(
    communicator_name: str = "xla",
    mesh=None,
    devices=None,
    size: Optional[int] = None,
    axis_name: Optional[str] = None,
) -> CommunicatorBase:
    """Create a communicator by name (reference: ``create_communicator`` [uv]).

    ``naive`` gives the pure-host numpy loopback (debug/oracle); every other
    historical name resolves to :class:`XlaCommunicator`.
    """
    name = communicator_name.lower()
    if name == "naive":
        if mesh is not None or devices is not None:
            raise ValueError(
                "naive is device-free; pass size=..., not mesh/devices")
        return NaiveCommunicator(size=size)
    if name in _ALIASES:
        if size is not None:
            # Honor the requested world size with the first `size` chips.
            if mesh is not None or devices is not None:
                raise ValueError("pass either size or mesh/devices, not both")
            import jax
            all_devices = jax.devices()
            if len(all_devices) < size:
                raise ValueError(
                    f"size={size} requested but only {len(all_devices)} devices")
            devices = all_devices[:size]
        kwargs = {}
        if axis_name is not None:
            kwargs["axis_name"] = axis_name
        return XlaCommunicator(mesh=mesh, devices=devices, **kwargs)
    raise ValueError(
        f"unknown communicator {communicator_name!r}; known: "
        f"{['naive', *sorted(_ALIASES)]}")


__all__ = [
    "CommunicatorBase",
    "NaiveCommunicator",
    "XlaCommunicator",
    "create_communicator",
]
