"""Pure-host reference communicator.

Reference parity: ``chainermn/communicators/naive_communicator.py ::
NaiveCommunicator`` [uv] (SURVEY.md §2.1) — the no-GPU, per-param, CPU-staged
MPI baseline used for debugging and as the correctness floor (BASELINE
config #1).  Here it is a pure-numpy implementation over rank-major stacks:
no devices, no XLA, no mesh — which makes it the *oracle* every accelerated
backend is tested against (the reference tested against numpy results the
same way, SURVEY.md §4).
"""

from __future__ import annotations

import pickle
from typing import Any, Callable, List, Optional

import jax
import numpy as np

from .base import CommunicatorBase

_REDUCERS = {
    "sum": lambda x: x.sum(axis=0),
    "mean": lambda x: x.mean(axis=0),
    "max": lambda x: x.max(axis=0),
    "min": lambda x: x.min(axis=0),
    "prod": lambda x: x.prod(axis=0),
}


class NaiveCommunicator(CommunicatorBase):
    """Loopback communicator: ``size`` logical ranks in one process, numpy math."""

    def __init__(self, size: Optional[int] = None):
        self._size = int(size) if size else max(len(jax.devices()), 1)
        self._mailbox: List[bytes] = []  # FIFO for send_obj/recv_obj loopback

    # topology: all ranks are "intra" (single host)
    @property
    def rank(self) -> int:
        return 0

    @property
    def size(self) -> int:
        return self._size

    @property
    def intra_rank(self) -> int:
        return 0

    @property
    def intra_size(self) -> int:
        return self._size

    @property
    def inter_rank(self) -> int:
        return 0

    @property
    def inter_size(self) -> int:
        return 1

    # ---- array collectives ----
    def _check(self, x) -> np.ndarray:
        return self._check_leading(np.asarray(x))

    def allreduce(self, x, op: str = "sum"):
        x = self._check(x)
        red = _REDUCERS[op](x)
        return np.broadcast_to(red, x.shape).copy()

    def bcast(self, x, root: int = 0):
        x = self._check(x)
        return np.broadcast_to(x[root], x.shape).copy()

    def gather(self, x, root: int = 0):
        return self._check(x).copy()

    def allgather(self, x):
        x = self._check(x)
        return np.broadcast_to(x[None], (self._size,) + x.shape).copy()

    def alltoall(self, x):
        x = self._check_alltoall(self._check(x))
        return np.swapaxes(x, 0, 1).copy()

    def scatter(self, x, root: int = 0):
        # Root's (size, *s) payload; each rank receives its slab — which for a
        # rank-major stack is the identity layout.
        return self._check(x).copy()

    def send(self, x, dest: int, source: int):
        x = self._check(x).copy()
        x[dest] = x[source]
        return x

    def recv(self, x, source: int, dest: int):
        return self.send(x, dest=dest, source=source)

    # ---- object transport ----
    def bcast_obj(self, obj: Any, root: int = 0) -> Any:
        return pickle.loads(pickle.dumps(obj))

    def gather_obj(self, obj: Any, root: int = 0) -> Optional[List[Any]]:
        return [pickle.loads(pickle.dumps(obj)) for _ in range(self._size)]

    def allgather_obj(self, obj: Any) -> List[Any]:
        return self.gather_obj(obj)

    def allreduce_obj(self, obj: Any, op: Callable = None) -> Any:
        op = op or (lambda a, b: a + b)
        out = obj
        for _ in range(self._size - 1):
            out = op(out, obj)
        return out

    def send_obj(self, obj: Any, dest: int) -> None:
        self._mailbox.append(pickle.dumps(obj))

    def recv_obj(self, source: int) -> Any:
        return pickle.loads(self._mailbox.pop(0))

    # ---- model helpers ----
    def broadcast_data(self, params):
        return jax.tree_util.tree_map(np.asarray, params)

    def multi_node_mean_grad(self, grads):
        return jax.tree_util.tree_map(lambda g: self.allreduce(g, op="mean"), grads)

    def split(self, color, key: int = 0):
        # Same contract as CommunicatorBase.split: scalar color = everyone in
        # one group (whole world); per-rank sequence = {color: communicator}
        # sized by group membership.
        if isinstance(color, int):
            return NaiveCommunicator(size=self._size)
        if len(color) != self._size:
            raise ValueError(f"need {self._size} colors, got {len(color)}")
        groups = {}
        for c in color:
            groups[int(c)] = groups.get(int(c), 0) + 1
        return {c: NaiveCommunicator(size=n) for c, n in sorted(groups.items())}
