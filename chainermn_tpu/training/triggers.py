"""Interval triggers (Chainer ``training.triggers.IntervalTrigger`` analog [uv])."""

from __future__ import annotations

from typing import Tuple, Union


class IntervalTrigger:
    """Fires every ``period`` iterations or epochs.

    Epoch triggering uses ``epoch_detail`` (fractional epochs from the
    iterator) so uneven shard sizes and mid-epoch resumes stay correct —
    the same contract Chainer's trigger relied on [uv].
    """

    def __init__(self, period: Union[int, float], unit: str):
        if unit not in ("iteration", "epoch"):
            raise ValueError(f"unit must be iteration|epoch, got {unit!r}")
        self.period = period
        self.unit = unit
        self._last_epoch_detail = 0.0

    def __call__(self, trainer) -> bool:
        if self.unit == "iteration":
            return trainer.iteration % self.period == 0
        prev, cur = self._last_epoch_detail, trainer.epoch_detail
        self._last_epoch_detail = cur
        return int(prev / self.period) != int(cur / self.period)

    def state_dict(self) -> dict:
        return {"last_epoch_detail": self._last_epoch_detail}

    def load_state_dict(self, state: dict) -> None:
        self._last_epoch_detail = float(state["last_epoch_detail"])


def get_trigger(trigger) -> IntervalTrigger:
    """Normalize ``(period, unit)`` tuples / None / callables to a trigger."""
    if trigger is None:
        return IntervalTrigger(1, "iteration")
    if isinstance(trigger, tuple):
        return IntervalTrigger(*trigger)
    if callable(trigger):
        return trigger
    raise TypeError(f"cannot interpret trigger {trigger!r}")
