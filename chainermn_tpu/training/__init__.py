"""Training loop with extension slots.

The reference has NO trainer of its own — it plugs into Chainer's
``Trainer``/``Updater``/``Extension`` machinery (SURVEY.md §1: "the
'runtime' is Chainer's Trainer loop").  A standalone framework must ship
that substrate, so this module provides the same architecture — an updater
that advances one iteration, a trainer that fires prioritized extensions on
interval triggers — built around jitted SPMD steps: the updater owns
replicated train state and calls one compiled step per iteration; the
device never syncs with the host unless an extension actually reads a
value.

Reference parity of the pieces (all [uv] against Chainer, the reference's
substrate): ``training.Trainer``, ``training.updaters.StandardUpdater``,
``training.triggers.IntervalTrigger``, extensions ``LogReport``,
``PrintReport``, ``snapshot``; ChainerMN's own extensions
(``chainermn/extensions/`` — SURVEY.md §2.6) slot in unchanged via
``__call__(trainer)``.
"""

from .trainer import Trainer, Extension, make_extension  # noqa: F401
from .triggers import IntervalTrigger, get_trigger  # noqa: F401
from .updaters import StandardUpdater  # noqa: F401
from . import extensions  # noqa: F401

__all__ = [
    "Trainer",
    "Extension",
    "make_extension",
    "IntervalTrigger",
    "get_trigger",
    "StandardUpdater",
    "extensions",
]
