"""Updaters: advance training by one iteration.

Chainer's ``StandardUpdater`` (the loop body under the reference's hot path,
SURVEY.md §3.2 "trainer.run → StandardUpdater.update_core") pulled a batch,
ran forward/backward eagerly, then the optimizer.  TPU-native the whole
iteration is one pre-compiled SPMD step: the updater converts the host
batch, shards it over the mesh, and calls the jitted step — device work is
dispatched asynchronously, so back-to-back iterations pipeline on-device
while the host prepares the next batch.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from ..observability import trace as _trace
from ..observability.comm import get_accountant as _get_accountant



def default_converter(batch):
    """List of (x, y, ...) tuples → tuple of stacked arrays."""
    if isinstance(batch[0], tuple):
        n = len(batch[0])
        return tuple(np.stack([b[i] for b in batch]) for i in range(n))
    return np.stack(batch)


class StandardUpdater:
    """Owns train state + iterator; one ``update()`` = one jitted step.

    ``step_fn(state, batch) -> (state, observation_dict)`` where ``state``
    is an arbitrary replicated pytree (params/opt_state/batch_stats...).
    ``observation`` values may be device scalars; they are NOT synced here
    (extensions decide when to block on them).
    """

    def __init__(self, iterator, step_fn: Callable, state: Any,
                 converter: Callable = default_converter,
                 mesh=None, axis_name: Optional[str] = None,
                 shard: bool = True):
        self.iterator = iterator
        self.step_fn = step_fn
        self.state = state
        self.converter = converter
        self.shard = shard
        self.iteration = 0
        self.phase_times: Optional[Dict[str, float]] = None
        self.last_batch_size: Optional[int] = None
        if shard:
            # Resolve mesh + sharding ONCE: rebuilding them per step would
            # put host-side Mesh construction on the hot path.
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ..topology import DEFAULT_AXIS_NAME, make_mesh
            ax = axis_name or DEFAULT_AXIS_NAME
            self.mesh = mesh if mesh is not None else make_mesh(axis_name=ax)
            self._batch_sharding = NamedSharding(
                self.mesh, P(self.mesh.axis_names[0]))
        else:
            self.mesh = mesh

    @property
    def epoch(self) -> int:
        return getattr(self.iterator, "epoch", 0)

    @property
    def is_new_epoch(self) -> bool:
        return getattr(self.iterator, "is_new_epoch", False)

    @property
    def epoch_detail(self) -> float:
        return getattr(self.iterator, "epoch_detail", float(self.epoch))

    def update(self) -> Dict[str, Any]:
        # Step-time breakdown: the data phase (host batch assembly +
        # device upload) vs the compute phase (the jitted step call —
        # asynchronous dispatch, so the on-device tail surfaces at the
        # next host sync).  ``phase_times`` feeds
        # ``observability.StepBreakdownReport``; spans land on the trace
        # timeline; the comm accountant's step capture attributes the
        # step program's collectives to this iteration.
        tracer = _trace.get_tracer()
        t0 = time.perf_counter()
        with tracer.span("step/data", cat="phase"):
            batch = self.iterator.next()
            arrays = self.converter(batch)
            if self.shard:
                arrays = jax.tree_util.tree_map(
                    lambda x: jax.device_put(x, self._batch_sharding), arrays)
        t1 = time.perf_counter()
        with _get_accountant().step("updater/step_fn"):
            with tracer.span("step/compute", cat="phase"):
                self.state, observation = self.step_fn(self.state, arrays)
        t2 = time.perf_counter()
        self.phase_times = {"data": t1 - t0, "compute": t2 - t1}
        leaves = jax.tree_util.tree_leaves(arrays)
        if leaves and getattr(leaves[0], "shape", None):
            self.last_batch_size = int(leaves[0].shape[0])
        self.iteration += 1
        return dict(observation)

    # ---- resume contract ----
    def state_dict(self) -> dict:
        out = {"iteration": self.iteration, "state": self.state}
        if hasattr(self.iterator, "state_dict"):
            out["iterator"] = self.iterator.state_dict()
        return out

    def load_state_dict(self, state: dict) -> None:
        self.iteration = int(state["iteration"])
        loaded = state["state"]
        # Restore device placement by matching the template's sharding.
        self.state = jax.tree_util.tree_map(
            lambda tmpl, v: jax.device_put(v, tmpl.sharding)
            if isinstance(tmpl, jax.Array) else v,
            self.state, loaded)
        if "iterator" in state and hasattr(self.iterator, "load_state_dict"):
            self.iterator.load_state_dict(state["iterator"])
