"""Updaters: advance training by one iteration.

Chainer's ``StandardUpdater`` (the loop body under the reference's hot path,
SURVEY.md §3.2 "trainer.run → StandardUpdater.update_core") pulled a batch,
ran forward/backward eagerly, then the optimizer.  TPU-native the whole
iteration is one pre-compiled SPMD step: the updater converts the host
batch, shards it over the mesh, and calls the jitted step — device work is
dispatched asynchronously, so back-to-back iterations pipeline on-device
while the host prepares the next batch.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from ..observability import trace as _trace
from ..observability.comm import get_accountant as _get_accountant


class _Prefetcher:
    """One-deep background host→device input pipeline (ISSUE 8 / ROADMAP
    5a): while step *k* runs on device, a daemon thread assembles batch
    *k+1* (iterator pull + convert + sharded ``device_put``), so the
    synchronous host→device handoff leaves the step's critical path —
    the ``data`` phase collapses to a queue pop.

    Exact-resume contract: each queued item carries the iterator
    ``state_dict`` captured right AFTER its batch was pulled, i.e. the
    state a resumed run needs so its next pull yields the FOLLOWING
    batch.  The updater checkpoints that per-item state, not the live
    iterator's (which runs up to two batches ahead), so prefetch never
    perturbs the training trajectory across a preemption.

    Errors raised while assembling (iterator exhaustion, converter bugs)
    re-raise in ``update()`` on the main thread, never vanish.
    """

    def __init__(self, iterator, converter, place):
        self.iterator = iterator
        self.converter = converter
        self.place = place
        self._q: queue.Queue = queue.Queue(maxsize=1)
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="chainermn-tpu-input-prefetch")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                batch = self.iterator.next()
                meta = {
                    "iterator_state": (self.iterator.state_dict()
                                       if hasattr(self.iterator,
                                                  "state_dict") else None),
                    "epoch": getattr(self.iterator, "epoch", 0),
                    "is_new_epoch": getattr(self.iterator, "is_new_epoch",
                                            False),
                    "epoch_detail": getattr(self.iterator, "epoch_detail",
                                            None),
                }
                arrays = self.place(self.converter(batch))
                item = ("batch", arrays, meta)
            except BaseException as e:  # noqa: BLE001 — re-raised in update()
                item = ("error", e, None)
            # bounded put that stays responsive to close()
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    break
                except queue.Full:
                    continue
            if item[0] == "error":
                return

    def get(self):
        # Latched error: the worker thread exits after enqueueing one
        # error item, so a caller that swallows the first raise (e.g. a
        # loop treating StopIteration as epoch end) and calls again must
        # re-raise, not block forever on an empty queue nobody feeds.
        if self._error is not None:
            raise self._error
        kind, payload, meta = self._q.get()
        if kind == "error":
            self._error = payload
            self.close()
            raise payload
        return payload, meta

    def close(self) -> None:
        self._stop.set()
        # unblock a put-blocked thread
        try:
            self._q.get_nowait()
        except queue.Empty:
            pass
        if self._thread.is_alive():
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                # Blocked inside iterator.next() (slow/streaming source):
                # Python can't kill it, and its in-flight pull may mutate
                # the iterator AFTER a load_state_dict restored the
                # cursor — warn loudly instead of silently racing the
                # exact-resume contract.
                import sys
                print("[chainermn_tpu prefetch] WARNING: prefetch worker "
                      "still blocked in iterator.next() after close(); "
                      "its in-flight pull may race a restored iterator "
                      "cursor", file=sys.stderr, flush=True)


def default_converter(batch):
    """List of (x, y, ...) tuples → tuple of stacked arrays."""
    if isinstance(batch[0], tuple):
        n = len(batch[0])
        return tuple(np.stack([b[i] for b in batch]) for i in range(n))
    return np.stack(batch)


class StandardUpdater:
    """Owns train state + iterator; one ``update()`` = one jitted step.

    ``step_fn(state, batch) -> (state, observation_dict)`` where ``state``
    is an arbitrary replicated pytree (params/opt_state/batch_stats...).
    ``observation`` values may be device scalars; they are NOT synced here
    (extensions decide when to block on them).
    """

    def __init__(self, iterator, step_fn: Callable, state: Any,
                 converter: Callable = default_converter,
                 mesh=None, axis_name: Optional[str] = None,
                 shard: bool = True, prefetch: bool = False):
        self.iterator = iterator
        self.step_fn = step_fn
        self.state = state
        self.converter = converter
        self.shard = shard
        self.iteration = 0
        self.phase_times: Optional[Dict[str, float]] = None
        self.last_batch_size: Optional[int] = None
        if shard:
            # Resolve mesh + sharding ONCE: rebuilding them per step would
            # put host-side Mesh construction on the hot path.
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ..topology import DEFAULT_AXIS_NAME, make_mesh
            ax = axis_name or DEFAULT_AXIS_NAME
            self.mesh = mesh if mesh is not None else make_mesh(axis_name=ax)
            self._batch_sharding = NamedSharding(
                self.mesh, P(self.mesh.axis_names[0]))
        else:
            self.mesh = mesh
        # Double-buffered input (see _Prefetcher): batch k+1 assembles on
        # a background thread while step k runs.  Epoch bookkeeping and
        # the checkpointed iterator state come from the CONSUMED batch's
        # snapshot, so triggers and elastic resume see the same trajectory
        # as the synchronous path.
        self.prefetch = bool(prefetch)
        self._prefetcher: Optional[_Prefetcher] = None
        self._consumed_meta: Optional[Dict[str, Any]] = None

    def _place(self, arrays):
        if self.shard:
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, self._batch_sharding), arrays)
        return arrays

    @property
    def epoch(self) -> int:
        if self._consumed_meta is not None:
            return self._consumed_meta["epoch"]
        return getattr(self.iterator, "epoch", 0)

    @property
    def is_new_epoch(self) -> bool:
        if self._consumed_meta is not None:
            return self._consumed_meta["is_new_epoch"]
        return getattr(self.iterator, "is_new_epoch", False)

    @property
    def epoch_detail(self) -> float:
        if self._consumed_meta is not None \
                and self._consumed_meta["epoch_detail"] is not None:
            return self._consumed_meta["epoch_detail"]
        return getattr(self.iterator, "epoch_detail", float(self.epoch))

    def close(self) -> None:
        """Stop the prefetch thread (no-op without ``prefetch=True``)."""
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None

    def update(self) -> Dict[str, Any]:
        # Step-time breakdown: the data phase (host batch assembly +
        # device upload) vs the compute phase (the jitted step call —
        # asynchronous dispatch, so the on-device tail surfaces at the
        # next host sync).  ``phase_times`` feeds
        # ``observability.StepBreakdownReport``; spans land on the trace
        # timeline; the comm accountant's step capture attributes the
        # step program's collectives to this iteration.
        tracer = _trace.get_tracer()
        t0 = time.perf_counter()
        with tracer.span("step/data", cat="phase"):
            if self.prefetch:
                if self._prefetcher is None:
                    self._prefetcher = _Prefetcher(
                        self.iterator, self.converter, self._place)
                arrays, self._consumed_meta = self._prefetcher.get()
            else:
                batch = self.iterator.next()
                arrays = self._place(self.converter(batch))
        t1 = time.perf_counter()
        with _get_accountant().step("updater/step_fn"):
            with tracer.span("step/compute", cat="phase"):
                self.state, observation = self.step_fn(self.state, arrays)
        t2 = time.perf_counter()
        self.phase_times = {"data": t1 - t0, "compute": t2 - t1}
        leaves = jax.tree_util.tree_leaves(arrays)
        if leaves and getattr(leaves[0], "shape", None):
            self.last_batch_size = int(leaves[0].shape[0])
        self.iteration += 1
        return dict(observation)

    # ---- resume contract ----
    def state_dict(self) -> dict:
        out = {"iteration": self.iteration, "state": self.state}
        if self.prefetch and self._consumed_meta is not None:
            # the CONSUMED batch's iterator snapshot, not the live
            # iterator's (which has prefetched ahead) — resuming from
            # this replays exactly the batches the steps never saw
            if self._consumed_meta["iterator_state"] is not None:
                out["iterator"] = self._consumed_meta["iterator_state"]
        elif hasattr(self.iterator, "state_dict"):
            out["iterator"] = self.iterator.state_dict()
        return out

    def load_state_dict(self, state: dict) -> None:
        self.iteration = int(state["iteration"])
        loaded = state["state"]
        # Restore device placement by matching the template's sharding.
        self.state = jax.tree_util.tree_map(
            lambda tmpl, v: jax.device_put(v, tmpl.sharding)
            if isinstance(tmpl, jax.Array) else v,
            self.state, loaded)
        # a running prefetcher holds batches pulled under the OLD cursor
        self.close()
        self._consumed_meta = None
        if "iterator" in state and hasattr(self.iterator, "load_state_dict"):
            self.iterator.load_state_dict(state["iterator"])
