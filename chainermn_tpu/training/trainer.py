"""The Trainer: run the updater until a stop trigger, firing extensions.

Chainer-Trainer analog [uv] (the reference's runtime substrate, SURVEY.md
§1/§3.2).  Extensions are callables ``ext(trainer)`` registered with an
interval trigger and a priority; higher priority runs first within an
iteration so aggregators (ObservationAggregator) run before writers
(LogReport) before readers (PrintReport) — the same three-band scheme
Chainer used (PRIORITY_WRITER/EDITOR/READER [uv]).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional

from ..observability import flight as _flight
from ..observability import trace as _trace
from .triggers import get_trigger

PRIORITY_EDITOR = 300   # mutate trainer.observation (aggregators)
PRIORITY_WRITER = 200   # persist observations (LogReport, snapshots)
PRIORITY_READER = 100   # consume logs (PrintReport)


class Extension:
    """Optional base class; any callable(trainer) works."""

    trigger = (1, "iteration")
    priority = PRIORITY_READER
    name: Optional[str] = None

    def __call__(self, trainer) -> None:
        raise NotImplementedError

    def initialize(self, trainer) -> None:
        pass

    def finalize(self) -> None:
        pass


def make_extension(trigger=(1, "iteration"), priority=PRIORITY_READER,
                   name=None):
    """Decorator stamping trigger/priority onto a plain function."""
    def wrap(fn):
        fn.trigger = trigger
        fn.priority = priority
        fn.name = name or fn.__name__
        return fn
    return wrap


class _Entry:
    def __init__(self, ext, trigger, priority, name):
        self.extension = ext
        self.trigger = get_trigger(trigger)
        self.priority = priority
        self.name = name


class Trainer:
    """Drive ``updater.update()`` until ``stop_trigger``; fire extensions."""

    def __init__(self, updater, stop_trigger, out: str = "result"):
        self.updater = updater
        period, unit = stop_trigger
        self._stop_period, self._stop_unit = period, unit
        self.out = out
        self.observation: Dict[str, Any] = {}
        self._extensions: Dict[str, _Entry] = {}
        self._start_time: Optional[float] = None
        # Monotonic stamp of the last completed unit of work (a step or any
        # single extension).  Liveness monitors (extensions.Watchdog) read
        # this so a slow-but-progressing extension pass is not mistaken for
        # a hang — only one stuck unit can exceed the timeout.
        self.last_progress: Optional[float] = None
        # Name of the last COMPLETED unit ("update" or "extension:<name>")
        # — the Watchdog includes it in stall reports, and the step-time
        # breakdown reads last_extension_time (the previous iteration's
        # whole extension pass, seconds).
        self.last_phase: Optional[str] = None
        self.last_extension_time: Optional[float] = None

    # ---- passthroughs the extensions read ----
    @property
    def iteration(self) -> int:
        return self.updater.iteration

    @property
    def epoch(self) -> int:
        return self.updater.epoch

    @property
    def epoch_detail(self) -> float:
        return self.updater.epoch_detail

    @property
    def is_new_epoch(self) -> bool:
        return self.updater.is_new_epoch

    @property
    def elapsed_time(self) -> float:
        return 0.0 if self._start_time is None else time.time() - self._start_time

    # ---- extension registry ----
    def extend(self, extension: Callable, trigger=None, priority=None,
               name: Optional[str] = None) -> None:
        trigger = trigger if trigger is not None else getattr(
            extension, "trigger", (1, "iteration"))
        priority = priority if priority is not None else getattr(
            extension, "priority", PRIORITY_READER)
        name = name or getattr(extension, "name", None) \
            or type(extension).__name__
        base, i = name, 0
        while name in self._extensions:
            i += 1
            name = f"{base}_{i}"
        self._extensions[name] = _Entry(extension, trigger, priority, name)

    def get_extension(self, name: str):
        return self._extensions[name].extension

    # ---- the loop ----
    def _stopped(self) -> bool:
        if self._stop_unit == "iteration":
            return self.iteration >= self._stop_period
        return self.epoch >= self._stop_period

    def run(self) -> None:
        if self._start_time is None:  # a resumed trainer keeps its offset
            self._start_time = time.time()
        for e in self._extensions.values():
            if hasattr(e.extension, "initialize"):
                e.extension.initialize(self)
        tracer = _trace.get_tracer()
        try:
            while not self._stopped():
                with tracer.span("step", cat="step",
                                 iteration=self.iteration + 1):
                    self.observation = self.updater.update()
                    self.last_progress = time.monotonic()
                    self.last_phase = "update"
                    _flight.note("phase", name="update",
                                 iteration=self.iteration)
                    t_ext = time.perf_counter()
                    with tracer.span("step/extensions", cat="phase"):
                        for e in sorted(self._extensions.values(),
                                        key=lambda e: -e.priority):
                            # Extensions with an ``observe`` hook see EVERY
                            # iteration (e.g. LogReport folding per-step stats
                            # into its means); ``__call__`` still fires only on
                            # the trigger — the same split Chainer's reporter/
                            # summary machinery provided [uv].
                            with tracer.span(f"ext/{e.name}", cat="extension"):
                                if hasattr(e.extension, "observe"):
                                    e.extension.observe(self)
                                if e.trigger(self):
                                    e.extension(self)
                            self.last_progress = time.monotonic()
                            self.last_phase = f"extension:{e.name}"
                    self.last_extension_time = time.perf_counter() - t_ext
        except BaseException:
            # Liveness monitors (Watchdog) MUST stop on the exception path —
            # a still-armed watchdog would os._exit a process that is busy
            # saving diagnostics.  Everything else keeps the no-finalize-on-
            # crash contract (see below).
            for e in self._extensions.values():
                if (getattr(e.extension, "finalize_on_error", False)
                        and hasattr(e.extension, "finalize")):
                    e.extension.finalize()
            raise
        # Finalize ONLY on clean completion (divergence from Chainer's
        # finally-block [uv], deliberately): extensions like the
        # checkpointer delete their fault-tolerance artifacts in finalize,
        # and doing that on the exception path would destroy exactly the
        # state a crashed job needs to resume from.
        for e in self._extensions.values():
            if hasattr(e.extension, "finalize"):
                e.extension.finalize()

    # ---- resume contract (MultiNodeCheckpointer calls checkpoint_state) ----
    def checkpoint_state(self) -> dict:
        state = {"updater": self.updater.state_dict(), "extensions": {},
                 "elapsed_time": self.elapsed_time}
        for name, e in self._extensions.items():
            if hasattr(e.extension, "state_dict"):
                state["extensions"][name] = e.extension.state_dict()
            if hasattr(e.trigger, "state_dict"):
                state["extensions"][f"{name}/trigger"] = e.trigger.state_dict()
        return state

    def load_checkpoint_state(self, state: dict) -> None:
        self.updater.load_state_dict(state["updater"])
        # Keep elapsed_time monotonic across the resume boundary.
        self._start_time = time.time() - float(state.get("elapsed_time", 0.0))
        for name, e in self._extensions.items():
            if name in state["extensions"] and hasattr(e.extension, "load_state_dict"):
                e.extension.load_state_dict(state["extensions"][name])
            tkey = f"{name}/trigger"
            if tkey in state["extensions"] and hasattr(e.trigger, "load_state_dict"):
                e.trigger.load_state_dict(state["extensions"][tkey])
