"""Stock trainer extensions: LogReport, PrintReport, Evaluator, snapshot.

Chainer analogs [uv] (`training/extensions/` in the reference's substrate);
rank-0 gating mirrors how the reference's examples register reporting
extensions only ``if comm.rank == 0`` (SURVEY.md §5 "metrics/logging").
Device scalars in observations are synced exactly once per log write —
the only host↔device sync points in the loop.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from .trainer import PRIORITY_READER, PRIORITY_WRITER


def _scalarize(v) -> float:
    return float(np.asarray(jax.device_get(v)))


class LogReport:
    """Accumulate observations; write mean entries every trigger.

    Entries land in ``trainer.out/log`` (JSON list, Chainer-compatible
    layout [uv]) and stay available in ``.log`` for PrintReport.
    """

    trigger = (1, "epoch")
    priority = PRIORITY_WRITER

    def __init__(self, keys: Optional[Sequence[str]] = None,
                 trigger=(1, "epoch"), filename: str = "log"):
        self.keys = keys
        self.trigger = trigger
        self.filename = filename
        self.log: List[Dict[str, Any]] = []
        self._accum: Dict[str, List[float]] = {}

    def initialize(self, trainer) -> None:
        os.makedirs(trainer.out, exist_ok=True)

    def _accumulate(self, observation) -> None:
        for k, v in observation.items():
            if self.keys is not None and k not in self.keys:
                continue
            try:
                self._accum.setdefault(k, []).append(_scalarize(v))
            except (TypeError, ValueError):
                pass  # non-scalar observation; LogReport only handles scalars

    def observe(self, trainer) -> None:
        # Trainer calls this every iteration: fold the step's observation
        # into the running means regardless of when the write trigger fires.
        self._accumulate(trainer.observation)

    def __call__(self, trainer) -> None:
        entry = {k: float(np.mean(vs)) for k, vs in self._accum.items()}
        entry.update({
            "iteration": trainer.iteration,
            "epoch": trainer.epoch,
            "elapsed_time": trainer.elapsed_time,
        })
        self.log.append(entry)
        self._accum = {}
        with open(os.path.join(trainer.out, self.filename), "w") as f:
            json.dump(self.log, f, indent=2)

    def state_dict(self) -> dict:
        # In-flight accumulators are part of the resume contract: a
        # mid-epoch checkpoint must reproduce the same epoch means as an
        # uninterrupted run.
        return {"log": self.log, "accum": self._accum}

    def load_state_dict(self, state: dict) -> None:
        self.log = list(state["log"])
        self._accum = {k: list(v) for k, v in state.get("accum", {}).items()}


class PrintReport:
    """Print selected LogReport columns as they appear (rank-0 style)."""

    trigger = (1, "epoch")
    priority = PRIORITY_READER

    def __init__(self, entries: Sequence[str], log_report: LogReport,
                 trigger=(1, "epoch")):
        self.entries = list(entries)
        self.log_report = log_report
        self.trigger = trigger
        self._printed = 0
        self._header_done = False

    def state_dict(self) -> dict:
        # Resume without re-printing the restored history.
        return {"printed": self._printed, "header_done": self._header_done}

    def load_state_dict(self, state: dict) -> None:
        self._printed = int(state["printed"])
        self._header_done = bool(state["header_done"])

    def __call__(self, trainer) -> None:
        if not self._header_done:
            print("  ".join(f"{e:>14}" for e in self.entries), flush=True)
            self._header_done = True
        for entry in self.log_report.log[self._printed:]:
            cells = []
            for e in self.entries:
                v = entry.get(e, "")
                cells.append(f"{v:14.6g}" if isinstance(v, float) else f"{v!s:>14}")
            print("  ".join(cells), flush=True)
        self._printed = len(self.log_report.log)


class StepTimer:
    """Per-step wall time (s) into ``observation['time/step']``.

    SURVEY.md §5: the reference had no in-tree profiling (Chainer TimerHook
    + nvprof externally); the rebuild ships per-step timing as a first-class
    extension.  LogReport folds the value into epoch means, giving
    throughput directly from the training log.  Priority above the writers
    so the stamp lands before LogReport.observe reads the observation.
    """

    trigger = (1, "iteration")
    priority = PRIORITY_WRITER + 50

    def __init__(self, key: str = "time/step"):
        self.key = key
        self._last: Optional[float] = None

    def observe(self, trainer) -> None:
        import time

        now = time.perf_counter()
        if self._last is not None:
            trainer.observation[self.key] = now - self._last
        self._last = now

    def __call__(self, trainer) -> None:
        pass

    def state_dict(self) -> dict:
        return {}  # wall-clock gaps across a resume are meaningless; restart

    def load_state_dict(self, state: dict) -> None:
        self._last = None


class JaxProfiler:
    """Capture a ``jax.profiler`` trace of iterations [start, stop).

    SURVEY.md §5 rebuild target ("jax.profiler hooks — cheap win"): the
    trace lands in ``logdir`` in TensorBoard/Perfetto format with the XLA
    executable timelines — the TPU-native answer to nvprof-wrapping the
    reference.  Defaults skip iteration 0-1 so compile time doesn't drown
    the steady-state steps.  Multi-host: every process writes its own
    host-suffixed trace directory, rank gating is unnecessary.
    """

    trigger = (1, "iteration")
    priority = PRIORITY_WRITER + 60  # bracket the step before observers run

    def __init__(self, logdir: str = "profile", start: int = 2,
                 stop: int = 5):
        if stop <= start:
            raise ValueError(f"need stop > start, got [{start}, {stop})")
        self.logdir = logdir
        self.start_iteration = int(start)
        self.stop_iteration = int(stop)
        self._active = False
        self._done = False

    def observe(self, trainer) -> None:
        it = trainer.iteration
        if (not self._done and not self._active
                and it + 1 >= self.start_iteration
                and it < self.stop_iteration):
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif self._active and it + 1 >= self.stop_iteration:
            self._stop()

    def _stop(self) -> None:
        # block so the trace captures the async dispatch queue, not a
        # still-running step
        jax.effects_barrier()
        jax.profiler.stop_trace()
        self._active = False
        self._done = True

    def __call__(self, trainer) -> None:
        pass

    def finalize(self) -> None:
        if self._active:
            self._stop()

    def state_dict(self) -> dict:
        return {"done": self._done}

    def load_state_dict(self, state: dict) -> None:
        self._done = bool(state.get("done", False))
        self._active = False


class EvaluatorExtension:
    """Run a multi-node evaluator on a trigger, merging results into the
    observation under ``validation/`` keys (Chainer ``Evaluator`` slot [uv])."""

    trigger = (1, "epoch")
    priority = PRIORITY_WRITER + 50  # before LogReport writes the entry

    def __init__(self, evaluate_fn: Callable[[Any], Dict[str, float]],
                 data, trigger=(1, "epoch"), prefix: str = "validation/"):
        self.evaluate_fn = evaluate_fn
        self.data = data
        self.trigger = trigger
        self.prefix = prefix

    def __call__(self, trainer) -> None:
        results = self.evaluate_fn(self.data)
        trainer.observation.update(
            {f"{self.prefix}{k}": v for k, v in results.items()})


def snapshot(checkpointer, trigger=None):
    """Adapt a MultiNodeCheckpointer into a trainer extension (the
    reference's ``trainer.extend(checkpointer, trigger=...)`` usage [uv]).

    Thin wrapper over the checkpointer's own extension ``__call__`` (single
    save path) whose only job is overriding the trigger and shielding the
    trainer from the checkpointer's ``finalize`` (which deletes shards —
    cleanup belongs to explicit job teardown, not loop exit).
    """
    from .trainer import make_extension

    trig = trigger or checkpointer.trigger

    @make_extension(trigger=trig, priority=PRIORITY_WRITER,
                    name="multi_node_snapshot")
    def _snap(trainer):
        checkpointer(trainer)
    return _snap
