"""SPMD training-step builder — the hot path.

Reference parity: the reference's hot loop (SURVEY.md §3.2) is
``_MultiNodeOptimizer.update``: forward/backward → eager bucketed NCCL
allreduce → optimizer kernels, four separate device phases.  TPU-native the
whole thing is ONE compiled SPMD program: forward, backward, the ICI
gradient mean (inside the optax wrapper) and the param update fuse into a
single XLA executable with buffer donation — the compiler overlaps the
collective with compute, which is what `_memory_utility` bucketing and the
double-buffering CUDA streams were approximating by hand.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import pcast_varying, shard_map
from .ops import collective as _col
from .optimizers import compressed_mean
from .topology import DEFAULT_AXIS_NAME, make_mesh


def _value_and_global_grads(local_loss, params, axis_name,
                            allreduce_grad_dtype, grad_reduce=None):
    """``((loss, aux), grads)`` with the cross-rank gradient mean done right.

    Default path: differentiate the GLOBAL mean loss (pmean over ranks of
    the local mean).  Under shard_map, autodiff w.r.t. replicated params
    inserts the cross-rank psum of cotangents itself — i.e. the gradient
    allreduce IS this pmean's backward pass, scheduled by XLA inside the
    step.  Taking grads of the local loss and averaging after would
    double-count (the AD-inserted psum already summed).

    Compressed path (``allreduce_grad_dtype`` set): differentiate the LOCAL
    loss w.r.t. a per-rank view of the params (pcast to varying OUTSIDE the
    differentiated function, so AD does not insert its own fp32 cotangent
    psum); the explicit :func:`compressed_mean` is then the one wire
    collective, in the reduced dtype.  ``local_loss(p)`` must return
    ``(loss, aux)``.

    ``grad_reduce`` replaces :func:`compressed_mean` entirely (same
    local-grad derivation): a ``grads -> grads`` callable owning the wire
    collective — e.g. ``ops.collective.hierarchical_pmean`` for the
    two-tier ICI×DCN mean over a multislice mesh.
    """
    if allreduce_grad_dtype is None and grad_reduce is None:
        def global_loss(p):
            loss, aux = local_loss(p)
            return _col.pmean(loss, axis_name), aux

        out = jax.value_and_grad(global_loss, has_aux=True)(params)
        # The gradient all-reduce on this path is AUTODIFF-INSERTED (the
        # psum of replicated-param cotangents behind the loss pmean), so
        # no wrapped collective sees it — book it explicitly at its known
        # size so the ledger reports the step's dominant wire traffic
        # instead of a 4-byte loss pmean (docs/OBSERVABILITY.md).
        from .observability.comm import note as _note
        _note("grad_allreduce_ad", axis_name, out[1])
        return out

    p_local = jax.tree_util.tree_map(
        lambda v: pcast_varying(v, axis_name), params)
    (loss, aux), grads = jax.value_and_grad(local_loss, has_aux=True)(p_local)
    if grad_reduce is not None:
        grads = grad_reduce(grads)
    else:
        grads = compressed_mean(grads, axis_name, allreduce_grad_dtype)
    return (_col.pmean(loss, axis_name), aux), grads


def _accumulated_local_grads(local_loss, params, batch, axis_name, steps):
    """Mean LOCAL loss/grads over ``steps`` microbatches via ``lax.scan``.

    Each microbatch's backward runs with only its own activations live
    (O(B/steps) instead of O(B)); gradients accumulate in fp32.  Returned
    grads are still per-rank local (varying) — the caller owns the one wire
    collective, exactly like the compressed path of
    :func:`_value_and_global_grads`.  ``local_loss(p, microbatch)`` must
    return ``(loss, aux)``; aux is averaged over microbatches.
    """
    import jax.numpy as jnp

    from .ops.collective import zeros_like_vma

    b_local = jax.tree_util.tree_leaves(batch)[0].shape[0]
    if b_local % steps:
        raise ValueError(
            f"per-rank batch {b_local} not divisible by "
            f"grad_accum_steps {steps}")
    micro = jax.tree_util.tree_map(
        lambda x: x.reshape((steps, x.shape[0] // steps) + x.shape[1:]), batch)
    p_local = jax.tree_util.tree_map(
        lambda v: pcast_varying(v, axis_name), params)
    any_leaf = jax.tree_util.tree_leaves(p_local)[0]

    def acc(carry, mb):
        g_acc, l_acc = carry
        (l, aux), g = jax.value_and_grad(local_loss, has_aux=True)(p_local, mb)
        g_acc = jax.tree_util.tree_map(
            lambda a, gg: a + gg.astype(jnp.float32), g_acc, g)
        return (g_acc, l_acc + l), aux

    g0 = jax.tree_util.tree_map(
        lambda v: zeros_like_vma(v, jnp.float32), p_local)
    l0 = zeros_like_vma(any_leaf, jnp.float32, ())
    (g_sum, l_sum), aux_stack = jax.lax.scan(acc, (g0, l0), micro)
    grads = jax.tree_util.tree_map(lambda g: g / steps, g_sum)
    aux = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.float32).mean(0), aux_stack)
    return (l_sum / steps, aux), grads


def make_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    axis_name: str = DEFAULT_AXIS_NAME,
    has_aux: bool = False,
    donate: bool = True,
    allreduce_grad_dtype=None,
    grad_reduce: Optional[Callable] = None,
    grad_accum_steps: int = 1,
    error_feedback: bool = False,
):
    """Build ``step(params, opt_state, batch) -> (params, opt_state, loss[, aux])``.

    ``loss_fn(params, local_batch)`` returns the mean loss over the *local*
    batch (plus an aux pytree when ``has_aux``).  ``batch`` leaves carry the
    global batch on their leading axis, sharded across ``axis_name``;
    ``params``/``opt_state`` are replicated.  ``optimizer`` should come from
    :func:`chainermn_tpu.optimizers.create_multi_node_optimizer`, whose
    in-jit pmean makes per-shard gradients globally correct.

    ``allreduce_grad_dtype`` (e.g. ``'bfloat16'``) is the reference's
    compressed-allreduce knob (``pure_nccl_communicator.py ::
    allreduce_grad_dtype`` [uv]): the cross-rank gradient mean — the step's
    dominant communication — runs in that dtype on the wire, halving ICI/DCN
    gradient bytes for bf16, with params and the optimizer update staying at
    full precision.  ``'int8'`` runs the block-scaled quantized ring
    (~1 byte/element; see ``ops.collective.quantized_ring_pmean``).

    ``error_feedback=True`` (int8 wire + an optimizer built with the same
    flag): the optimizer transform owns the wire collective — local
    gradients flow to it uncorrected and its :class:`~chainermn_tpu
    .optimizers.ErrorFeedbackState` residual rows shard per rank, so the
    step binding derives per-leaf opt-state specs from the state's
    structure (``opt_state_partition_specs``) at first call.  One
    compiled program per opt-state STRUCTURE — value variants reuse it
    (the ``train.quantized_step`` analysis entry point pins this).

    ``grad_accum_steps > 1`` splits each rank's local batch into that many
    microbatches and accumulates their gradients in fp32 via ``lax.scan``
    before the ONE cross-rank mean and optimizer update — activation memory
    drops by the factor while the wire traffic per update is unchanged
    (beyond-reference: large effective batches on small HBM).
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)

    if grad_accum_steps < 1:
        raise ValueError(f"grad_accum_steps must be >= 1, got {grad_accum_steps}")
    if error_feedback and grad_reduce is not None:
        raise ValueError("error_feedback=True and grad_reduce are exclusive "
                         "(the optimizer owns the wire collective under EF)")
    # Under EF the builder must NOT pre-reduce: the optimizer's EF
    # transform is the one wire collective (it needs the still-local
    # grads to quantize WITH the residual correction).
    builder_reduce = (lambda g: g) if error_feedback else grad_reduce

    def spmd(params, opt_state, batch):
        def local_loss(p, b):
            out = loss_fn(p, b)
            if has_aux:
                return out
            return out, None

        if grad_accum_steps == 1:
            (loss, aux), grads = _value_and_global_grads(
                lambda p: local_loss(p, batch), params, axis_name,
                allreduce_grad_dtype, builder_reduce)
        else:
            (loss, aux), grads = _accumulated_local_grads(
                local_loss, params, batch, axis_name, grad_accum_steps)
            if builder_reduce is not None:
                grads = builder_reduce(grads)
            else:
                grads = compressed_mean(grads, axis_name, allreduce_grad_dtype)
            loss = _col.pmean(loss, axis_name)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if has_aux:
            aux = _col.pmean(aux, axis_name)
            return params, opt_state, loss, aux
        return params, opt_state, loss

    if not error_feedback:
        out_specs = (P(), P(), P(), P()) if has_aux else (P(), P(), P())
        smapped = shard_map(
            spmd,
            mesh=mesh,
            in_specs=(P(), P(), P(axis_name)),
            out_specs=out_specs,
        )
        return jax.jit(smapped, donate_argnums=(0, 1) if donate else ())

    from .optimizers import opt_state_partition_specs

    # EF residual leaves shard per rank, so the opt-state specs depend on
    # the state's pytree STRUCTURE — bind shard_map lazily, one compiled
    # program per structure (value variants share it; jit caches by the
    # inner function identity held in `programs`).
    programs = {}

    def step(params, opt_state, batch):
        key = jax.tree_util.tree_structure(opt_state)
        fn = programs.get(key)
        if fn is None:
            ospecs = opt_state_partition_specs(opt_state, axis_name)
            out_specs = ((P(), ospecs, P(), P()) if has_aux
                         else (P(), ospecs, P()))
            smapped = shard_map(
                spmd, mesh=mesh,
                in_specs=(P(), ospecs, P(axis_name)),
                out_specs=out_specs)
            fn = jax.jit(smapped, donate_argnums=(0, 1) if donate else ())
            programs[key] = fn
        return fn(params, opt_state, batch)

    step._programs = programs  # the recompile probes read through this
    step._cache_size = lambda: sum(
        f._cache_size() for f in programs.values())
    return step


def make_flax_train_step(
    model,
    loss_and_metrics: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    axis_name: str = DEFAULT_AXIS_NAME,
    donate: bool = True,
    allreduce_grad_dtype=None,
    grad_reduce: Optional[Callable] = None,
    preprocess: Optional[Callable] = None,
):
    """Train step for flax modules with mutable ``batch_stats`` (BatchNorm).

    ``loss_and_metrics(logits, batch) -> (loss, metrics)`` over the local
    shard.  Returns ``step(variables, opt_state, batch) -> (variables,
    opt_state, loss, metrics)`` where ``variables = {'params': ...,
    'batch_stats': ...}``.  Running BN statistics are pmean-synced across
    ranks every step, the TPU analog of the reference's
    ``AllreducePersistent`` keeping eval-time BN consistent
    (extensions/allreduce_persistent.py [uv]) — but continuously, not as a
    pre-eval extension.

    ``grad_reduce``: custom wire collective replacing the default pmean —
    e.g. ``ops.collective.hierarchical_pmean`` for the two-tier ICI×DCN
    mean over a multislice mesh (see :func:`_value_and_global_grads`).

    ``preprocess(batch) -> batch`` runs INSIDE the jitted step, on the
    local shard, before the model sees it — the TPU-first input contract:
    upload the network's compact form (e.g. uint8 pixels, 4× fewer
    host→device bytes than float32) and cast/normalize on device, where
    XLA fuses it into the first conv's prologue.  The reference did the
    equivalent transform on CPU inside its iterator workers
    (SURVEY.md §2.9 ImageNet example); on TPU host-side float conversion
    would quadruple PCIe/DCN ingest bytes for zero benefit.
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)

    def spmd(variables, opt_state, batch):
        if preprocess is not None:
            batch = preprocess(batch)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})

        def local_loss(p):
            out, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats},
                batch[0], train=True, mutable=["batch_stats"])
            loss, metrics = loss_and_metrics(out, batch)
            return loss, (mutated, metrics)

        (loss, (mutated, metrics)), grads = _value_and_global_grads(
            local_loss, params, axis_name, allreduce_grad_dtype,
            grad_reduce=grad_reduce)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        new_stats = _col.pmean(mutated["batch_stats"], axis_name)
        metrics = _col.pmean(metrics, axis_name)
        return ({"params": params, "batch_stats": new_stats},
                opt_state, loss, metrics)

    smapped = shard_map(
        spmd, mesh=mesh,
        in_specs=(P(), P(), P(axis_name)),
        out_specs=(P(), P(), P(), P()),
    )
    return jax.jit(smapped, donate_argnums=(0, 1) if donate else ())


def replicate(tree, mesh: Optional[Mesh] = None):
    """Place a pytree replicated over the mesh (params/opt_state)."""
    if mesh is None:
        mesh = make_mesh()
    return jax.device_put(tree, NamedSharding(mesh, P()))


def shard_batch(batch, mesh: Optional[Mesh] = None, axis_name: str = DEFAULT_AXIS_NAME):
    """Shard a host batch's leading axis across the mesh (rank-major).

    Single-controller face: every process holds the FULL global batch.
    Under multi-controller (one process per host), use
    :func:`shard_batch_local` instead — each host only loads its own rows.
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), batch)


def _ring_mean(g, axis_name: str, world: int):
    """Cross-rank gradient mean as an EXPLICIT ring decomposition —
    ``all_gather(reduce_scatter(g)/P)`` when the leading dim divides by
    the world size, ``psum(g)/P`` otherwise.  Identical math to ``pmean``
    (an all-reduce IS reduce-scatter + all-gather), spelled out through
    the accounted collective face so a traced run books each wire leg
    separately — the demo/smoke path of ``python -m chainermn_tpu.train``.
    """
    if world > 1 and getattr(g, "ndim", 0) >= 1 and g.shape[0] % world == 0:
        return _col.all_gather(
            _col.reduce_scatter(g, axis_name) / world, axis_name)
    return _col.psum(g, axis_name) / world


def make_demo_step(optimizer, mesh: Optional[Mesh] = None,
                   axis_name: str = DEFAULT_AXIS_NAME):
    """Tiny-MLP classification step for the CLI smoke run.

    ``step(state, batch) -> (state, observation)`` with ``state =
    (params, opt_state)`` — the :class:`training.updaters.StandardUpdater`
    contract.  Differentiates the LOCAL loss under ``check_vma=False``
    (no autodiff-inserted cross-rank psum) so the hand-rolled
    :func:`_ring_mean` is the one wire collective, and reduces the
    metrics with accounted ``psum`` — a traced run therefore records
    byte/call counters for ``psum``, ``all_gather`` AND
    ``reduce_scatter``.
    """
    import jax.numpy as jnp

    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    world = mesh.devices.size

    def spmd(state, batch):
        params, opt_state = state
        x, y = batch

        def local_loss(p):
            h = jnp.tanh(x @ p["w1"] + p["b1"])
            logits = h @ p["w2"] + p["b2"]
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
            correct = (logits.argmax(-1) == y).sum()
            return nll, correct

        (loss, correct), grads = jax.value_and_grad(
            local_loss, has_aux=True)(params)
        grads = jax.tree_util.tree_map(
            lambda g: _ring_mean(g, axis_name, world), grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        observation = {
            "main/loss": _col.psum(loss, axis_name) / world,
            "main/accuracy": (_col.psum(correct, axis_name)
                              / (x.shape[0] * world)),
        }
        return (params, opt_state), observation

    smapped = shard_map(
        spmd, mesh=mesh,
        in_specs=(P(), P(axis_name)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(smapped)


def main(argv=None) -> int:
    """``python -m chainermn_tpu.train``: a tiny self-contained training
    run wired through the whole observability stack — Trainer +
    StandardUpdater phase spans, collective accounting (psum /
    all_gather / reduce_scatter), step-time breakdown, and a
    ``--trace-out`` Chrome-trace artifact loadable in Perfetto.  Doubles
    as the CI smoke invocation (tests/test_observability.py).
    """
    import argparse
    import json
    import os

    parser = argparse.ArgumentParser(
        description="chainermn_tpu demo trainer + observability smoke")
    parser.add_argument("--devices", type=int, default=0,
                        help="fake an N-device CPU mesh (0 = real chips)")
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--batchsize", type=int, default=64,
                        help="GLOBAL batch (split across the mesh)")
    parser.add_argument("--hidden", type=int, default=64)
    parser.add_argument("--lr", type=float, default=1e-2)
    parser.add_argument("--n-train", type=int, default=512)
    parser.add_argument("--log-every", type=int, default=10)
    parser.add_argument("--out", default="result")
    parser.add_argument("--trace-out", default=None,
                        help="write a Chrome-trace/Perfetto JSON here "
                             "(also enables tracing); under "
                             "multi-controller each process writes a "
                             "rank shard and process 0 merges them into "
                             "this path (one Perfetto lane per rank)")
    parser.add_argument("--metrics-out", default=None,
                        help="append a versioned JSONL metrics stream "
                             "here (also enables tracing); a Prometheus "
                             "textfile lands next to it at <path>.prom "
                             "and the cross-rank skew report is appended "
                             "at exit")
    parser.add_argument("--watchdog-timeout", type=float, default=1800.0)
    parser.add_argument("--prefetch", action="store_true",
                        help="double-buffered host->device input "
                             "prefetch: batch k+1 assembles on a "
                             "background thread while step k runs "
                             "(exact-resume safe; see docs/ROBUSTNESS.md)")
    parser.add_argument("--checkpoint-dir", default=None,
                        help="enable v2 manifest checkpoints here "
                             "(periodic saves every --checkpoint-every "
                             "iters + auto-resume, including ELASTIC "
                             "resume from a different world size)")
    parser.add_argument("--checkpoint-every", type=int, default=5)
    parser.add_argument("--preemption-grace-s", type=float, default=None,
                        help="treat SIGTERM as a scheduler preemption: "
                             "final async checkpoint + flight bundle + "
                             "exit 0, all within this grace budget "
                             "(requires --checkpoint-dir for the save)")
    parser.add_argument("--self-heal", action="store_true",
                        help="run the rank health plane (ISSUE 13): a "
                             "per-rank heartbeat lease over the KV side "
                             "channel, a collective watchdog that NAMES "
                             "a lost rank instead of hanging, and the "
                             "gang_health /statusz provider; hand-rolled "
                             "loops add live shrink via "
                             "SelfHealingGang.heal() — see "
                             "docs/ROBUSTNESS.md 'Training failure "
                             "domains'")
    parser.add_argument("--self-heal-min-world", type=int, default=1,
                        help="live-shrink floor: below this many "
                             "survivors heal() refuses and the job falls "
                             "back to the PR 8 checkpoint restart")
    parser.add_argument("--self-heal-beat-s", type=float, default=0.05,
                        help="heartbeat interval; detection window is "
                             "beat * (miss_beats + 1) with miss_beats=4")
    parser.add_argument("--statusz-port", type=int, default=None,
                        help="live introspection HTTP server (/statusz "
                             "/metricsz /requestz /debugz) on this port; "
                             "0 picks a free port (printed to stderr)")
    parser.add_argument("--flight-dump-dir", default=None,
                        help="crash-bundle directory for the flight "
                             "recorder (SIGTERM/SIGUSR1/uncaught "
                             "exception/Watchdog dumps land here; "
                             "defaults to --out when --statusz-port or "
                             "an observability sink is active)")
    args = parser.parse_args(argv)

    if args.devices:
        jax.config.update("jax_platforms", "cpu")
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.devices}")

    import numpy as np

    # Local imports: chainermn_tpu's package face (circular at module
    # scope — train.py IS part of the package).
    from . import observability as obs
    from .communicators import create_communicator
    from .extensions.observation_aggregator import ObservationAggregator
    from .extensions.watchdog import Watchdog
    from .iterators import SerialIterator
    from .training.extensions import LogReport, PrintReport
    from .training.trainer import PRIORITY_EDITOR, Trainer
    from .training.updaters import StandardUpdater

    if args.trace_out or args.metrics_out:
        obs.enable()
    # flight recorder: bounded ring, always teed; crash bundles go to
    # --flight-dump-dir (explicit) or --out once any sink is active
    obs.install_tracer_tee()
    dump_dir = args.flight_dump_dir
    if dump_dir is None and (args.trace_out or args.metrics_out
                             or args.statusz_port is not None):
        dump_dir = args.out
    if dump_dir:
        from .global_except_hook import add_hook
        obs.install_signal_handlers(dump_dir)
        add_hook()

    comm = create_communicator("xla")
    mesh = comm.mesh
    world = comm.size
    # Rank-sharded artifact mode: one controller process per host means
    # per-PROCESS shards; single-controller writes plain files.
    multi = jax.process_count() > 1
    rank = jax.process_index() if multi else None
    if args.batchsize % world:
        raise SystemExit(
            f"--batchsize {args.batchsize} must divide by the {world}-chip mesh")

    # Learnable synthetic task (labels are a fixed linear map of the
    # inputs — same recipe as examples/mnist).
    in_dim, n_classes = 32, 10
    w_true = np.random.RandomState(42).randn(in_dim, n_classes)
    xs = np.random.RandomState(0).randn(args.n_train, in_dim).astype(np.float32)
    ys = (xs @ w_true).argmax(-1).astype(np.int32)
    dataset = list(zip(xs, ys))

    import optax as _optax

    rng = np.random.RandomState(1)
    params = {
        "w1": (rng.randn(in_dim, args.hidden) / np.sqrt(in_dim)
               ).astype(np.float32),
        "b1": np.zeros((args.hidden,), np.float32),
        "w2": (rng.randn(args.hidden, n_classes) / np.sqrt(args.hidden)
               ).astype(np.float32),
        "b2": np.zeros((n_classes,), np.float32),
    }
    optimizer = _optax.sgd(args.lr, momentum=0.9)
    step = make_demo_step(optimizer, mesh=mesh)
    state = replicate((params, optimizer.init(params)), mesh)

    updater = StandardUpdater(
        SerialIterator(dataset, args.batchsize, seed=0), step, state,
        mesh=mesh, prefetch=args.prefetch)
    trainer = Trainer(updater, (args.steps, "iteration"), out=args.out)
    trainer.extend(ObservationAggregator(comm), trigger=(1, "iteration"),
                   priority=PRIORITY_EDITOR)
    trainer.extend(obs.StepBreakdownReport(items_per_step=args.batchsize))
    monitor = None
    if args.trace_out or args.metrics_out:
        monitor = obs.HealthMonitor()
        trainer.extend(monitor)
    metrics_path = None
    if args.metrics_out:
        metrics_path = (obs.shard_path(args.metrics_out, rank)
                        if rank is not None else args.metrics_out)
        trainer.extend(obs.MetricsReport(
            metrics_path, prometheus_path=metrics_path + ".prom",
            monitor=monitor, rank=rank))
    # goodput attribution for the TRAIN loop: fold the updater's phase
    # stamps (data → host, compute → compute) + the extension pass
    # (host) into a ledger surfaced via /statusz and the final result
    goodput = obs.GoodputLedger()

    class _GoodputFold:
        trigger = (1, "iteration")
        priority = 331  # right after MetricsReport's 330 slot

        def observe(self, tr) -> None:
            phases = getattr(tr.updater, "phase_times", None) or {}
            goodput.add("host", phases.get("data", 0.0))
            goodput.add("compute", phases.get("compute", 0.0))
            ext = getattr(tr, "last_extension_time", None)
            if ext:
                goodput.add("host", ext)

        def __call__(self, tr) -> None:
            pass

        def state_dict(self):
            return {}

        def load_state_dict(self, state):
            pass

    trainer.extend(_GoodputFold(), name="goodput_fold")
    obs.register_provider("train", lambda: {
        "iteration": trainer.iteration,
        "last_phase": trainer.last_phase,
        "elapsed_time": trainer.elapsed_time,
        "goodput": goodput.report(),
    })
    statusz = None
    if args.statusz_port is not None:
        statusz = obs.start_status_server(
            args.statusz_port, dump_dir=dump_dir, rank=rank)
    log = LogReport(trigger=(args.log_every, "iteration"))
    trainer.extend(log)
    trainer.extend(PrintReport(
        ["iteration", "main/loss", "main/accuracy", "time/data",
         "time/compute", "comm/bytes", "throughput/items_per_sec"],
        log, trigger=(args.log_every, "iteration")))
    trainer.extend(Watchdog(timeout=args.watchdog_timeout,
                            dump_dir=args.out, monitor=monitor, rank=rank))
    # Elastic checkpointing + preemption (ISSUE 8, docs/ROBUSTNESS.md):
    # v2 manifest checkpoints resume across WORLD-SIZE changes; SIGTERM
    # inside the grace budget saves a final generation, books the save
    # into the goodput ledger's `checkpoint` bucket, dumps a `preempt`
    # bundle, and exits 0.
    checkpointer = None
    if args.checkpoint_dir:
        from .extensions.checkpoint import create_multi_node_checkpointer
        checkpointer = create_multi_node_checkpointer(
            "train", comm, cp_interval=args.checkpoint_every,
            path=args.checkpoint_dir)
        trainer.extend(checkpointer,
                       trigger=(args.checkpoint_every, "iteration"))
        loaded, it_resumed = checkpointer.maybe_load()
        if it_resumed is not None:
            trainer.load_checkpoint_state(loaded)
            print(f"[chainermn_tpu train] resumed from generation "
                  f"{it_resumed} in {args.checkpoint_dir}",
                  file=__import__("sys").stderr, flush=True)
    if args.preemption_grace_s is not None:
        from .extensions.preemption import PreemptionHandler
        # installed AFTER the flight handlers: SIGTERM now means
        # checkpoint-and-exit-0, SIGUSR1 stays dump-and-continue
        preempt = PreemptionHandler(
            checkpointer, grace_s=args.preemption_grace_s,
            dump_dir=dump_dir or args.out, ledger=goodput, rank=rank)
        trainer.extend(preempt)
    # Self-healing plane (ISSUE 13): heartbeat lease per rank over the
    # communicator's KV side channel + the collective watchdog threaded
    # through the accounted face — a rank death during any eager
    # collective aborts loudly NAMING the lost rank(s) (exit 44, with a
    # `rank_lost` bundle) instead of wedging the gang.  The min-world
    # floor is recorded so operators (and heal() callers) know where
    # live shrink hands back to the PR 8 checkpoint restart.
    gang = None
    if args.self_heal:
        from .extensions.gang import SelfHealingGang
        gang = SelfHealingGang(
            comm.gang_lease_store(),
            rank=jax.process_index(), world=jax.process_count(),
            name="train", beat_interval_s=args.self_heal_beat_s,
            min_world=args.self_heal_min_world,
            dump_dir=dump_dir or args.out)
        gang.start()
        # join barrier BEFORE arming any detector: gang processes boot
        # with arbitrary skew, and a peer that has not started yet must
        # not read as a death (the guard would exit-44 a healthy gang)
        gang.wait_for_members(timeout_s=120.0)
        # the guard bound tracks the GANG's op bound (4× the lease
        # window, ≥ 5 s), floored at 30 s so a legitimately slow eager
        # object collective (blocking KV get on a busy peer) is not
        # mistaken for a death — NOT the step watchdog's budget, which
        # would delay naming a dead rank by many minutes.  Sub-second
        # death detection itself comes from the lease window.
        gang.install_collective_guard(
            timeout_s=max(gang.op_timeout_s, 30.0))
    try:
        trainer.run()
    finally:
        if gang is not None:
            gang.stop()
    updater.close()  # stop the prefetch thread (no-op when not prefetching)

    final = log.log[-1] if log.log else {}
    result = {
        "steps": trainer.iteration,
        "world": world,
        "final_loss": final.get("main/loss"),
        "final_accuracy": final.get("main/accuracy"),
        "goodput": goodput.report(),
    }
    if gang is not None:
        st = gang.stats()
        result["self_heal"] = {
            k: st[k] for k in (
                "epoch", "world", "min_world", "detection_window_s",
                "rank_lost_events", "reconfigs", "fenced_refusals")}
    if statusz is not None:
        result["statusz_port"] = statusz.port
        statusz.stop()
    if args.trace_out:
        obs.export_chrome_trace(args.trace_out, rank=rank)
        result["trace_out"] = (args.trace_out if rank is None
                               else obs.shard_path(args.trace_out, rank))
        result["trace_events"] = len(obs.get_tracer().events())
        result["comm_totals"] = {
            k: {kk: vv for kk, vv in v.items() if kk != "host_time_s"}
            for k, v in obs.comm_report()["per_op"].items()}
        if multi:
            # barrier: every shard on disk before process 0 merges them
            comm.allgather_obj("trace-exported")
            if jax.process_index() == 0:
                merged = obs.merge_trace_shards(
                    args.trace_out, out_path=args.trace_out,
                    expected_ranks=jax.process_count())
                result["merged_trace"] = args.trace_out
                result["merged_ranks"] = merged["metadata"]["merged_ranks"]
    if args.trace_out or args.metrics_out:
        # Cross-rank skew report: collective over the DCN object lane.
        skew = obs.cross_rank_report(comm)
        result["straggler_rank"] = skew["straggler_rank"]
        result["step_time_skew"] = {
            k: round(v, 6) for k, v in skew["step_time"].items()
            if k != "per_rank"}
        if metrics_path and (rank is None or rank == 0):
            w = obs.MetricsWriter(metrics_path, rank=rank)
            w.write(skew, kind="skew_report")
            w.close()
    print(json.dumps(result))
    return 0


def shard_batch_local(local_batch, mesh: Optional[Mesh] = None,
                      axis_name: str = DEFAULT_AXIS_NAME):
    """Assemble a globally-sharded batch from per-process LOCAL rows.

    The multi-controller input path (reference analog: each MPI rank feeds
    its own ``scatter_dataset`` shard straight to its GPU — SURVEY.md §3.4):
    each process passes only the rows its own devices will hold (e.g. the
    output of ``scatter_dataset(...)`` + a local iterator), and the result
    is one global jax.Array whose leading axis is the concatenation over
    processes, without any cross-host data movement.

    Works single-process too (where it equals :func:`shard_batch`), so the
    same input code runs on a laptop mesh and a pod.
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(sharding, x),
        local_batch)


if __name__ == "__main__":
    raise SystemExit(main())
