"""Shared baseline plumbing for the three analyzer CLIs.

The AST lint (``cli.py`` / ``.spmd-lint-baseline.json``), the shard-flow
analyzer (``shardflow.py`` / ``.shardflow-baseline.json``), and the
concurrency lint (``concurrency.py`` / ``.concurrency-baseline.json``)
all speak the same baseline dialect — findings accepted by fingerprint,
``--fix-baseline`` regeneration that preserves human comments and
carries over entries outside the invocation's scope, unreadable
baselines = exit 2.  Before ISSUE 15 that logic existed as three
drifting copies; this module is the ONE implementation (the semantics
are tested once in tests/test_concurrency_lint.py::TestBaselineGate and
shared everywhere).

Pure stdlib — importable without jax, like the rest of the findings
machinery.
"""

from __future__ import annotations

import os
import sys
from typing import Callable, Iterable, List, Optional, Tuple

from .findings import Baseline, Finding, find_baseline, load_baseline

#: an entry predicate for --fix-baseline scope-carrying: True = the
#: entry WAS in this invocation's scope (so absence from the fresh
#: findings means it is gone for real and must be dropped); False = the
#: entry was not re-checked and carries over untouched.
InScope = Callable[[dict], bool]


class BaselineGate:
    """One analyzer run's view of its baseline file.

    ``path`` is the resolved baseline path (may be None: no baseline
    found and none requested).  ``load()`` parses it; a broken file
    returns an error string — the caller's exit-2 condition.
    """

    def __init__(self, path: Optional[str], enabled: bool = True):
        self.path = path
        self.enabled = bool(enabled)
        self.baseline: Optional[Baseline] = None

    @staticmethod
    def resolve(explicit: Optional[str], search_start: str,
                filename: str, enabled: bool = True) -> "BaselineGate":
        """The common discovery dance: an explicit ``--baseline`` path
        wins, else the nearest ``filename`` at or above
        ``search_start``."""
        path = explicit or find_baseline(search_start, filename=filename)
        return BaselineGate(path, enabled=enabled)

    def load(self) -> Optional[str]:
        """Load the baseline if enabled and present.  Returns an error
        message when the file exists but is unreadable (exit 2), else
        None."""
        if not self.enabled or not self.path \
                or not os.path.exists(self.path):
            return None
        try:
            self.baseline = load_baseline(self.path)
        except (OSError, ValueError, KeyError) as e:
            return f"unreadable baseline {self.path}: {e}"
        return None

    def filter(self, findings: Iterable[Finding]
               ) -> Tuple[List[Finding], List[Finding]]:
        """Split into (new, accepted) — identity when no baseline."""
        findings = list(findings)
        if self.baseline is None:
            return findings, []
        return self.baseline.filter(findings)

    def fix(self, findings: Iterable[Finding], *,
            default_target: str,
            in_scope: Optional[InScope] = None,
            out=sys.stderr) -> str:
        """``--fix-baseline``: regenerate from the current findings.

        Semantics (identical across all three CLIs, tested once):

        * human-written comments on surviving entries are preserved;
        * entries ``in_scope`` says were NOT re-checked by this
          invocation (path not scanned, rule filtered out, entry point
          not selected) carry over untouched — a partial regen must
          never wipe another scope's keepers;
        * the file is written atomically (tmp + rename).

        Returns the written path.
        """
        target = self.path or default_target
        new_bl = Baseline.from_findings(findings, path=target)
        carried = 0
        if self.baseline is not None:
            for fp, e in self.baseline.entries.items():
                if in_scope is not None and not in_scope(e) \
                        and fp not in new_bl.entries:
                    new_bl.entries[fp] = dict(e)
                    carried += 1
            new_bl.merge_comments_from(self.baseline)
        new_bl.save(target)
        extra = f", {carried} out-of-scope carried over" if carried else ""
        print(f"baseline written: {target} ({len(new_bl.entries)} "
              f"accepted findings{extra})", file=out)
        return target
