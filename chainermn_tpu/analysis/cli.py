"""Runner: ``python -m chainermn_tpu.analysis`` / ``scripts/lint_spmd.py``.

Exit-code contract (same as ``scripts/check_perf_regression.py``):

* **0** — clean: no findings beyond the checked-in baseline;
* **1** — findings: at least one non-baselined finding (any severity);
* **2** — unusable: bad arguments, missing paths, broken baseline.

Human output is one block per finding (``path:line: severity: rule
[scope]: message``); ``--json`` emits a single machine document
(``chainermn_tpu.spmd_lint.v1``) with the findings, the baseline-accepted
count, and the per-entry-point collective sequences from the jaxpr engine.

``--fix-baseline`` regenerates the baseline from the current findings —
the INTENTIONAL way to accept a triaged finding; human-written comments
on surviving entries are preserved.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .ast_engine import AST_RULES, analyze_paths
from .baseline import BaselineGate
from .concurrency import (CONCURRENCY_BASELINE_FILENAME,
                          CONCURRENCY_RULES)
from .concurrency import analyze_paths as analyze_concurrency
from .findings import BASELINE_FILENAME, Finding, find_baseline
from .registry import default_registry

SCHEMA = "chainermn_tpu.spmd_lint.v1"

#: ``--rules concurrency`` selects the whole lock-discipline family.
RULE_FAMILIES = {"concurrency": tuple(sorted(CONCURRENCY_RULES))}


def _all_rules():
    from .jaxpr_engine import JAXPR_RULES
    out = dict(AST_RULES)
    out.update(JAXPR_RULES)
    out.update(CONCURRENCY_RULES)
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m chainermn_tpu.analysis",
        description="SPMD-aware static analyzer: collective-deadlock, "
                    "PRNG, host-aliasing, and recompilation lint for "
                    "JAX code (docs/ANALYSIS.md).  With --gate, runs "
                    "EVERY analysis plane (lint + protocol models + "
                    "shardflow + schedule verifier) as one CI check "
                    "(see --gate --help)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to scan (default: the "
                        "chainermn_tpu package directory)")
    p.add_argument("--json", action="store_true",
                   help="one machine-readable JSON document on stdout")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: nearest "
                        f"{BASELINE_FILENAME} above the first path)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline: report everything")
    p.add_argument("--fix-baseline", action="store_true",
                   help="regenerate the baseline from current findings "
                        "(intentional acceptance; keeps existing comments)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset to run")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--no-jaxpr", action="store_true",
                   help="skip the jaxpr engine (no jax import: pure-AST "
                        "mode, runs on any box)")
    p.add_argument("--entry", action="append", default=None,
                   metavar="NAME",
                   help="run the jaxpr checks on ONE registered entry "
                        "point (repeatable; default: all) — iterate on "
                        "a single subsystem without paying the whole "
                        "sweep")
    return p


def _package_dir() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


#: the ``--gate`` stages, in run order: each is (name, thunk returning
#: an exit code under the same 0/1/2 contract).  ``calibration``
#: (ISSUE 20) drift-checks the measured cost-model fit against fresh
#: schedule_exec records and exits 0 ("skipped") until any exist.
GATE_STAGES = ("lint", "protocol", "shardflow", "schedules",
               "calibration")


def gate_main(argv: Optional[List[str]] = None) -> int:
    """``python -m chainermn_tpu.analysis --gate`` — ONE CI-callable
    check running every analysis plane: the SPMD+concurrency lint, the
    protocol model checker, the shardflow statics reconciliation, the
    collective schedule verifier, and the cost-model calibration drift
    check.  Exit is the worst stage under the shared contract: 0
    clean, 1 findings/violations, 2 unusable.
    """
    p = argparse.ArgumentParser(
        prog="python -m chainermn_tpu.analysis --gate",
        description="run all analysis gates "
                    f"({', '.join(GATE_STAGES)}) and exit 0/1/2")
    p.add_argument("--stages", default=",".join(GATE_STAGES),
                   help="comma-separated stage subset, in run order")
    p.add_argument("--json", action="store_true",
                   help="one machine-readable summary document on "
                        "stdout (stage output goes to stderr)")
    args = p.parse_args(argv)
    stages = [s.strip() for s in args.stages.split(",") if s.strip()]
    unknown = set(stages) - set(GATE_STAGES)
    if unknown:
        print(f"error: unknown stage(s): {', '.join(sorted(unknown))} "
              f"(have {', '.join(GATE_STAGES)})", file=sys.stderr)
        return 2

    def run_stage(name: str) -> int:
        if name == "lint":
            return main([])
        if name == "protocol":
            from .protocol import main as protocol_main
            return protocol_main([])
        if name == "shardflow":
            from .shardflow import main as shardflow_main
            return shardflow_main([])
        if name == "calibration":
            from .calibrate import main as calibrate_main
            return calibrate_main(["--gate"])
        from .schedule_check import main as schedule_main
        return schedule_main([])

    import contextlib

    rcs = {}
    for name in stages:
        print(f"=== gate stage: {name} ===",
              file=sys.stderr if args.json else sys.stdout)
        try:
            if args.json:
                with contextlib.redirect_stdout(sys.stderr):
                    rcs[name] = run_stage(name)
            else:
                rcs[name] = run_stage(name)
        except SystemExit as e:  # stage argparse bail-outs
            rcs[name] = int(e.code or 0)
        except Exception as e:
            print(f"gate stage {name} crashed: {e!r}", file=sys.stderr)
            rcs[name] = 2
    worst = max(rcs.values(), default=0)
    if args.json:
        print(json.dumps({"schema": "chainermn_tpu.analysis_gate.v1",
                          "stages": rcs, "exit": worst}, indent=2,
                         sort_keys=True))
    else:
        tally = ", ".join(f"{k}={v}" for k, v in rcs.items())
        print(f"analysis-gate: {tally} -> exit {worst}",
              file=sys.stderr)
    return worst


def main(argv: Optional[List[str]] = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if "--gate" in argv:
        rest = [a for a in argv if a != "--gate"]
        return gate_main(rest)
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule, (sev, desc) in sorted(_all_rules().items()):
            print(f"{rule:24s} {sev:8s} {desc}")
        for fam, members in sorted(RULE_FAMILIES.items()):
            print(f"{fam:24s} family   = {', '.join(members)}")
        return 0

    paths = args.paths or [_package_dir()]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"error: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2

    raw_rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
                 if args.rules else None)
    rules: Optional[List[str]] = None
    if raw_rules:
        rules = []
        for r in raw_rules:
            rules.extend(RULE_FAMILIES.get(r, (r,)))
        unknown = set(rules) - set(_all_rules())
        if unknown:
            print(f"error: unknown rule(s): {', '.join(sorted(unknown))} "
                  "(see --list-rules)", file=sys.stderr)
            return 2
    if args.entry and args.no_jaxpr:
        print("error: --entry needs the jaxpr engine (drop --no-jaxpr)",
              file=sys.stderr)
        return 2

    # the concurrency family runs alongside the SPMD rules (own engine,
    # own baseline file); a pure-concurrency --rules selection skips the
    # AST/jaxpr engines entirely
    conc_only = rules is not None and all(
        r in CONCURRENCY_RULES for r in rules)
    run_conc = rules is None or any(r in CONCURRENCY_RULES
                                    for r in rules)

    registry = default_registry()
    findings = ([] if conc_only
                else analyze_paths(paths, registry=registry,
                                   rules=rules))
    conc_findings: List[Finding] = []
    if run_conc:
        conc_findings = analyze_concurrency(paths, rules=rules)
        if not conc_only:
            # both engines parsed the same files: keep the AST
            # engine's parse-error as the canonical one
            conc_findings = [f for f in conc_findings
                             if f.rule != "parse-error"]

    reports = []
    if not args.no_jaxpr and not conc_only:
        try:
            from .jaxpr_engine import check_entrypoints
            eps = None
            if args.entry:
                from .entrypoints import select_entrypoints
                eps, err = select_entrypoints(args.entry)
                if err:
                    print(f"error: {err}", file=sys.stderr)
                    return 2
            jf, reports = check_entrypoints(eps)
            if rules is not None:
                # entrypoint-error bypasses the filter: "this entry point
                # could not be analyzed" must never read as "clean under
                # rule X" (same carve-out as the AST engine's parse-error)
                jf = [f for f in jf
                      if f.rule in rules or f.rule == "entrypoint-error"]
            findings.extend(jf)
        except ImportError as e:
            print(f"note: jaxpr engine skipped (jax unavailable: {e})",
                  file=sys.stderr)

    # ---- normalize paths for stable fingerprints regardless of cwd:
    # anchor at the baseline's directory when it contains every scanned
    # path (the checked-in layout), else at the scanned paths' common
    # ancestor — NEVER at a root that forces "../" segments, which would
    # bake the checkout's absolute location into fingerprints ----
    bl_path = args.baseline or find_baseline(paths[0])
    abs_paths = [os.path.abspath(p) for p in paths]
    common = os.path.commonpath(abs_paths)
    if os.path.isfile(common):
        common = os.path.dirname(common)
    root = common
    if bl_path:
        bl_dir = os.path.dirname(os.path.abspath(bl_path))
        if os.path.commonpath([bl_dir, common]) == bl_dir:
            root = bl_dir
    gate = BaselineGate(bl_path, enabled=not args.no_baseline)
    conc_gate = BaselineGate.resolve(
        None, paths[0], CONCURRENCY_BASELINE_FILENAME,
        enabled=not args.no_baseline)
    # each family anchors its findings at ITS OWN baseline's directory
    # (falling back to the scan root): an `--baseline` redirect of the
    # SPMD file must not re-root the concurrency fingerprints — or a
    # fixture-dir --fix-baseline would resolve the repo keepers'
    # relative paths against the wrong root and wipe them as in-scope
    conc_root = root
    if conc_gate.path:
        cd = os.path.dirname(os.path.abspath(conc_gate.path))
        if os.path.commonpath([cd, common]) == cd:
            conc_root = cd
    for f in findings:
        if f.path and not f.path.startswith("entrypoint:"):
            f.path = os.path.relpath(os.path.abspath(f.path), root)
    for f in conc_findings:
        if f.path:
            f.path = os.path.relpath(os.path.abspath(f.path), conc_root)
    for g in (gate, conc_gate):
        err = g.load()
        if err:
            print(f"error: {err}", file=sys.stderr)
            return 2

    if args.fix_baseline:
        # regeneration is scoped to THIS invocation: entries for paths
        # not scanned, rules filtered out, or entry points not run
        # (--no-jaxpr) are carried over untouched — a partial
        # `--fix-baseline chainermn_tpu/` must not wipe the examples/
        # keepers.  Each family regenerates its OWN baseline file.
        def path_in_scope(entry, anchor) -> bool:
            ap = os.path.normpath(os.path.join(anchor, entry["path"]))
            return any(ap == sp or ap.startswith(sp + os.sep)
                       for sp in abs_paths)

        def in_scope(entry) -> bool:
            p = entry["path"]
            if p.startswith("entrypoint:"):
                if args.entry and p[len("entrypoint:"):] not in args.entry:
                    return False  # --entry: unselected entries carry over
                return not args.no_jaxpr and (
                    rules is None or entry["rule"] in rules
                    or entry["rule"] == "entrypoint-error")
            if rules is not None and entry["rule"] not in rules \
                    and entry["rule"] != "parse-error":
                return False
            return path_in_scope(entry, root)

        def conc_in_scope(entry) -> bool:
            if entry["rule"] == "parse-error" and not conc_only:
                # the combined run dedups parse-errors into the SPMD
                # family (they are stripped from conc_findings above);
                # a parse-error the STANDALONE concurrency runner
                # baselined must carry over, not be wiped as in-scope
                return False
            if rules is not None and entry["rule"] not in rules \
                    and entry["rule"] != "parse-error":
                return False
            return path_in_scope(entry, conc_root)

        if not conc_only:
            gate.fix(findings, in_scope=in_scope,
                     default_target=os.path.join(root,
                                                 BASELINE_FILENAME))
        if run_conc:
            conc_gate.fix(
                conc_findings, in_scope=conc_in_scope,
                default_target=os.path.join(
                    conc_root, CONCURRENCY_BASELINE_FILENAME))
        return 0

    findings, accepted = gate.filter(findings)
    conc_new, conc_accepted = conc_gate.filter(conc_findings)
    findings = sorted(findings + conc_new,
                      key=lambda f: (f.path, f.line, f.rule))
    accepted = accepted + conc_accepted

    if args.json:
        doc = {
            "schema": SCHEMA,
            "paths": [os.path.relpath(os.path.abspath(p), root)
                      for p in paths],
            "baseline": (os.path.relpath(bl_path, root)
                         if bl_path and gate.baseline is not None
                         else None),
            "concurrency_baseline": (
                os.path.relpath(conc_gate.path, root)
                if conc_gate.path and conc_gate.baseline is not None
                else None),
            "n_accepted_by_baseline": len(accepted),
            "findings": [f.to_dict() for f in findings],
            "entrypoints": [
                {"name": r.name,
                 "collectives": [list(c) for c in r.collectives],
                 "n_compiles": r.n_compiles,
                 "error": r.error} for r in reports],
        }
        print(json.dumps(doc, indent=2))
    else:
        for f in findings:
            print(f.render())
        sev = {}
        for f in findings:
            sev[f.severity] = sev.get(f.severity, 0) + 1
        tally = ", ".join(f"{n} {s}" for s, n in sorted(sev.items())) or \
            "no findings"
        extra = (f" ({len(accepted)} accepted by baseline)"
                 if accepted else "")
        print(f"spmd-lint: {tally}{extra} over {len(paths)} path(s)",
              file=sys.stderr)

    return 1 if findings else 0
