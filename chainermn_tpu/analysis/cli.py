"""Runner: ``python -m chainermn_tpu.analysis`` / ``scripts/lint_spmd.py``.

Exit-code contract (same as ``scripts/check_perf_regression.py``):

* **0** — clean: no findings beyond the checked-in baseline;
* **1** — findings: at least one non-baselined finding (any severity);
* **2** — unusable: bad arguments, missing paths, broken baseline.

Human output is one block per finding (``path:line: severity: rule
[scope]: message``); ``--json`` emits a single machine document
(``chainermn_tpu.spmd_lint.v1``) with the findings, the baseline-accepted
count, and the per-entry-point collective sequences from the jaxpr engine.

``--fix-baseline`` regenerates the baseline from the current findings —
the INTENTIONAL way to accept a triaged finding; human-written comments
on surviving entries are preserved.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .ast_engine import AST_RULES, analyze_paths
from .findings import (BASELINE_FILENAME, Baseline, Finding, find_baseline,
                       load_baseline)
from .registry import default_registry

SCHEMA = "chainermn_tpu.spmd_lint.v1"


def _all_rules():
    from .jaxpr_engine import JAXPR_RULES
    out = dict(AST_RULES)
    out.update(JAXPR_RULES)
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m chainermn_tpu.analysis",
        description="SPMD-aware static analyzer: collective-deadlock, "
                    "PRNG, host-aliasing, and recompilation lint for "
                    "JAX code (docs/ANALYSIS.md)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to scan (default: the "
                        "chainermn_tpu package directory)")
    p.add_argument("--json", action="store_true",
                   help="one machine-readable JSON document on stdout")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: nearest "
                        f"{BASELINE_FILENAME} above the first path)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline: report everything")
    p.add_argument("--fix-baseline", action="store_true",
                   help="regenerate the baseline from current findings "
                        "(intentional acceptance; keeps existing comments)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset to run")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--no-jaxpr", action="store_true",
                   help="skip the jaxpr engine (no jax import: pure-AST "
                        "mode, runs on any box)")
    p.add_argument("--entry", action="append", default=None,
                   metavar="NAME",
                   help="run the jaxpr checks on ONE registered entry "
                        "point (repeatable; default: all) — iterate on "
                        "a single subsystem without paying the whole "
                        "sweep")
    return p


def _package_dir() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule, (sev, desc) in sorted(_all_rules().items()):
            print(f"{rule:24s} {sev:8s} {desc}")
        return 0

    paths = args.paths or [_package_dir()]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"error: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    if rules:
        unknown = set(rules) - set(_all_rules())
        if unknown:
            print(f"error: unknown rule(s): {', '.join(sorted(unknown))} "
                  "(see --list-rules)", file=sys.stderr)
            return 2
    if args.entry and args.no_jaxpr:
        print("error: --entry needs the jaxpr engine (drop --no-jaxpr)",
              file=sys.stderr)
        return 2

    registry = default_registry()
    findings = analyze_paths(paths, registry=registry, rules=rules)

    reports = []
    if not args.no_jaxpr:
        try:
            from .jaxpr_engine import check_entrypoints
            eps = None
            if args.entry:
                from .entrypoints import select_entrypoints
                eps, err = select_entrypoints(args.entry)
                if err:
                    print(f"error: {err}", file=sys.stderr)
                    return 2
            jf, reports = check_entrypoints(eps)
            if rules is not None:
                # entrypoint-error bypasses the filter: "this entry point
                # could not be analyzed" must never read as "clean under
                # rule X" (same carve-out as the AST engine's parse-error)
                jf = [f for f in jf
                      if f.rule in rules or f.rule == "entrypoint-error"]
            findings.extend(jf)
        except ImportError as e:
            print(f"note: jaxpr engine skipped (jax unavailable: {e})",
                  file=sys.stderr)

    # ---- normalize paths for stable fingerprints regardless of cwd:
    # anchor at the baseline's directory when it contains every scanned
    # path (the checked-in layout), else at the scanned paths' common
    # ancestor — NEVER at a root that forces "../" segments, which would
    # bake the checkout's absolute location into fingerprints ----
    baseline: Optional[Baseline] = None
    bl_path = args.baseline or find_baseline(paths[0])
    abs_paths = [os.path.abspath(p) for p in paths]
    common = os.path.commonpath(abs_paths)
    if os.path.isfile(common):
        common = os.path.dirname(common)
    root = common
    if bl_path:
        bl_dir = os.path.dirname(os.path.abspath(bl_path))
        if os.path.commonpath([bl_dir, common]) == bl_dir:
            root = bl_dir
    for f in findings:
        if f.path and not f.path.startswith("entrypoint:"):
            f.path = os.path.relpath(os.path.abspath(f.path), root)

    if not args.no_baseline and bl_path and os.path.exists(bl_path):
        try:
            baseline = load_baseline(bl_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"error: unreadable baseline {bl_path}: {e}",
                  file=sys.stderr)
            return 2

    if args.fix_baseline:
        target = bl_path or os.path.join(root, BASELINE_FILENAME)
        new_bl = Baseline.from_findings(findings, path=target)
        carried = 0
        if baseline is not None:
            # regeneration is scoped to THIS invocation: entries for
            # paths not scanned, rules filtered out, or entry points not
            # run (--no-jaxpr) are carried over untouched — a partial
            # `--fix-baseline chainermn_tpu/` must not wipe the
            # examples/ keepers
            def in_scope(entry) -> bool:
                p = entry["path"]
                if p.startswith("entrypoint:"):
                    if args.entry and p[len("entrypoint:"):] not in args.entry:
                        return False  # --entry: unselected entries carry over
                    return not args.no_jaxpr and (
                        rules is None or entry["rule"] in rules
                        or entry["rule"] == "entrypoint-error")
                if rules is not None and entry["rule"] not in rules \
                        and entry["rule"] != "parse-error":
                    return False
                ap = os.path.normpath(os.path.join(root, p))
                return any(ap == sp or ap.startswith(sp + os.sep)
                           for sp in abs_paths)

            for fp, e in baseline.entries.items():
                if not in_scope(e) and fp not in new_bl.entries:
                    new_bl.entries[fp] = dict(e)
                    carried += 1
            new_bl.merge_comments_from(baseline)
        new_bl.save()
        extra = f", {carried} out-of-scope carried over" if carried else ""
        print(f"baseline written: {target} ({len(new_bl.entries)} "
              f"accepted findings{extra})", file=sys.stderr)
        return 0

    accepted: List[Finding] = []
    if baseline is not None:
        findings, accepted = baseline.filter(findings)

    if args.json:
        doc = {
            "schema": SCHEMA,
            "paths": [os.path.relpath(os.path.abspath(p), root)
                      for p in paths],
            "baseline": (os.path.relpath(bl_path, root)
                         if bl_path and baseline is not None else None),
            "n_accepted_by_baseline": len(accepted),
            "findings": [f.to_dict() for f in findings],
            "entrypoints": [
                {"name": r.name,
                 "collectives": [list(c) for c in r.collectives],
                 "n_compiles": r.n_compiles,
                 "error": r.error} for r in reports],
        }
        print(json.dumps(doc, indent=2))
    else:
        for f in findings:
            print(f.render())
        sev = {}
        for f in findings:
            sev[f.severity] = sev.get(f.severity, 0) + 1
        tally = ", ".join(f"{n} {s}" for s, n in sorted(sev.items())) or \
            "no findings"
        extra = (f" ({len(accepted)} accepted by baseline)"
                 if accepted else "")
        print(f"spmd-lint: {tally}{extra} over {len(paths)} path(s)",
              file=sys.stderr)

    return 1 if findings else 0
