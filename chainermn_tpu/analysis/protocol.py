"""Exhaustive-interleaving model checker for the fleet's protocols.

The static lint (``concurrency.py``) argues about lock *shapes*; this
module argues about protocol *state spaces*.  Each load-bearing
concurrent machine in the serving/health plane is modeled as a small
explicit-state transition system — every nondeterministic scheduling
choice (a thread interleaving, a message delay, a SIGKILL) is a
transition — and the checker walks EVERY reachable state (BFS, so a
violation comes back with a minimal counterexample trace).  Exhaustive
exploration up to the model's bounded parameters replaces "we reviewed
the interleavings by hand", which is how the ~25 PR 10-13 races
shipped in the first place.

Three models, three invariants (docs/ANALYSIS.md has the table):

* :func:`make_done_xor_shed_model` — request ownership across submit
  threads, worker death, supervisor failover, and the shed path
  (``FleetRouter``).  Invariant: every accepted request reaches
  EXACTLY one terminal outcome (done XOR shed), never both, never
  neither (no forever-hang) — over every interleaving of dispatch,
  death, detection, redispatch, and late result delivery.
* :func:`make_lease_fence_model` — lease/epoch zombie fencing under
  SIGSTOP/SIGKILL/readmission schedules (``EpochFence`` +
  supervisor).  Invariant: a fenced writer's artifact NEVER lands —
  any write produced after the fence and before a fresh-epoch hello is
  refused on every delivery schedule.
* :func:`make_slot_model` — the ``SlotAllocator``
  free→reserved→busy→cached(rc)→free lifecycle.  Invariant: the slot
  partition is exact (free ∪ busy ∪ cached ∪ reserved = all slots,
  pairwise disjoint — no leak, no alias) after every legal operation
  sequence.

Each model is tied to the REAL class by a conformance test
(tests/test_concurrency_lint.py) that replays explored traces through
the actual implementation; the mutation tests there flip one
transition and assert the checker produces a counterexample — the
checker itself is checked.

Pure stdlib; states are hashable namedtuples, transitions are pure
functions.  ``python -m chainermn_tpu.analysis.protocol`` runs all
three models and exits 0/1/2 (the lint contract).
"""

from __future__ import annotations

from collections import deque, namedtuple
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Transition", "Model", "CheckResult", "check", "reachable_graph",
    "path_to", "make_done_xor_shed_model", "make_lease_fence_model",
    "make_slot_model", "ALL_MODELS", "main",
]


@dataclass(frozen=True)
class Transition:
    """One atomic step of one actor: enabled when ``guard(state)`` and
    rewriting the state via the pure ``apply(state)``."""
    name: str
    guard: Callable
    apply: Callable


@dataclass
class Model:
    name: str
    initial: tuple
    transitions: List[Transition]
    #: state predicate: None = holds, else a violation description.
    invariant: Callable[[tuple], Optional[str]]
    #: checked on states with NO enabled transition (complete
    #: schedules); None = nothing to assert at quiescence.
    terminal_invariant: Optional[Callable[[tuple],
                                          Optional[str]]] = None

    def replace(self, name: str, *, guard=None,
                apply=None) -> "Model":
        """A copy with one transition's guard/apply swapped — the
        mutation-injection hook (tests break a transition and assert
        the checker notices)."""
        out: List[Transition] = []
        hit = False
        for t in self.transitions:
            if t.name == name:
                hit = True
                out.append(Transition(
                    t.name, guard or t.guard, apply or t.apply))
            else:
                out.append(t)
        if not hit:
            raise KeyError(f"no transition named {name!r} in "
                           f"{self.name}; have "
                           f"{[t.name for t in self.transitions]}")
        return Model(self.name, self.initial, out, self.invariant,
                     self.terminal_invariant)


@dataclass
class CheckResult:
    ok: bool
    model: str
    n_states: int = 0
    n_edges: int = 0
    n_terminal: int = 0
    #: every reachable state expanded within the bounds (False = the
    #: depth/state cap truncated the walk: "counterexample-free" then
    #: only means "up to the bound")
    complete: bool = True
    violation: Optional[str] = None
    #: minimal trace to the violating state: [(transition name, state)]
    counterexample: List[Tuple[str, tuple]] = field(
        default_factory=list)

    def render(self) -> str:
        head = (f"{self.model}: "
                + ("OK" if self.ok else f"VIOLATION: {self.violation}")
                + f" ({self.n_states} states, {self.n_edges} edges, "
                  f"{self.n_terminal} terminal"
                + ("" if self.complete else ", TRUNCATED") + ")")
        if self.ok:
            return head
        lines = [head, "  counterexample (minimal):"]
        for i, (t, s) in enumerate(self.counterexample, 1):
            lines.append(f"    {i:2d}. {t:36s} -> {s}")
        return "\n".join(lines)


def path_to(parents: Dict[tuple, Optional[Tuple[tuple, str]]],
            state: tuple) -> List[Tuple[str, tuple]]:
    out: List[Tuple[str, tuple]] = []
    while parents[state] is not None:
        prev, tname = parents[state]
        out.append((tname, state))
        state = prev
    out.reverse()
    return out


def check(model: Model, max_depth: int = 10 ** 9,
          max_states: int = 500_000) -> CheckResult:
    """BFS over every reachable state.  BFS (not DFS) so the first
    invariant violation found is at minimal depth — the counterexample
    is a shortest trace, which is what a human debugging the protocol
    wants to read."""
    parents: Dict[tuple, Optional[Tuple[tuple, str]]] = {
        model.initial: None}
    depth = {model.initial: 0}
    q = deque([model.initial])
    n_edges = 0
    n_terminal = 0
    complete = True

    v = model.invariant(model.initial)
    if v:
        return CheckResult(False, model.name, 1, 0, 0, True,
                           f"initial state: {v}", [])

    n_states = 0
    while q:
        s = q.popleft()
        n_states += 1
        enabled = [t for t in model.transitions if t.guard(s)]
        if not enabled:
            n_terminal += 1
            if model.terminal_invariant is not None:
                v = model.terminal_invariant(s)
                if v:
                    return CheckResult(
                        False, model.name, n_states, n_edges,
                        n_terminal, complete,
                        f"terminal state: {v}",
                        path_to(parents, s))
            continue
        if depth[s] >= max_depth:
            complete = False
            continue
        for t in enabled:
            ns = t.apply(s)
            n_edges += 1
            if ns in parents:
                continue
            parents[ns] = (s, t.name)
            depth[ns] = depth[s] + 1
            v = model.invariant(ns)
            if v:
                return CheckResult(
                    False, model.name, n_states, n_edges, n_terminal,
                    complete, v, path_to(parents, ns))
            if len(parents) > max_states:
                return CheckResult(
                    True, model.name, n_states, n_edges, n_terminal,
                    False, None, [])
            q.append(ns)
    return CheckResult(True, model.name, n_states, n_edges, n_terminal,
                       complete, None, [])


def reachable_graph(model: Model, max_states: int = 500_000
                    ) -> Dict[tuple, List[Tuple[str, tuple]]]:
    """state -> [(transition name, next state)] over the reachable
    space, plus (via :func:`path_to`-style BFS parents baked into the
    insertion order) — the conformance tests walk this to replay every
    reachable edge through the real implementation."""
    graph: Dict[tuple, List[Tuple[str, tuple]]] = {}
    q = deque([model.initial])
    graph[model.initial] = []
    order = [model.initial]
    while q:
        s = q.popleft()
        for t in model.transitions:
            if not t.guard(s):
                continue
            ns = t.apply(s)
            graph[s].append((t.name, ns))
            if ns not in graph:
                graph[ns] = []
                order.append(ns)
                if len(graph) > max_states:
                    raise RuntimeError("state space exceeds max_states")
                q.append(ns)
    return graph


def bfs_paths(model: Model) -> Dict[tuple, List[Tuple[str, tuple]]]:
    """state -> one minimal trace reaching it (transition/state pairs
    from the initial state)."""
    parents: Dict[tuple, Optional[Tuple[tuple, str]]] = {
        model.initial: None}
    q = deque([model.initial])
    while q:
        s = q.popleft()
        for t in model.transitions:
            if t.guard(s):
                ns = t.apply(s)
                if ns not in parents:
                    parents[ns] = (s, t.name)
                    q.append(ns)
    return {s: path_to(parents, s) for s in parents}


# ==========================================================================
# model 1: done-XOR-shed request ownership (FleetRouter)
# ==========================================================================

#: has_req[i] is the dispatch ATTEMPT number sitting in worker i's
#: queue (None = nothing): a result message carries the attempt it was
#: produced under, and the router accepts a result only from the
#: CURRENT owner at the CURRENT attempt — the orphan-drop rule that
#: closes the late-result/failover TOCTOU (PR 10 review round).
DxsState = namedtuple("DxsState", [
    "registered",   # submit registered the entry
    "owner",        # current owning worker index (or None)
    "attempts",     # dispatch attempts so far
    "alive",        # tuple[bool] — process truly alive
    "detected",     # tuple[bool] — supervisor marked it dead
    "has_req",      # tuple[Optional[int]] — queued dispatch attempt
    "results",      # frozenset[(worker, attempt)] — in-flight results
    "done",         # terminal done count (must stay <= 1)
    "shed",         # terminal shed count (must stay <= 1)
    "returned",     # a LIVE owner gave the request back (queue_full)
])


def make_done_xor_shed_model(n_workers: int = 2,
                             max_attempts: int = 2) -> Model:
    """Submit vs worker death vs supervisor failover vs shed.

    Nondeterminism modeled: submit's liveness snapshot is STALE (it may
    dispatch to a dead-but-undetected worker — the submit/_mark_dead
    TOCTOU), workers die at any point, results survive their producer
    (the lane store persists a published result), detection and
    failover interleave with delivery.
    """
    W = range(n_workers)

    def st(**kw):
        base = dict(
            registered=False, owner=None, attempts=0,
            alive=tuple(True for _ in W),
            detected=tuple(False for _ in W),
            has_req=tuple(None for _ in W),
            results=frozenset(), done=0, shed=0, returned=False)
        base.update(kw)
        return DxsState(**base)

    def tup_set(t, i, v):
        lst = list(t)
        lst[i] = v
        return tuple(lst)

    ts: List[Transition] = []

    # submit: dispatch to ANY not-yet-detected worker (stale snapshot:
    # an undetected corpse is a legal target — the TOCTOU under test)
    for w in W:
        ts.append(Transition(
            f"submit(->w{w})",
            lambda s, w=w: not s.registered and not s.detected[w],
            lambda s, w=w: s._replace(
                registered=True, owner=w, attempts=1,
                has_req=tup_set(s.has_req, w, 1))))
    ts.append(Transition(
        "submit(reject:no_live_worker)",
        lambda s: not s.registered and all(s.detected),
        lambda s: s._replace(registered=True, shed=s.shed + 1)))

    for w in W:
        ts.append(Transition(
            f"worker{w}.produce_result",
            lambda s, w=w: s.alive[w] and s.has_req[w] is not None,
            lambda s, w=w: s._replace(
                has_req=tup_set(s.has_req, w, None),
                results=s.results | {(w, s.has_req[w])})))
        ts.append(Transition(
            f"worker{w}.dies",
            lambda s, w=w: s.alive[w],
            lambda s, w=w: s._replace(alive=tup_set(s.alive, w, False))))
        ts.append(Transition(
            f"supervisor.detect(w{w})",
            lambda s, w=w: not s.alive[w] and not s.detected[w],
            lambda s, w=w: s._replace(
                detected=tup_set(s.detected, w, True))))
        # give-back: a LIVE owner sheds the dispatched request back to
        # the router (worker-side queue_full backpressure) — ownership
        # returns WITHOUT a death, and the router may then re-dispatch
        # or shed (the scenario plane's burst workloads drive this)
        ts.append(Transition(
            f"worker{w}.give_back",
            lambda s, w=w: (
                s.registered and s.done + s.shed == 0
                and s.alive[w] and s.owner == w
                and s.has_req[w] is not None),
            lambda s, w=w: s._replace(
                has_req=tup_set(s.has_req, w, None), returned=True)))

    # failover: the supervisor owns re-dispatch (mark_dead loop + the
    # orphan sweep both funnel here) — enabled whenever the current
    # owner is detected dead OR gave the request back, and the entry
    # has no outcome yet
    for w in W:
        for v in W:
            if v == w:
                continue
            ts.append(Transition(
                f"supervisor.failover(w{w}->w{v})",
                lambda s, w=w, v=v: (
                    s.registered and s.done + s.shed == 0
                    and s.owner == w
                    and (s.detected[w] or s.returned)
                    and s.attempts < max_attempts
                    and not s.detected[v]),
                lambda s, w=w, v=v: s._replace(
                    owner=v, attempts=s.attempts + 1, returned=False,
                    has_req=tup_set(s.has_req, v, s.attempts + 1))))
        ts.append(Transition(
            f"supervisor.shed(w{w})",
            lambda s, w=w: (
                s.registered and s.done + s.shed == 0
                and s.owner == w
                and (s.detected[w] or s.returned)
                and (s.attempts >= max_attempts
                     or all(s.detected[v] for v in W if v != w))),
            lambda s, w=w: s._replace(shed=s.shed + 1)))

    for w in W:
        for att in range(1, max_attempts + 1):
            ts.append(Transition(
                f"router.deliver_result(w{w},att{att})",
                lambda s, w=w, att=att: (w, att) in s.results,
                lambda s, w=w, att=att: s._replace(
                    results=s.results - {(w, att)},
                    done=(s.done + 1
                          if (s.done + s.shed == 0 and s.owner == w
                              and s.attempts == att)
                          else s.done))))

    def invariant(s: DxsState) -> Optional[str]:
        if s.done > 1:
            return f"request completed TWICE (done={s.done})"
        if s.shed > 1:
            return f"request shed TWICE (shed={s.shed})"
        if s.done + s.shed > 1:
            return ("request both done AND shed "
                    f"(done={s.done}, shed={s.shed})")
        return None

    def terminal_invariant(s: DxsState) -> Optional[str]:
        if s.registered and s.done + s.shed != 1:
            return ("accepted request reached quiescence with NO "
                    "terminal outcome (forever-hang): "
                    f"done={s.done}, shed={s.shed}, owner=w{s.owner}")
        return None

    return Model("done_xor_shed", st(), ts, invariant,
                 terminal_invariant)


# ==========================================================================
# model 2: lease/epoch zombie fencing (EpochFence + supervisor)
# ==========================================================================

LeaseState = namedtuple("LeaseState", [
    "worker_epoch",    # the epoch the worker stamps writes with
    "current_epoch",   # the fence's current epoch for this worker
    "fenced",          # fence flag on current_epoch
    "running",         # False = SIGSTOP'd
    "view",            # supervisor's view: "live" | "dead"
    "hello_pending",   # readmission hello not yet processed
    "zombie",          # worker fenced at some point, no hello since
    "pending",         # tuple[(epoch, was_zombie)] in-flight writes
    "landed",          # tuple[(epoch, was_zombie)] admitted writes
    "refused",         # refusal count
    "writes_left",     # bound
    "readmits_left",   # bound
])


def make_lease_fence_model(max_writes: int = 3,
                           max_readmits: int = 2,
                           max_pending: int = 2) -> Model:
    """SIGSTOP/SIGCONT/death-detection/readmission schedules against
    the epoch fence.  ``zombie`` is the INTRINSIC truth the invariant
    uses: the worker was fenced (rightly or wrongly — the model
    includes false-positive detection of a live worker) and has not yet
    re-joined through a fresh-epoch hello; nothing such a worker
    publishes may ever land."""

    init = LeaseState(
        worker_epoch=1, current_epoch=1, fenced=False, running=True,
        view="live", hello_pending=False, zombie=False,
        pending=(), landed=(), refused=0,
        writes_left=max_writes, readmits_left=max_readmits)

    ts = [
        Transition(
            "worker.write",
            lambda s: (s.running and s.writes_left > 0
                       and len(s.pending) < max_pending),
            lambda s: s._replace(
                pending=s.pending + ((s.worker_epoch, s.zombie),),
                writes_left=s.writes_left - 1)),
        Transition(
            "worker.sigstop",
            lambda s: s.running,
            lambda s: s._replace(running=False)),
        Transition(
            "worker.sigcont",
            lambda s: not s.running,
            lambda s: s._replace(running=True)),
        Transition(
            # lease aged out — ALSO enabled while the worker is alive
            # and beating slowly: the false-positive-detection case a
            # fence must survive
            "supervisor.fence",
            lambda s: s.view == "live",
            lambda s: s._replace(fenced=True, view="dead",
                                 zombie=True)),
        Transition(
            "fence.deliver_write",
            lambda s: bool(s.pending),
            lambda s: (lambda e, z: s._replace(
                pending=s.pending[1:],
                landed=(s.landed + ((e, z),)
                        if e == s.current_epoch and not s.fenced
                        else s.landed),
                refused=(s.refused
                         if e == s.current_epoch and not s.fenced
                         else s.refused + 1)))(*s.pending[0])),
        Transition(
            # a NEW stale-seq beat from a fenced worker is the breaker's
            # re-admission evidence; the supervisor mints a FRESH epoch
            # and sends hello — the worker keeps stamping its old epoch
            # until it processes the hello
            "supervisor.readmit",
            lambda s: (s.view == "dead" and s.running
                       and s.readmits_left > 0),
            lambda s: s._replace(
                current_epoch=s.current_epoch + 1, fenced=False,
                view="live", hello_pending=True,
                readmits_left=s.readmits_left - 1)),
        Transition(
            "worker.process_hello",
            lambda s: s.hello_pending and s.running,
            lambda s: s._replace(
                worker_epoch=s.current_epoch, hello_pending=False,
                zombie=False)),
    ]

    def invariant(s: LeaseState) -> Optional[str]:
        for e, z in s.landed:
            if z:
                return (f"FENCED WRITER LANDED: a write stamped "
                        f"epoch {e}, produced after the fence and "
                        "before a fresh-epoch hello, was admitted")
        return None

    return Model("lease_fence", init, ts, invariant, None)


# ==========================================================================
# model 3: SlotAllocator free -> reserved -> busy -> cached(rc) -> free
# ==========================================================================

SlotState = namedtuple("SlotState", [
    "free",       # tuple[int] sorted — the free list
    "busy",       # frozenset[int]
    "cached",     # tuple[(slot, rc)] sorted
    "reserved",   # frozenset[int]
])


def make_slot_model(n_slots: int = 2, max_rc: int = 2) -> Model:
    """The allocator lifecycle with guards mirroring the real class's
    hard errors (an illegal transition is DISABLED here and RAISES
    there — the conformance test checks that equivalence edge by
    edge).  The state deliberately mirrors the real internal sets so a
    mutated transition can produce the real failure modes: a slot in
    two sets (alias) or in none (leak)."""
    ALL = frozenset(range(n_slots))

    init = SlotState(free=tuple(range(n_slots)), busy=frozenset(),
                     cached=(), reserved=frozenset())

    def cached_dict(s):
        return dict(s.cached)

    def with_cached(s, d):
        return s._replace(cached=tuple(sorted(d.items())))

    ts: List[Transition] = [
        Transition(
            "acquire",
            lambda s: bool(s.free),
            lambda s: s._replace(free=s.free[1:],
                                 busy=s.busy | {s.free[0]})),
        Transition(
            "reserve",
            lambda s: bool(s.free),
            lambda s: s._replace(free=s.free[1:],
                                 reserved=s.reserved | {s.free[0]})),
    ]
    for i in range(n_slots):
        ts.extend([
            Transition(
                f"release({i})",
                lambda s, i=i: i in s.busy,
                lambda s, i=i: s._replace(
                    busy=s.busy - {i},
                    free=tuple(sorted(s.free + (i,))))),
            Transition(
                f"commit_reservation({i})",
                lambda s, i=i: i in s.reserved,
                lambda s, i=i: s._replace(reserved=s.reserved - {i},
                                          busy=s.busy | {i})),
            Transition(
                f"cancel_reservation({i})",
                lambda s, i=i: i in s.reserved,
                lambda s, i=i: s._replace(
                    reserved=s.reserved - {i},
                    free=tuple(sorted(s.free + (i,))))),
            Transition(
                f"cache({i})",
                lambda s, i=i: i in s.busy,
                lambda s, i=i: with_cached(
                    s._replace(busy=s.busy - {i}),
                    {**cached_dict(s), i: 0})),
            Transition(
                f"retain({i})",
                lambda s, i=i: cached_dict(s).get(i, max_rc) < max_rc,
                lambda s, i=i: with_cached(
                    s, {**cached_dict(s),
                        i: cached_dict(s)[i] + 1})),
            Transition(
                f"unretain({i})",
                lambda s, i=i: cached_dict(s).get(i, 0) > 0,
                lambda s, i=i: with_cached(
                    s, {**cached_dict(s),
                        i: cached_dict(s)[i] - 1})),
            Transition(
                f"uncache({i})",
                lambda s, i=i: cached_dict(s).get(i) == 0,
                lambda s, i=i: with_cached(
                    s._replace(free=tuple(sorted(s.free + (i,)))),
                    {k: v for k, v in cached_dict(s).items()
                     if k != i})),
        ])

    def invariant(s: SlotState) -> Optional[str]:
        free = frozenset(s.free)
        cached = frozenset(dict(s.cached))
        if len(s.free) != len(free):
            return f"free list holds a DUPLICATE: {s.free}"
        groups = [("free", free), ("busy", s.busy),
                  ("cached", cached), ("reserved", s.reserved)]
        for i, (na, a) in enumerate(groups):
            for nb, b in groups[i + 1:]:
                both = a & b
                if both:
                    return (f"slot(s) {sorted(both)} ALIASED: in "
                            f"{na} and {nb} simultaneously")
        union = free | s.busy | cached | s.reserved
        if union != ALL:
            return (f"slot(s) {sorted(ALL - union)} LEAKED: in no "
                    "state set — capacity silently lost")
        for slot, rc in s.cached:
            if rc < 0:
                return f"slot {slot} refcount underflow ({rc})"
        return None

    return Model("slot_lifecycle", init, ts, invariant, None)


ALL_MODELS: Dict[str, Callable[[], Model]] = {
    "done_xor_shed": make_done_xor_shed_model,
    "lease_fence": make_lease_fence_model,
    "slot_lifecycle": make_slot_model,
}


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Run every model; exit 0 when all spaces are counterexample-free
    AND fully explored, 1 on a violation, 2 on unusable arguments."""
    import argparse
    import json as _json
    import sys

    p = argparse.ArgumentParser(
        prog="python -m chainermn_tpu.analysis.protocol",
        description="Exhaustive protocol model checker: done-XOR-shed "
                    "ownership, lease/epoch fencing, slot lifecycle "
                    "(docs/ANALYSIS.md)")
    p.add_argument("--model", action="append", default=None,
                   help="run one model (repeatable; default: all)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    names = args.model or sorted(ALL_MODELS)
    unknown = set(names) - set(ALL_MODELS)
    if unknown:
        print(f"error: unknown model(s) {sorted(unknown)}; have "
              f"{sorted(ALL_MODELS)}", file=sys.stderr)
        return 2

    results = [check(ALL_MODELS[n]()) for n in names]
    if args.json:
        print(_json.dumps({
            "schema": "chainermn_tpu.protocol_check.v1",
            "results": [{
                "model": r.model, "ok": r.ok,
                "n_states": r.n_states, "n_edges": r.n_edges,
                "n_terminal": r.n_terminal, "complete": r.complete,
                "violation": r.violation,
                "counterexample": [
                    {"transition": t, "state": list(s)}
                    for t, s in r.counterexample],
            } for r in results]}, indent=2))
    else:
        for r in results:
            print(r.render())
    bad = [r for r in results if not r.ok or not r.complete]
    return 1 if bad else 0


if __name__ == "__main__":   # pragma: no cover - python -m face
    import sys

    sys.exit(main())
