"""Opt-in runtime lock-order recorder (``CHAINERMN_TPU_LOCK_ASSERT=1``).

The static lock graph (``concurrency.lock_graph``) sees only orders the
AST can prove: ``with self.a: with self.b``, intra-class call chains.
Dynamic orders — a callback that takes a foreign lock, a lock handed
across objects, an order that only materializes under a particular
schedule — are invisible to it.  This module closes the gap at TEST
time: with the env var set, every ``threading.Lock``/``RLock`` CREATED
INSIDE the chainermn_tpu package is replaced by a thin recording proxy
(creation-site filtered, so stdlib/third-party locks stay native), each
acquisition while other tracked locks are held records an ordered edge,
and at teardown the UNION of the observed edges with the static graph
must be acyclic — a dynamic edge that closes a static path is a latent
deadlock the AST alone could not see.

Creation sites are keyed ``(abs file, lineno)`` — the same key
``concurrency.lock_sites`` derives statically, so observed edges are
named ``Class.attr -> Class.attr`` in failures.

Wiring: ``tests/conftest.py`` installs the recorder for the serving
test modules when the env var is set (tier-1 runs it on demand), and
``tests/test_concurrency_lint.py`` exercises it unconditionally on an
in-process serving scenario so the machinery itself cannot rot.

The proxy is intentionally minimal (acquire/release/context manager/
``locked``): enough for every lock use in this package.  Recording is
O(held) per acquisition with a per-thread held stack; the edge set is
a plain set under one internal (native) lock.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

Site = Tuple[str, int]
Edge = Tuple[Site, Site]

ENV_VAR = "CHAINERMN_TPU_LOCK_ASSERT"


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _TrackedLock:
    """Recording proxy over a real lock primitive."""

    def __init__(self, recorder: "LockOrderRecorder", inner, site: Site,
                 reentrant: bool):
        self._recorder = recorder
        self._inner = inner
        self._site = site
        self._reentrant = reentrant

    # the real lock API surface this package uses
    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._recorder._note_acquire(self)
        return got

    def release(self):
        self._recorder._note_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def _is_owned(self):   # Condition(lock) compatibility
        owned = getattr(self._inner, "_is_owned", None)
        if owned is not None:
            return owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __repr__(self):
        return f"<TrackedLock {self._site[0]}:{self._site[1]}>"


class LockOrderRecorder:
    """Patches ``threading.Lock``/``RLock`` factories; records the
    acquisition-order edge set of package-created locks."""

    def __init__(self, root: Optional[str] = None):
        self.root = os.path.abspath(root or _package_root())
        self._orig_lock = None
        self._orig_rlock = None
        self._local = threading.local()
        self._mu = None    # native lock guarding the edge set
        self._edges: Dict[Edge, int] = {}   # edge -> observation count
        self.n_tracked = 0
        self.installed = False

    # ---- patching ----
    def install(self) -> "LockOrderRecorder":
        if self.installed:
            return self
        self._orig_lock = threading.Lock
        self._orig_rlock = threading.RLock
        self._mu = self._orig_lock()
        rec = self

        def _site_of_caller() -> Optional[Site]:
            f = sys._getframe(2)
            path = os.path.abspath(f.f_code.co_filename)
            if path.startswith(rec.root + os.sep):
                return (path, f.f_lineno)
            return None

        def make_lock():
            site = _site_of_caller()
            inner = rec._orig_lock()
            if site is None:
                return inner
            rec.n_tracked += 1
            return _TrackedLock(rec, inner, site, reentrant=False)

        def make_rlock():
            site = _site_of_caller()
            inner = rec._orig_rlock()
            if site is None:
                return inner
            rec.n_tracked += 1
            return _TrackedLock(rec, inner, site, reentrant=True)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        self.installed = True
        return self

    def uninstall(self) -> None:
        if not self.installed:
            return
        threading.Lock = self._orig_lock
        threading.RLock = self._orig_rlock
        self.installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # ---- recording ----
    def _held(self) -> List[_TrackedLock]:
        held = getattr(self._local, "held", None)
        if held is None:
            held = self._local.held = []
        return held

    def _note_acquire(self, lock: _TrackedLock) -> None:
        held = self._held()
        new_edges = []
        for h in held:
            if h is lock:
                if lock._reentrant:
                    continue   # legal RLock re-entry, not an order
                new_edges.append((h._site, lock._site))
            elif h._site != lock._site:
                new_edges.append((h._site, lock._site))
        held.append(lock)
        if new_edges:
            with self._mu:
                for e in new_edges:
                    self._edges[e] = self._edges.get(e, 0) + 1

    def _note_release(self, lock: _TrackedLock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    # ---- reporting ----
    def edges(self) -> Set[Edge]:
        if self._mu is None:
            return set()
        with self._mu:
            return set(self._edges)

    def named_edges(self, sites: Dict[Site, Tuple[str, str]]
                    ) -> Set[Tuple[str, str]]:
        """Observed edges named by the STATIC lock table
        (``concurrency.lock_sites``): ``Class.attr`` ids where the
        creation site is known, ``file:line`` otherwise."""
        def name(site: Site) -> str:
            hit = sites.get(site)
            if hit is not None:
                owner, attr = hit
                return f"{owner}.{attr}"
            rel = os.path.relpath(site[0], self.root)
            return f"{rel}:{site[1]}"
        return {(name(a), name(b)) for a, b in self.edges()}


def find_cycle(edges: Set[Tuple[str, str]]) -> Optional[List[str]]:
    """A cycle in the (union) edge graph, or None.  Deterministic:
    nodes visited in sorted order."""
    graph: Dict[str, List[str]] = {}
    for a, b in sorted(edges):
        graph.setdefault(a, []).append(b)
    for start in sorted(graph):
        stack = [(start, [start])]
        seen: Set[str] = set()
        while stack:
            node, path = stack.pop()
            for nxt in graph.get(node, ()):
                if nxt == start:
                    return path + [start]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
    return None


def assert_consistent(recorder: LockOrderRecorder,
                      paths: Optional[List[str]] = None) -> Set[
                          Tuple[str, str]]:
    """The teardown assertion: the union of the STATIC lock graph and
    the edges observed at run time must be acyclic.  Returns the named
    dynamic edge set (for reporting); raises ``AssertionError`` naming
    the cycle otherwise."""
    from .concurrency import analyze_lock_surface

    paths = paths or [_package_root()]
    sites, static = analyze_lock_surface(paths)   # one pass, both halves
    dynamic = recorder.named_edges(sites)
    cycle = find_cycle(static | dynamic)
    if cycle:
        only_dyn = sorted(e for e in dynamic if e not in static)
        raise AssertionError(
            "lock-order cycle in the static+observed union graph: "
            + " -> ".join(cycle)
            + f"; dynamic-only edges: {only_dyn} — an order the AST "
              "could not see closed a deadlock cycle "
              "(CHAINERMN_TPU_LOCK_ASSERT)")
    return dynamic


def install_from_env(root: Optional[str] = None
                     ) -> Optional[LockOrderRecorder]:
    """The conftest hook: a live recorder when
    ``CHAINERMN_TPU_LOCK_ASSERT=1``, else None."""
    if os.environ.get(ENV_VAR) != "1":
        return None
    return LockOrderRecorder(root).install()
