"""Cost-model calibration + critical-path attribution from measured
``schedule_exec`` records — the truth side of the schedule plane.

PR 19 made comm programs compiled, checkable artifacts; every one of
them is still PRICED by the hand-set r04 constants in
:class:`~.schedule.CostModel`.  This module closes the loop (ISSUE 20):

* :func:`read_exec_records` pools ``chainermn_tpu.schedule_exec.v1``
  records from raw JSONL files or PR 17 journal files (torn tails
  skipped, foreign schemas refused — the journal's own read
  discipline).
* :func:`fit_calibration` fits per-link ``wall = alpha + bytes/bw`` by
  least squares and returns a versioned, commented artifact
  (``chainermn_tpu.calibration.v1``) that
  :func:`~.schedule.price_schedule`/:func:`~.schedule_check.compile_verified`
  consume via ``calibration=`` — candidates then rank by MEASURED
  costs.  :func:`load_calibration` refuses stale artifacts by schema
  version.
* :func:`drift_report` is the gate: when the calibrated model's
  predictions diverge from fresh measurements beyond a threshold the
  artifact has rotted (new host, new kernel, new numpy) and the fit
  must be redone.  ``python -m chainermn_tpu.analysis --gate`` runs it
  as the ``calibration`` stage, exiting 0 ("skipped") when no records
  exist yet.
* :func:`schedule_critical_path` walks the start/done dependency edges
  of one executed run to name the longest chain, the dominant link
  class on it, and the OVERLAP FRACTION — wire time hidden behind
  other work vs exposed on the critical path.  This is the instrument
  ROADMAP item 5's bucket-pipelined overlap is gated on.

Analysis-package contract: stdlib + numpy only at import time, no jax,
no observability imports (``scripts/check_schedules.py`` loads this
package standalone).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .schedule import (
    CALIBRATION_SCHEMA, CostModel, calibrated_cost_model,
)
from .schedule_check import SCHEDULE_EXEC_SCHEMA

__all__ = [
    "CALIBRATION_SCHEMA", "read_exec_records", "transfer_samples",
    "fit_calibration", "save_calibration", "load_calibration",
    "drift_report", "schedule_critical_path", "find_records", "main",
]

#: The PR 17 journal schema — records teed through ``journal.emit``
#: arrive wrapped in this envelope; the constant is duplicated here
#: (string only) so the analysis package stays importable standalone.
_JOURNAL_SCHEMA = "chainermn_tpu.journal.v1"

#: Journal-envelope fields stripped when unwrapping a teed record.
_ENVELOPE = ("schema", "kind", "hlc")


# --------------------------------------------------------------------------
# record ingestion — the journal's torn-tail discipline
# --------------------------------------------------------------------------

def _coerce_record(doc: dict) -> Optional[dict]:
    """A usable exec record or None.  Accepts raw
    ``schedule_exec.v1`` lines and journal-enveloped lines
    (``kind == "schedule_exec"``); anything else is not ours."""
    schema = doc.get("schema")
    if schema == _JOURNAL_SCHEMA:
        if doc.get("kind") != "schedule_exec":
            return None
        doc = {k: v for k, v in doc.items() if k not in _ENVELOPE}
    elif schema is not None and schema != SCHEDULE_EXEC_SCHEMA:
        return None
    # partial/torn records (a crashed run journals what it got to) are
    # tolerated by dropping, not by crashing the fit.
    if doc.get("op") is None or doc.get("link") is None:
        return None
    try:
        doc["bytes"] = int(doc["bytes"])
        doc["wall_us"] = float(doc["wall_us"])
    except (KeyError, TypeError, ValueError):
        return None
    return doc


def read_exec_records(path: str) -> List[dict]:
    """All schedule-exec records under ``path`` (a JSONL file or a
    directory scanned for ``*.jsonl``).  Torn trailing lines and
    foreign lines are skipped silently — same contract as
    ``journal.read_journal``."""
    files: List[str] = []
    if os.path.isdir(path):
        for name in sorted(os.listdir(path)):
            if name.endswith(".jsonl"):
                files.append(os.path.join(path, name))
    else:
        files.append(path)
    out: List[dict] = []
    for fp in files:
        try:
            with open(fp, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        doc = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail / partial write
                    if not isinstance(doc, dict):
                        continue
                    rec = _coerce_record(doc)
                    if rec is not None:
                        out.append(rec)
        except OSError:
            continue
    return out


def find_records(paths: Sequence[str] = ()) -> List[dict]:
    """Record discovery for the gate: explicit paths, else
    ``$CHAINERMN_SCHEDULE_EXEC_RECORDS`` (file or directory), else
    ``./schedule_exec.jsonl`` when present.  Empty list = nothing
    measured yet (the gate skips cleanly)."""
    cands = list(paths)
    if not cands:
        env = os.environ.get("CHAINERMN_SCHEDULE_EXEC_RECORDS")
        if env:
            cands = [env]
        elif os.path.exists("schedule_exec.jsonl"):
            cands = ["schedule_exec.jsonl"]
    recs: List[dict] = []
    for p in cands:
        if os.path.exists(p):
            recs.extend(read_exec_records(p))
    return recs


# --------------------------------------------------------------------------
# the least-squares (alpha, bw) fit
# --------------------------------------------------------------------------

def transfer_samples(records: Sequence[dict]
                     ) -> Dict[str, List[Tuple[int, float]]]:
    """Per-link (bytes, wall_s) samples.

    A wire sample is one TRANSFER: its ``start`` wall (gather + post)
    plus its ``done`` wall (await + landing copy), paired by
    (run, tid).  A ``start`` whose ``done`` never recorded (torn run)
    contributes nothing.  ``copy`` samples are individual local
    copy/unstage ops."""
    out: Dict[str, List[Tuple[int, float]]] = {
        "ici": [], "dcn": [], "copy": []}
    starts: Dict[Tuple[str, str], dict] = {}
    for r in records:
        link = r["link"]
        if link == "copy":
            out["copy"].append((r["bytes"], r["wall_us"] / 1e6))
            continue
        if link not in ("ici", "dcn"):
            continue
        key = (str(r.get("run", "?")), str(r.get("arg", "?")))
        if r["op"] == "start":
            starts[key] = r
        elif r["op"] == "done":
            s = starts.pop(key, None)
            if s is not None and s["link"] == link:
                wall_s = (s["wall_us"] + r["wall_us"]) / 1e6
                out[link].append((r["bytes"], wall_s))
    return out


def _fit_link(samples: List[Tuple[int, float]]
              ) -> Optional[Dict[str, float]]:
    """alpha + bytes/bw least squares over one link's samples; None
    when the link was never measured or the fit is degenerate."""
    pts = [(b, w) for b, w in samples if w > 0 and b > 0]
    if not pts:
        return None
    b = np.array([p[0] for p in pts], dtype=np.float64)
    w = np.array([p[1] for p in pts], dtype=np.float64)
    alpha, slope = 0.0, None
    if len(pts) >= 2 and float(b.std()) > 0:
        A = np.stack([np.ones_like(b), b], axis=1)
        coef, *_ = np.linalg.lstsq(A, w, rcond=None)
        alpha, slope = float(coef[0]), float(coef[1])
    if slope is None or slope <= 0 or alpha < 0:
        # degenerate (one sample, uniform sizes, or a negative
        # intercept/slope from noise): refit through the origin —
        # a pure-bandwidth model is still a measurement.
        alpha = max(0.0, alpha) if slope is not None and slope > 0 \
            else 0.0
        denom = float((b * b).sum())
        slope = float((b * w).sum()) / denom if denom > 0 else 0.0
        if slope <= 0:
            return None
    pred = alpha + slope * b
    residual = float(np.median(np.abs(pred - w) / w))
    return {
        "alpha_s": alpha,
        "bw": 1.0 / slope,
        "n": len(pts),
        "residual_rel": residual,
    }


def fit_calibration(records: Sequence[dict]) -> dict:
    """Fit per-link (alpha, bw) from pooled exec records and return
    the versioned calibration artifact.  Deterministic: same records
    in, byte-identical artifact out (no timestamps, no host salt)."""
    samples = transfer_samples(records)
    links: Dict[str, dict] = {}
    for link in ("ici", "dcn", "copy"):
        fit = _fit_link(samples[link])
        if fit is not None:
            links[link] = fit
    fingerprints = sorted({str(r.get("fingerprint"))
                           for r in records if r.get("fingerprint")})
    stock = CostModel()
    return {
        "schema": CALIBRATION_SCHEMA,
        "comment": [
            "Measured per-link cost-model constants fitted by"
            " chainermn_tpu.analysis.calibrate from schedule_exec"
            " records (wall = alpha_s + bytes/bw, least squares).",
            "links.<link>.alpha_s: fitted per-message setup latency"
            " in seconds; links.<link>.bw: fitted bandwidth in B/s;"
            " n: samples; residual_rel: median |pred-meas|/meas of"
            " the fit itself.",
            "Consumed by price_schedule(calibration=) /"
            " compile_verified(calibration=); stock r04 constants"
            " fill any link absent here.",
            f"Stock r04 baseline: ici_bw={stock.ici_bw:g}"
            f" dcn_bw={stock.dcn_bw:g} alpha_ici_s={stock.alpha_ici_s:g}"
            f" alpha_dcn_s={stock.alpha_dcn_s:g}"
            f" copy_bw={stock.copy_bw:g}.",
        ],
        "n_records": len(records),
        "fingerprints": fingerprints,
        "links": links,
    }


def save_calibration(doc: dict, path: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def load_calibration(path: str) -> dict:
    """Load and validate a calibration artifact; a wrong/absent schema
    version raises (stale artifacts must be re-fit, never silently
    consumed)."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) \
            or doc.get("schema") != CALIBRATION_SCHEMA:
        raise ValueError(
            f"{path}: stale/foreign calibration artifact "
            f"(schema={doc.get('schema') if isinstance(doc, dict) else None!r},"
            f" want {CALIBRATION_SCHEMA})")
    return doc


# --------------------------------------------------------------------------
# drift gate
# --------------------------------------------------------------------------

def drift_report(records: Sequence[dict], calibration: dict,
                 threshold: float = 0.5) -> dict:
    """Has reality drifted from the calibrated predictions?

    Per wire sample the calibrated model predicts
    ``alpha + bytes/bw``; the report is the median relative error per
    link and overall.  ``ok`` is False once the overall median exceeds
    ``threshold`` — time to re-fit (or to ask what changed on the
    host)."""
    cm = calibrated_cost_model(calibration)
    samples = transfer_samples(records)
    per_link: Dict[str, dict] = {}
    errs_all: List[float] = []
    for link in ("ici", "dcn"):
        errs = []
        for b, w in samples[link]:
            if w <= 0:
                continue
            pred = cm.alpha(link) + b / cm.bw(link)
            errs.append(abs(pred - w) / w)
        if errs:
            per_link[link] = {
                "n": len(errs),
                "median_rel_err": float(np.median(errs)),
            }
            errs_all.extend(errs)
    overall = float(np.median(errs_all)) if errs_all else 0.0
    return {
        "ok": overall <= threshold,
        "threshold": threshold,
        "n_samples": len(errs_all),
        "median_rel_err": overall,
        "links": per_link,
    }


# --------------------------------------------------------------------------
# causal critical path + overlap attribution
# --------------------------------------------------------------------------

def schedule_critical_path(records: Sequence[dict]) -> dict:
    """The longest dependency chain through one executed run.

    Edges: program order on each rank (the interpreter retires a
    rank's ops in order) and ``start(t) -> done(t)`` across ranks (a
    landing cannot precede its send).  The chain's length is the sum
    of op walls along it — the part of the measured wall that NO
    scheduling freedom can hide.  Wire time OFF the chain is hidden
    behind other work; the overlap fraction is
    ``hidden_wire / total_wire`` (1.0 = all wire time overlapped,
    0.0 = every wire microsecond exposed).  ``wire_exposed_frac`` is
    the complement — the gateable lower-is-better face.

    With records from several runs, the LAST run is attributed.
    """
    runs: List[str] = []
    for r in records:
        rid = str(r.get("run", "?"))
        if not runs or runs[-1] != rid:
            runs.append(rid)
    if not runs:
        return {"run": None, "n_ops": 0, "critical_path_us": 0.0,
                "chain": [], "by_link_path_us": {},
                "dominant_link": None, "dominant_op": None,
                "wire_total_us": 0.0, "wire_exposed_us": 0.0,
                "wire_hidden_us": 0.0, "overlap_frac": 0.0,
                "wire_exposed_frac": 0.0}
    run = runs[-1]
    recs = [r for r in records if str(r.get("run", "?")) == run]
    recs = sorted(recs, key=lambda r: r.get("seq", 0))
    n = len(recs)
    cp = [0.0] * n       # chain length ending at i (inclusive)
    pred = [-1] * n
    last_on_rank: Dict[int, int] = {}
    start_ix: Dict[str, int] = {}
    for i, r in enumerate(recs):
        best, best_p = 0.0, -1
        j = last_on_rank.get(r.get("rank"))
        if j is not None and cp[j] > best:
            best, best_p = cp[j], j
        if r["op"] == "done":
            j = start_ix.get(str(r.get("arg")))
            if j is not None and cp[j] > best:
                best, best_p = cp[j], j
        cp[i] = best + float(r["wall_us"])
        pred[i] = best_p
        last_on_rank[r.get("rank")] = i
        if r["op"] == "start":
            start_ix[str(r.get("arg"))] = i
    end = int(np.argmax(cp)) if n else -1
    chain_ix: List[int] = []
    i = end
    while i >= 0:
        chain_ix.append(i)
        i = pred[i]
    chain_ix.reverse()
    on_path = set(chain_ix)
    by_link: Dict[str, float] = {}
    wire_total = wire_exposed = 0.0
    for i, r in enumerate(recs):
        w = float(r["wall_us"])
        if i in on_path:
            by_link[r["link"]] = by_link.get(r["link"], 0.0) + w
        if r["link"] in ("ici", "dcn"):
            wire_total += w
            if i in on_path:
                wire_exposed += w
    hidden = max(0.0, wire_total - wire_exposed)
    dom_link = max(by_link, key=lambda k: by_link[k]) if by_link \
        else None
    dom_op = None
    if chain_ix:
        i = max(chain_ix, key=lambda j: recs[j]["wall_us"])
        r = recs[i]
        dom_op = (f"r{r.get('rank')}.{r['op']}({r.get('arg')})"
                  f"[{r['link']}] {r['wall_us']:.1f}us")
    return {
        "run": run,
        "n_ops": n,
        "critical_path_us": float(cp[end]) if n else 0.0,
        "chain": [f"r{recs[j].get('rank')}."
                  f"{recs[j]['op']}({recs[j].get('arg')})"
                  f"[{recs[j]['link']}]" for j in chain_ix],
        "by_link_path_us": {k: float(v) for k, v in
                            sorted(by_link.items())},
        "dominant_link": dom_link,
        "dominant_op": dom_op,
        "wire_total_us": wire_total,
        "wire_exposed_us": wire_exposed,
        "wire_hidden_us": hidden,
        "overlap_frac": (hidden / wire_total) if wire_total else 0.0,
        "wire_exposed_frac": (wire_exposed / wire_total)
        if wire_total else 0.0,
    }


# --------------------------------------------------------------------------
# CLI — the --gate face (exit 0 clean/skip, 1 drift, 2 unusable)
# --------------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m chainermn_tpu.analysis.calibrate",
        description="fit per-link (alpha, bw) from schedule_exec "
                    "records and gate calibration drift (exit 0 "
                    "clean/skipped, 1 drift, 2 unusable)")
    p.add_argument("records", nargs="*",
                   help="record JSONL files / journal dirs; default = "
                        "$CHAINERMN_SCHEDULE_EXEC_RECORDS or "
                        "./schedule_exec.jsonl")
    p.add_argument("--fit-out", default=None,
                   help="persist the fitted calibration artifact here")
    p.add_argument("--calibration", default=None,
                   help="existing artifact to drift-check against "
                        "(default: $CHAINERMN_CALIBRATION when set, "
                        "else the fresh fit checks itself)")
    p.add_argument("--drift-threshold", type=float, default=0.5,
                   help="median relative error above which the gate "
                        "flags drift (default 0.5)")
    p.add_argument("--gate", action="store_true",
                   help="gate mode: exit 0 when no records exist yet")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    try:
        records = find_records(args.records)
    except Exception as e:
        print(f"calibrate: unusable: {e!r}", file=sys.stderr)
        return 2
    if not records:
        msg = {"stage": "calibration", "skipped": True,
               "reason": "no schedule_exec records found"}
        print(json.dumps(msg) if args.json
              else "calibration-drift: skipped (no records yet)")
        # nothing measured is not a finding — the gate stays green
        # until the first profiled execution lands records.
        return 0 if args.gate else 2

    try:
        cal_path = args.calibration \
            or os.environ.get("CHAINERMN_CALIBRATION")
        if cal_path:
            calibration = load_calibration(cal_path)
        else:
            calibration = fit_calibration(records)
        if args.fit_out:
            fresh = calibration if not cal_path \
                else fit_calibration(records)
            save_calibration(fresh, args.fit_out)
        drift = drift_report(records, calibration,
                             threshold=args.drift_threshold)
    except ValueError as e:
        print(f"calibrate: unusable: {e}", file=sys.stderr)
        return 2
    except Exception as e:
        print(f"calibrate: unusable: {e!r}", file=sys.stderr)
        return 2

    out = {
        "stage": "calibration",
        "n_records": len(records),
        "calibration_source": cal_path or "(fresh fit)",
        "links": calibration.get("links", {}),
        "drift": drift,
    }
    if args.json:
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        for link, fit in sorted(out["links"].items()):
            print(f"calibration: {link}: alpha={fit['alpha_s']*1e6:.2f}us "
                  f"bw={fit['bw']:.3g}B/s n={fit['n']} "
                  f"residual={fit['residual_rel']:.3f}")
        verdict = "ok" if drift["ok"] else "DRIFT"
        print(f"calibration-drift: {verdict} "
              f"median_rel_err={drift['median_rel_err']:.3f} "
              f"(threshold {drift['threshold']}, "
              f"{drift['n_samples']} samples, "
              f"{len(records)} records)")
    return 0 if drift["ok"] else 1


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
