"""Shard-flow analyzer: static sharding, memory, and collective-cost
model, reconciled against the runtime comm ledger.

The jaxpr engine (``jaxpr_engine.py``) checks *which* collectives a
registered entry point runs and over which axes; this module answers the
three questions the ROADMAP's next tentpoles (ZeRO-1 weight-update
sharding, the ``reshard`` primitive) stand or fall on:

* **Replication report** — for every entry-point argument leaf and every
  sizeable intermediate, is it REPLICATED across the entry's declared
  data axis?  Full replication of optimizer state is exactly the failure
  mode ZeRO-1 (ROADMAP item 2, arxiv 2004.13336) must eliminate, so the
  report names it today and the ZeRO PR lands with a red→green diff:
  entry points declare *expected* replication (label → reason), an
  undeclared replicated arg is an ``unexpected-replication`` finding, and
  a declaration whose arg is no longer replicated is a
  ``stale-replication-annotation`` finding (the annotation must be
  deleted when the sharding lands — same discipline as stale baseline
  entries).

* **Static collective cost model** — per collective equation: the
  LEDGER-convention payload bytes (``observability.comm.payload_info``:
  shape × itemsize of the input payload, axis-size independent) plus the
  physical ring decomposition (``ops.collective.collective_wire_cost``:
  per-rank wire bytes and message counts from the axis size), with scan
  trip counts reported as multipliers.  The quantized int8 ring is
  modeled analytically by ``ops.collective.quantized_ring_cost``; a
  declaring entry point swaps its composite ledger row for the
  per-primitive groups of ``quantized_ring_static_groups`` via the
  ``composite`` build-spec key (see the reconciliation section below).

* **Peak live memory per replica** — classical liveness over the jaxpr:
  a value is live from its defining equation to its last use; the peak
  of the live-set byte total (recursing into sub-jaxprs, where shard_map
  body avals are already per-replica block shapes) estimates the
  activation watermark a replica must hold.  This is the number the
  ZeRO-1 acceptance gate ("peak memory/replica at n=1..8") reads.

Static↔dynamic reconciliation — the anti-rot mechanism
------------------------------------------------------
A cost model that nothing checks decays silently.  Here, every analysis
run ALSO executes the entry point once under the PR 1 accounting layer
(a fresh build, so the compile lands inside a ``CommAccountant.step``
bracket) and asserts, per ``primitive@axis`` group::

    static_eqn_bytes == wrapped_ledger_bytes
                        + (legacy jax ? declared ad_transpose_bytes : 0)
                        + (vma jax    ? declared noted bytes        : 0)

* ``wrapped`` rows are bookings by the accounted collective face — each
  one has exactly its forward equation in the traced program, so the two
  sides must agree byte-exactly; a gap is a ``comm-ledger-gap`` ERROR
  (either the model rotted or a collective bypasses the accounted face).
* ``noted`` rows (``observability.comm.note`` — traffic no wrapper sees,
  e.g. the autodiff-inserted gradient psum of the default train step)
  must equal the entry's declaration; whether the matching psum EQUATION
  exists is jax-version dependent (``_compat.ad_inserts_replicated_psum``)
  and the expectation adapts.
* ``ad_transpose_bytes`` declares the equations legacy-jax autodiff adds
  by transposing a *wrapped* collective (transpose(psum) = psum on
  0.4.x), which the ledger cannot book.

The only tolerance is dtype-dependent padding: sub-byte or odd-itemsize
wire dtypes may pad up to one element per call (``pad_tolerance``); for
the shipped dtypes the comparison is exact.

Findings flow through the same fingerprint/baseline/suppression
machinery as the AST engine; the checked-in baseline is
``.shardflow-baseline.json`` and ``scripts/shardflow_report.py`` is the
CI runner (exit 0/1/2 — the ``check_perf_regression.py`` contract).

jax is imported lazily: importing this module costs nothing on jax-free
boxes (same contract as ``jaxpr_engine``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .findings import Finding

SHARDFLOW_SCHEMA = "chainermn_tpu.shardflow.v1"
SHARDFLOW_BASELINE_FILENAME = ".shardflow-baseline.json"

SHARDFLOW_RULES: Dict[str, Tuple[str, str]] = {
    "unexpected-replication": (
        "warning", "argument replicated across the data axis without a "
                   "declared expectation"),
    "stale-replication-annotation": (
        "warning", "declared expected replication no longer observed — "
                   "delete the annotation (the sharding landed)"),
    "comm-ledger-gap": (
        "error", "static collective bytes and the runtime comm ledger "
                 "disagree (cost-model rot, or a collective bypassing "
                 "the accounted face)"),
    "shardflow-error": (
        "error", "entry point failed to build/trace/execute under the "
                 "shard-flow analyzer"),
}

#: jaxpr primitive aliases across jax versions → canonical name.
_PRIM_ALIAS = {"reduce_scatter": "psum_scatter"}

#: Collectives whose result is replication-INVARIANT over their axes
#: (the axes leave the varying set)…
_REDUCING_PRIMS = frozenset({"psum", "pmax", "pmin", "all_gather"})
#: …and collectives whose result stays (or becomes) rank-varying.
_VARYING_PRIMS = frozenset({"psum_scatter", "ppermute", "all_to_all",
                            "pshuffle", "pgather"})
_COLLECTIVE_PRIMS = _REDUCING_PRIMS | _VARYING_PRIMS

#: How many intermediates the replication report keeps (largest first).
_TOP_INTERMEDIATES = 5


# --------------------------------------------------------------------------
# small jaxpr helpers (shared shapes with jaxpr_engine, kept dependency-free)
# --------------------------------------------------------------------------

def _inner(jx):
    return getattr(jx, "jaxpr", jx)  # ClosedJaxpr -> Jaxpr


def _subjaxprs(v) -> List[Any]:
    subs = []
    if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
        subs.append(v)
    elif isinstance(v, (tuple, list)):
        for item in v:
            subs.extend(_subjaxprs(item))
    return subs


def _eqn_subjaxprs(eqn) -> List[Any]:
    out = []
    for v in eqn.params.values():
        out.extend(_subjaxprs(v))
    return out


def _canon(prim_name: str) -> str:
    return _PRIM_ALIAS.get(prim_name, prim_name)


def _axes_of(params: Dict[str, Any]) -> Tuple[str, ...]:
    for key in ("axes", "axis_name"):
        if key in params:
            v = params[key]
            if isinstance(v, str):
                return (v,)
            if isinstance(v, (tuple, list)):
                return tuple(x for x in v if isinstance(x, str))
    return ()


def _aval_nbytes(aval) -> int:
    """Byte size of one aval, computed THROUGH the ledger's own
    convention function (``observability.comm.payload_info`` — avals
    carry shape/dtype, which is all it reads): the static model and the
    accountant can never disagree on the formula, only on what they
    count."""
    if aval is None or getattr(aval, "shape", None) is None \
            or getattr(aval, "dtype", None) is None:
        return 0  # tokens / abstract values carry no payload
    from chainermn_tpu.observability.comm import payload_info

    return payload_info(aval)[0]


def _var_nbytes(v) -> int:
    return _aval_nbytes(getattr(v, "aval", None))


def _is_var(v) -> bool:
    import jax

    return isinstance(v, jax.core.Var)


# --------------------------------------------------------------------------
# static collective cost model
# --------------------------------------------------------------------------

@dataclass
class CollectiveCost:
    """One collective equation of the traced program."""

    primitive: str                 # canonical jaxpr primitive name
    axes: Tuple[str, ...]
    payload_bytes: int             # ledger convention (input payload)
    wire_bytes: int                # physical ring bytes per rank
    messages: int                  # per-rank wire messages
    dtype: str
    shape: Tuple[int, ...]
    trip_count: int = 1            # scan multiplier (1 = straight-line)

    @property
    def group(self) -> str:
        return f"{self.primitive}@{'+'.join(self.axes)}"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "primitive": self.primitive, "axes": list(self.axes),
            "payload_bytes": self.payload_bytes,
            "wire_bytes": self.wire_bytes, "messages": self.messages,
            "dtype": self.dtype, "shape": list(self.shape),
            "trip_count": self.trip_count,
        }


def static_costs(jaxpr, default_axis_sizes: Optional[Dict[str, int]] = None
                 ) -> List[CollectiveCost]:
    """Every collective equation of ``jaxpr`` (recursively), costed.

    Axis sizes come from the enclosing ``shard_map`` equation's mesh
    (``default_axis_sizes`` seeds the walk for bodies traced bare).
    ``trip_count`` carries scan ``length`` multipliers: the LEDGER books
    once per trace, so reconciliation compares at ``trip_count``-blind
    granularity, while the report's physical totals honor it.
    """
    from chainermn_tpu.ops.collective import collective_wire_cost

    out: List[CollectiveCost] = []

    def walk(jx, sizes: Dict[str, int], mult: int):
        for eqn in _inner(jx).eqns:
            name = _canon(eqn.primitive.name)
            if name == "shard_map":
                sub_sizes = dict(sizes)
                mesh = eqn.params.get("mesh")
                shape = getattr(mesh, "shape", None)
                if shape:
                    sub_sizes.update({str(k): int(v)
                                      for k, v in dict(shape).items()})
                walk(eqn.params["jaxpr"], sub_sizes, mult)
                continue
            if name in _COLLECTIVE_PRIMS:
                axes = _axes_of(eqn.params)
                payload = sum(_var_nbytes(v) for v in eqn.invars)
                p = 1
                for a in axes:
                    p *= int(sizes.get(a, 1))
                cost = collective_wire_cost(name, payload, p)
                aval = getattr(eqn.invars[0], "aval", None)
                out.append(CollectiveCost(
                    primitive=name, axes=axes, payload_bytes=payload,
                    wire_bytes=cost["wire_bytes"],
                    messages=cost["messages"],
                    dtype=str(getattr(aval, "dtype", "?")),
                    shape=tuple(getattr(aval, "shape", ())),
                    trip_count=mult))
            sub_mult = mult
            if eqn.primitive.name == "scan":
                sub_mult = mult * int(eqn.params.get("length", 1) or 1)
            for sub in _eqn_subjaxprs(eqn):
                walk(sub, sizes, sub_mult)

    walk(jaxpr, dict(default_axis_sizes or {}), 1)
    return out


def group_bytes(costs: Sequence[CollectiveCost],
                trip_adjusted: bool = False) -> Dict[str, int]:
    """``primitive@axis`` → summed payload bytes (ledger convention)."""
    out: Dict[str, int] = {}
    for c in costs:
        k = c.group
        out[k] = out.get(k, 0) + c.payload_bytes * (
            c.trip_count if trip_adjusted else 1)
    return out


# --------------------------------------------------------------------------
# peak live memory (liveness over the jaxpr)
# --------------------------------------------------------------------------

def peak_live_bytes(jx) -> int:
    """Peak byte total of simultaneously-live values in ``jx``.

    Linear-scan liveness: a var is live from its defining equation until
    its last use (outputs to the end).  A call equation contributes its
    sub-jaxpr's own peak minus the I/O already counted at this level.
    Inside ``shard_map`` bodies the avals are per-replica block shapes,
    so recursing through the shard_map equation yields the PER-REPLICA
    estimate the report publishes.  An estimate, not a simulation: XLA
    fusion/rematerialization can only lower it, donation lowers the
    input share — treat it as the no-fusion upper bound.
    """
    inner = _inner(jx)
    eqns = list(inner.eqns)
    last: Dict[Any, int] = {}
    for i, e in enumerate(eqns):
        for v in e.invars:
            if _is_var(v):
                last[v] = i
    for v in inner.outvars:
        if _is_var(v):
            last[v] = len(eqns)

    alive: Set[Any] = set()
    live = 0
    for v in list(inner.invars) + list(inner.constvars):
        if v in last and v not in alive:
            alive.add(v)
            live += _var_nbytes(v)
    peak = live
    for i, e in enumerate(eqns):
        subs = _eqn_subjaxprs(e)
        extra = 0
        if subs:
            io = (sum(_var_nbytes(v) for v in e.invars if _is_var(v))
                  + sum(_var_nbytes(v) for v in e.outvars))
            extra = max(0, max(peak_live_bytes(s) for s in subs) - io)
        for v in e.outvars:
            if v in last and v not in alive:
                alive.add(v)
                live += _var_nbytes(v)
        peak = max(peak, live + extra)
        for v in list(e.invars) + list(e.outvars):
            if _is_var(v) and v in alive and last.get(v, -1) <= i:
                alive.discard(v)
                live -= _var_nbytes(v)
    return peak


# --------------------------------------------------------------------------
# replication analysis (varying-axes propagation)
# --------------------------------------------------------------------------

def _propagate_vary(jx, in_vary: List[Set[str]],
                    record: Optional[List[Tuple[Any, Set[str]]]] = None
                    ) -> List[Set[str]]:
    """Propagate varying-axes sets through a (Closed)Jaxpr body.

    ``in_vary[i]`` is the set of mesh axes over which invar ``i`` is
    rank-varying (empty = replicated).  Returns the outvars' sets.
    Collective rules: reducing collectives (psum/pmax/pmin/all_gather)
    subtract their axes, redistributing ones (psum_scatter/ppermute/
    all_to_all) add them, ``axis_index`` introduces its axis; every
    other primitive unions its inputs.  Sub-jaxprs recurse; scan/while
    bodies run twice with the carry-out unioned in (a cheap fixed point
    in the ast-engine loop-twice spirit).  ``record`` (top level only)
    collects ``(eqn, out_vary)`` for the intermediates report.
    """
    inner = _inner(jx)
    vary: Dict[Any, Set[str]] = {}
    for v, s in zip(inner.invars, in_vary):
        vary[v] = set(s)
    for v in inner.constvars:
        vary[v] = set()

    def get(v) -> Set[str]:
        if not _is_var(v):
            return set()
        return vary.get(v, set())

    def run_sub(sub, eqn_invars, twice: bool = False) -> List[Set[str]]:
        sub_in = [get(v) for v in eqn_invars]
        si = _inner(sub)
        n = len(si.invars)
        sub_in = (sub_in + [set()] * n)[:n]
        out = _propagate_vary(sub, sub_in)
        if twice:
            # feed outputs back through positionally-matching inputs
            # (scan carries line up after num_consts; a union over ALL
            # positions is a safe over-approximation)
            fed = [set(s) for s in sub_in]
            for o in out:
                for f in fed:
                    f |= o
            out2 = _propagate_vary(sub, fed)
            out = [a | b for a, b in zip(out, out2)]
        return out

    for eqn in inner.eqns:
        name = _canon(eqn.primitive.name)
        base: Set[str] = set()
        for v in eqn.invars:
            base |= get(v)
        if name in _REDUCING_PRIMS:
            res = base - set(_axes_of(eqn.params))
            outs = [set(res) for _ in eqn.outvars]
        elif name in _VARYING_PRIMS:
            res = base | set(_axes_of(eqn.params))
            outs = [set(res) for _ in eqn.outvars]
        elif name == "axis_index":
            outs = [set(_axes_of(eqn.params)) for _ in eqn.outvars]
        elif name in ("pvary", "pcast", "pbroadcast"):
            res = base | set(_axes_of(eqn.params))
            outs = [set(res) for _ in eqn.outvars]
        elif name == "cond":
            branches = _subjaxprs(eqn.params.get("branches", ()))
            merged: Optional[List[Set[str]]] = None
            for br in branches:
                o = run_sub(br, eqn.invars[1:])
                merged = o if merged is None else [
                    a | b for a, b in zip(merged, o)]
            outs = merged or [set(base) for _ in eqn.outvars]
        elif name == "while":
            # invars = cond_consts + body_consts + carry, but the BODY
            # jaxpr's invars are body_consts + carry — a positional zip
            # over eqn.invars would feed the carry slots the cond
            # consts' (usually empty) sets and lose the carry's axes
            cn = int(eqn.params.get("cond_nconsts", 0))
            bn = int(eqn.params.get("body_nconsts", 0))
            body = eqn.params.get("body_jaxpr")
            body_in = [get(v) for v in eqn.invars[cn:]]
            if body is not None:
                out1 = _propagate_vary(body, body_in)
                # carry fixed point: body outvars ARE the carry, fed back
                fed = [set(s) for s in body_in]
                for i, o in enumerate(out1):
                    if bn + i < len(fed):
                        fed[bn + i] |= o
                out2 = _propagate_vary(body, fed)
                outs = [a | b for a, b in zip(out1, out2)]
            else:  # pragma: no cover - malformed eqn
                outs = [set(base) for _ in eqn.outvars]
        else:
            subs = _eqn_subjaxprs(eqn)
            if subs:
                # scan invars (consts + carry + xs) align positionally
                # with its jaxpr's invars; run twice with outputs
                # union-fed back for the carry fixed point
                twice = eqn.primitive.name == "scan"
                merged = None
                for sub in subs:
                    o = run_sub(sub, eqn.invars, twice=twice)
                    merged = o if merged is None else [
                        a | b for a, b in zip(merged, o)]
                outs = ([set(s) for s in merged]
                        + [set(base)] * len(eqn.outvars))[:len(eqn.outvars)]
            else:
                outs = [set(base) for _ in eqn.outvars]
        for v, s in zip(eqn.outvars, outs):
            if _is_var(v):
                vary[v] = s
        if record is not None:
            record.append((eqn, set().union(*outs) if outs else set()))
    return [get(v) for v in inner.outvars]


def _find_shard_maps(jaxpr) -> List[Tuple[Any, List[Optional[int]]]]:
    """All shard_map equations, each with a map from its invar positions
    to the OUTER jaxpr's flattened-argument leaf index (None where the
    value was produced by intermediate computation rather than passed
    straight through pjit/call boundaries)."""
    found: List[Tuple[Any, List[Optional[int]]]] = []

    def walk(jx, var_to_leaf: Dict[Any, int]):
        inner = _inner(jx)
        for eqn in inner.eqns:
            if eqn.primitive.name == "shard_map":
                found.append(
                    (eqn, [var_to_leaf.get(v) for v in eqn.invars]))
                continue
            subs = _eqn_subjaxprs(eqn)
            for sub in subs:
                si = _inner(sub)
                sub_map = {}
                for sv, ov in zip(si.invars, eqn.invars):
                    if _is_var(ov) and ov in var_to_leaf:
                        sub_map[sv] = var_to_leaf[ov]
                walk(sub, sub_map)

    outer = _inner(jaxpr)
    walk(jaxpr, {v: i for i, v in enumerate(outer.invars)})
    return found


def _leaf_labels(args: Sequence[Any],
                 arg_labels: Optional[Sequence[str]]) -> List[str]:
    """One label per flattened arg leaf: ``<arg_label><pytree path>``."""
    import jax

    leaves, _ = jax.tree_util.tree_flatten_with_path(tuple(args))
    labels = []
    for path, _leaf in leaves:
        idx = getattr(path[0], "idx", None)
        if arg_labels and idx is not None and idx < len(arg_labels):
            root = arg_labels[idx]
        else:
            root = f"arg{idx if idx is not None else '?'}"
        labels.append(root + jax.tree_util.keystr(path[1:]))
    return labels


def replication_report(jaxpr, args: Sequence[Any], data_axis: str,
                       arg_labels: Optional[Sequence[str]] = None
                       ) -> Dict[str, Any]:
    """Which argument leaves / intermediates are replicated across
    ``data_axis``?

    Arg replication is read off the shard_map bindings' ``in_names``
    (a leaf whose binding never splits a dimension over ``data_axis`` is
    fully materialized on every replica of that axis); intermediates come
    from varying-axes propagation through each shard_map body.  Returns::

        {"args": {root_label: {"replicated_bytes", "total_bytes",
                               "fully_replicated", "leaves": [...]}},
         "intermediates": [top-N largest replicated],
         "replicated_arg_bytes": total}
    """
    labels = _leaf_labels(args, arg_labels)
    leaf_info: Dict[int, Dict[str, Any]] = {}
    intermediates: List[Dict[str, Any]] = []

    for eqn, leaf_map in _find_shard_maps(jaxpr):
        in_names = eqn.params.get("in_names") or ()
        body = eqn.params.get("jaxpr")
        in_vary: List[Set[str]] = []
        for pos, names in enumerate(in_names):
            axes: Set[str] = set()
            for dim_axes in dict(names).values():
                axes.update(dim_axes if isinstance(dim_axes, (tuple, list))
                            else (dim_axes,))
            in_vary.append(axes)
            leaf = leaf_map[pos] if pos < len(leaf_map) else None
            if leaf is None:
                continue
            nbytes = _var_nbytes(eqn.invars[pos])
            info = leaf_info.setdefault(
                leaf, {"replicated": False, "nbytes": nbytes})
            if data_axis not in axes:
                info["replicated"] = True
        if body is not None:
            recs: List[Tuple[Any, Set[str]]] = []
            _propagate_vary(body, in_vary, record=recs)
            for sub_eqn, vset in recs:
                if data_axis in vset:
                    continue
                nbytes = sum(_var_nbytes(v) for v in sub_eqn.outvars)
                if nbytes <= 0:
                    continue
                aval = getattr(sub_eqn.outvars[0], "aval", None)
                intermediates.append({
                    "primitive": sub_eqn.primitive.name,
                    "shape": list(getattr(aval, "shape", ())),
                    "dtype": str(getattr(aval, "dtype", "?")),
                    "nbytes": nbytes,
                })

    groups: Dict[str, Dict[str, Any]] = {}
    for leaf, info in leaf_info.items():
        label = labels[leaf] if leaf < len(labels) else f"leaf{leaf}"
        root = label.split("[", 1)[0].split("/", 1)[0]
        g = groups.setdefault(root, {
            "replicated_bytes": 0, "total_bytes": 0,
            "fully_replicated": True, "leaves": []})
        g["total_bytes"] += info["nbytes"]
        if info["replicated"]:
            g["replicated_bytes"] += info["nbytes"]
            g["leaves"].append({"label": label, "nbytes": info["nbytes"]})
        else:
            g["fully_replicated"] = False
    for g in groups.values():
        g["fully_replicated"] = (g["fully_replicated"]
                                 and g["total_bytes"] > 0)

    intermediates.sort(key=lambda d: -d["nbytes"])
    return {
        "data_axis": data_axis,
        "args": groups,
        "intermediates": intermediates[:_TOP_INTERMEDIATES],
        "replicated_arg_bytes": sum(
            g["replicated_bytes"] for g in groups.values()),
    }


# --------------------------------------------------------------------------
# the per-entry-point analysis + reconciliation
# --------------------------------------------------------------------------

@dataclass
class ShardflowReport:
    """Everything the analyzer learned about one entry point."""

    name: str
    data_axis: Optional[str] = None
    costs: List[CollectiveCost] = field(default_factory=list)
    static_groups: Dict[str, int] = field(default_factory=dict)
    ledger_wrapped: Dict[str, int] = field(default_factory=dict)
    ledger_noted: Dict[str, int] = field(default_factory=dict)
    expected_static: Dict[str, int] = field(default_factory=dict)
    replication: Dict[str, Any] = field(default_factory=dict)
    peak_live_bytes: Optional[int] = None
    reconciled: Optional[bool] = None
    error: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "data_axis": self.data_axis,
            "costs": [c.to_dict() for c in self.costs],
            "static_groups": dict(self.static_groups),
            "ledger_wrapped": dict(self.ledger_wrapped),
            "ledger_noted": dict(self.ledger_noted),
            "expected_static": dict(self.expected_static),
            "replication": self.replication,
            "peak_live_bytes": self.peak_live_bytes,
            "reconciled": self.reconciled,
            "error": self.error,
        }


def _ledger_groups(rows: Dict[str, Dict[str, Any]]
                   ) -> Tuple[Dict[str, int], Dict[str, int]]:
    """Split ledger rows into (wrapped per primitive-group, noted per raw
    row key), mapping wrapper op names onto canonical primitives via
    ``ops.collective.LEDGER_TO_PRIMITIVE``.  Rows aggregate per
    ``op@axis`` and may mix wrapped calls with ``comm.note`` bookings —
    the accountant keeps the noted share in ``noted_bytes``, so the
    split is exact even on a shared key."""
    from chainermn_tpu.ops.collective import LEDGER_TO_PRIMITIVE

    wrapped: Dict[str, int] = {}
    noted: Dict[str, int] = {}
    for key, row in rows.items():
        op, _, axis = key.partition("@")
        noted_part = int(row.get("noted_bytes", 0))
        wrapped_part = int(row["bytes"]) - noted_part
        if noted_part:
            noted[key] = noted.get(key, 0) + noted_part
        if wrapped_part:
            prim = LEDGER_TO_PRIMITIVE.get(op, _canon(op))
            if prim is None:
                # composite op (quantized ring): its equations are the
                # wire-dtype ppermute/psum schedule — reconciled via
                # quantized_ring_cost by a declaring entry point; an
                # UNDECLARED composite row surfaces as a group mismatch.
                prim = op
            group = f"{prim}@{axis}"
            wrapped[group] = wrapped.get(group, 0) + wrapped_part
    return wrapped, noted


def _run_under_ledger(fn, args, name: str) -> Dict[str, Dict[str, Any]]:
    """Execute ``fn(*args)`` freshly-compiled under the accounting layer,
    returning the per-op rows booked by exactly this run.  Prior
    process-global observability state is restored afterwards (the lint
    tier shares its pytest process with the whole suite)."""
    import jax

    from chainermn_tpu import observability as obs
    from chainermn_tpu.observability.comm import get_accountant

    was_enabled = obs.enabled()
    obs.enable()
    acct = get_accountant()
    try:
        with acct.step(("shardflow", name)):
            out = fn(*args)
            jax.tree_util.tree_map(
                lambda x: getattr(x, "block_until_ready", lambda: x)(), out)
        report = acct.last_step_report or {}
        return dict(report.get("per_op", {}))
    finally:
        if not was_enabled:
            obs.disable()


def analyze_entrypoint(ep, reconcile: bool = True,
                       pad_tolerance: int = 0
                       ) -> Tuple[List[Finding], ShardflowReport]:
    """Full shard-flow analysis of one registered entry point."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from chainermn_tpu._compat import ad_inserts_replicated_psum

    report = ShardflowReport(name=ep.name)
    findings: List[Finding] = []
    loc = f"entrypoint:{ep.name}"

    def fail(stage: str, e: BaseException):
        report.error = f"{stage} failed: {type(e).__name__}: {e}"
        findings.append(Finding(
            rule="shardflow-error", severity="error", path=loc, line=0,
            message=report.error, context=ep.name, snippet=ep.description))

    try:
        spec = ep.build()
    except Exception as e:  # noqa: BLE001
        fail("build", e)
        return findings, report

    fn, args = spec["trace"]
    data_axis = spec.get("data_axis")
    report.data_axis = data_axis
    expected_repl: Dict[str, str] = dict(spec.get("expected_replication", {}))

    # ---- dynamic side FIRST: a fresh build's compile must land inside
    # the accounting bracket (in-jit bookings happen at trace time) ----
    rows: Dict[str, Dict[str, Any]] = {}
    if reconcile:
        try:
            rows = _run_under_ledger(fn, args, ep.name)
        except Exception as e:  # noqa: BLE001
            fail("ledger run", e)
            return findings, report

    # ---- static side ----
    try:
        jaxpr = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # noqa: BLE001
        fail("trace", e)
        return findings, report

    report.costs = static_costs(jaxpr)
    report.static_groups = group_bytes(report.costs)
    try:
        report.peak_live_bytes = peak_live_bytes(jaxpr)
    except Exception as e:  # noqa: BLE001  pragma: no cover
        report.error = f"liveness failed: {type(e).__name__}: {e}"

    # ---- replication report + findings ----
    if data_axis:
        try:
            report.replication = replication_report(
                jaxpr, args, data_axis, spec.get("arg_labels"))
        except Exception as e:  # noqa: BLE001
            fail("replication analysis", e)
            return findings, report
        groups = report.replication.get("args", {})
        for root, g in sorted(groups.items()):
            if g["replicated_bytes"] <= 0:
                continue
            if root in expected_repl:
                g["expected"] = expected_repl[root]
                continue
            full = "fully" if g["fully_replicated"] else "partially"
            findings.append(Finding(
                rule="unexpected-replication", severity="warning",
                path=loc, line=0, context=root,
                message=(
                    f"argument `{root}` is {full} replicated across data "
                    f"axis '{data_axis}' ({g['replicated_bytes']} of "
                    f"{g['total_bytes']} bytes on EVERY replica) — shard "
                    "it, or declare expected_replication with the reason "
                    "(entrypoints.py)"),
                snippet=f"replicated:{root}"))
        for root, reason in sorted(expected_repl.items()):
            g = groups.get(root)
            if g is None or g["replicated_bytes"] <= 0:
                findings.append(Finding(
                    rule="stale-replication-annotation", severity="warning",
                    path=loc, line=0, context=root,
                    message=(
                        f"expected_replication[{root!r}] ({reason!r}) no "
                        "longer matches a replicated argument — the "
                        "sharding landed; delete the annotation so the "
                        "report shows the red→green diff"),
                    snippet=f"expected:{root}"))

    # ---- static↔dynamic reconciliation ----
    if reconcile:
        wrapped, noted = _ledger_groups(rows)
        report.ledger_wrapped = wrapped
        report.ledger_noted = noted

        declared_noted: Dict[str, int] = dict(spec.get("noted", {}))
        ad_extra: Dict[str, int] = dict(spec.get("ad_transpose_bytes", {}))
        vma = ad_inserts_replicated_psum()

        expected: Dict[str, int] = dict(wrapped)

        # COMPOSITE rows (LEDGER_TO_PRIMITIVE → None, e.g. the quantized
        # int8 ring): the entry declares, per ledger row, (a) the bytes
        # the accountant must have booked for it (the compressed-wire
        # ledger convention) and (b) the per-primitive-group payload
        # bytes its hand-written schedule puts in the traced program
        # (``ops.collective.quantized_ring_static_groups``).  The row is
        # swapped for its equation groups before the comparison, so the
        # schedule is held byte-exact like any wrapped collective.
        composite_ok = True
        for key, decl in sorted(dict(spec.get("composite", {})).items()):
            booked = expected.pop(key, 0)
            want_row = int(decl.get("ledger_bytes", 0))
            if booked != want_row:
                composite_ok = False
                findings.append(Finding(
                    rule="comm-ledger-gap", severity="error", path=loc,
                    line=0, context=ep.name,
                    message=(
                        f"composite ledger row `{key}` books {booked} "
                        f"bytes but the entry point declares {want_row} "
                        "— the compressed-wire convention and the "
                        "declaration drifted apart"),
                    snippet=f"composite:{key}"))
            for g, b in dict(decl.get("static_groups", {})).items():
                expected[g] = expected.get(g, 0) + int(b)
        if not vma:
            # legacy jax: transpose(psum) is a psum — declared equations
            # the ledger cannot book
            for g, b in ad_extra.items():
                expected[g] = expected.get(g, 0) + int(b)
        else:
            # vma jax: the noted (AD-inserted) collectives ARE equations
            from chainermn_tpu.ops.collective import LEDGER_TO_PRIMITIVE
            for key, b in declared_noted.items():
                op, _, axis = key.partition("@")
                prim = LEDGER_TO_PRIMITIVE.get(op, _canon(op)) or op
                g = f"{prim}@{axis}"
                expected[g] = expected.get(g, 0) + int(b)
        report.expected_static = expected

        ok = composite_ok
        for g in sorted(set(expected) | set(report.static_groups)):
            want = expected.get(g, 0)
            got = report.static_groups.get(g, 0)
            if abs(want - got) > pad_tolerance:
                ok = False
                findings.append(Finding(
                    rule="comm-ledger-gap", severity="error", path=loc,
                    line=0, context=ep.name,
                    message=(
                        f"collective group `{g}`: traced program carries "
                        f"{got} payload bytes but the runtime ledger "
                        f"accounts for {want} (wrapped "
                        f"{wrapped.get(g, 0)}"
                        + (f" + declared AD-transpose {ad_extra[g]}"
                           if not vma and g in ad_extra else "")
                        + ") — the static cost model rotted, or a "
                        "collective on this path bypasses the accounted "
                        "face (ops.collective)"),
                    snippet=f"group:{g}"))
        for key, brow in sorted(noted.items()):
            want = declared_noted.get(key)
            if want is None:
                ok = False
                findings.append(Finding(
                    rule="comm-ledger-gap", severity="error", path=loc,
                    line=0, context=ep.name,
                    message=(
                        f"noted ledger row `{key}` ({brow} bytes) has no "
                        "declaration on this entry point — declare it in "
                        "the build spec's `noted` dict (with the bytes) "
                        "so the reconciliation can hold it to account"),
                    snippet=f"noted:{key}"))
            elif abs(int(want) - brow) > pad_tolerance:
                ok = False
                findings.append(Finding(
                    rule="comm-ledger-gap", severity="error", path=loc,
                    line=0, context=ep.name,
                    message=(
                        f"noted ledger row `{key}` books {brow} bytes but "
                        f"the entry point declares {want} — the note in "
                        "the builder and the declaration drifted apart"),
                    snippet=f"noted:{key}"))
        for key, want in sorted(declared_noted.items()):
            if key not in noted:
                ok = False
                findings.append(Finding(
                    rule="comm-ledger-gap", severity="error", path=loc,
                    line=0, context=ep.name,
                    message=(
                        f"declared noted collective `{key}` ({want} "
                        "bytes) was never booked by the run — the "
                        "builder's comm.note disappeared; update the "
                        "declaration"),
                    snippet=f"noted:{key}"))
        report.reconciled = ok

    return findings, report


def analyze_entrypoints(eps: Optional[Sequence[Any]] = None,
                        reconcile: bool = True
                        ) -> Tuple[List[Finding], List[ShardflowReport]]:
    """Shard-flow analysis over registered entry points (default: all).

    Entry points registered with ``shardflow=False`` are skipped — the
    observability-tee variants re-run the very same compiled programs
    their base entries already analyze."""
    if eps is None:
        from .entrypoints import ENTRYPOINTS
        eps = ENTRYPOINTS
    findings: List[Finding] = []
    reports: List[ShardflowReport] = []
    for ep in eps:
        if not getattr(ep, "shardflow", True):
            continue
        f, r = analyze_entrypoint(ep, reconcile=reconcile)
        findings.extend(f)
        reports.append(r)
    return findings, reports


# --------------------------------------------------------------------------
# runner (scripts/shardflow_report.py / python -m chainermn_tpu.analysis.shardflow)
# --------------------------------------------------------------------------

def find_shardflow_baseline(start: Optional[str] = None) -> Optional[str]:
    """Nearest ``.shardflow-baseline.json`` at or above ``start``
    (default: the package checkout root) — the one upward walk of
    ``findings.find_baseline``, parameterized by filename."""
    from .findings import find_baseline

    d = start or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return find_baseline(d, filename=SHARDFLOW_BASELINE_FILENAME)


def _select_entrypoints(names: Optional[Sequence[str]]):
    from .entrypoints import select_entrypoints

    return select_entrypoints(names, for_shardflow=True)


def _render_report(r: ShardflowReport) -> str:
    lines = [f"== {r.name} (data axis: {r.data_axis or '-'}) =="]
    if r.error:
        lines.append(f"  ERROR: {r.error}")
    if r.reconciled is not None:
        lines.append("  static<->ledger: "
                     + ("RECONCILED" if r.reconciled else "MISMATCH"))
    for g in sorted(set(r.static_groups) | set(r.expected_static)):
        lines.append(
            f"    {g:28s} static {r.static_groups.get(g, 0):>10d} B   "
            f"ledger-expected {r.expected_static.get(g, 0):>10d} B")
    for k, b in sorted(r.ledger_noted.items()):
        lines.append(f"    {k:28s} noted  {b:>10d} B (declared)")
    phys = sum(c.wire_bytes * c.trip_count for c in r.costs)
    msgs = sum(c.messages * c.trip_count for c in r.costs)
    lines.append(f"  physical wire estimate: {phys} B, {msgs} messages "
                 f"(ring decomposition at the traced axis sizes)")
    if r.peak_live_bytes is not None:
        lines.append(f"  peak live memory / replica: {r.peak_live_bytes} B "
                     "(liveness upper bound, pre-fusion)")
    repl = r.replication or {}
    for root, g in sorted(repl.get("args", {}).items()):
        mark = ("expected: " + g["expected"] if "expected" in g
                else ("REPLICATED" if g["replicated_bytes"] else "sharded"))
        lines.append(
            f"    arg {root:12s} {g['replicated_bytes']:>8d}/"
            f"{g['total_bytes']:<8d} B replicated  [{mark}]")
    for it in repl.get("intermediates", []):
        lines.append(
            f"    intermediate {it['primitive']:16s} "
            f"{tuple(it['shape'])!s:14s} {it['dtype']:9s} "
            f"{it['nbytes']} B replicated")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """Shard-flow report runner.  Exit contract (the
    ``check_perf_regression.py`` / ``lint_spmd.py`` contract): 0 = clean
    modulo baseline, 1 = findings, 2 = unusable inputs."""
    import argparse
    import json
    import sys

    from .baseline import BaselineGate

    p = argparse.ArgumentParser(
        prog="python scripts/shardflow_report.py",
        description="Shard-flow analyzer: static sharding/memory/"
                    "collective-cost model reconciled against the "
                    "runtime comm ledger (docs/ANALYSIS.md)")
    p.add_argument("--entry", action="append", default=None,
                   help="restrict to one registered entry point (repeat "
                        "for several; default: all)")
    p.add_argument("--list-entrypoints", action="store_true",
                   help="print the registered entry points and exit")
    p.add_argument("--json", action="store_true",
                   help="one machine-readable JSON document on stdout")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: nearest "
                        f"{SHARDFLOW_BASELINE_FILENAME} above the repo)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline: report everything")
    p.add_argument("--fix-baseline", action="store_true",
                   help="regenerate the baseline from current findings "
                        "(keeps existing comments; entries for entry "
                        "points not selected via --entry are carried "
                        "over untouched)")
    args = p.parse_args(argv)

    if args.list_entrypoints:
        from .entrypoints import ENTRYPOINTS
        for ep in ENTRYPOINTS:
            tag = "" if getattr(ep, "shardflow", True) else "  [shardflow: skipped]"
            print(f"{ep.name:36s} {ep.description}{tag}")
        return 0

    eps, err = _select_entrypoints(args.entry)
    if err:
        print(f"error: {err}", file=sys.stderr)
        return 2

    findings, reports = analyze_entrypoints(eps)

    gate = BaselineGate(args.baseline or find_shardflow_baseline(),
                        enabled=not args.no_baseline)
    err = gate.load()
    if err:
        print(f"error: {err}", file=sys.stderr)
        return 2

    if args.fix_baseline:
        analyzed = {f"entrypoint:{r.name}" for r in reports}
        gate.fix(findings,
                 in_scope=lambda e: e["path"] in analyzed,
                 default_target=SHARDFLOW_BASELINE_FILENAME)
        return 0

    findings, accepted = gate.filter(findings)

    if args.json:
        print(json.dumps({
            "schema": SHARDFLOW_SCHEMA,
            "baseline": gate.path if gate.baseline is not None else None,
            "n_accepted_by_baseline": len(accepted),
            "findings": [f.to_dict() for f in findings],
            "reports": [r.to_dict() for r in reports],
        }, indent=2))
    else:
        for r in reports:
            print(_render_report(r))
        for f in findings:
            print(f.render())
        sev: Dict[str, int] = {}
        for f in findings:
            sev[f.severity] = sev.get(f.severity, 0) + 1
        tally = ", ".join(f"{n} {s}" for s, n in sorted(sev.items())) \
            or "no findings"
        extra = (f" ({len(accepted)} accepted by baseline)"
                 if accepted else "")
        print(f"shardflow: {tally}{extra} over {len(reports)} "
              f"entry point(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":  # pragma: no cover - python -m face
    import sys

    sys.exit(main())
