"""The collective surface, *derived* from source — not hardcoded strings.

Rules need to know "what is a collective" for three vocabularies:

* the in-jit face: public functions of ``chainermn_tpu/ops/collective.py``
  (parsed from its AST, minus the explicitly non-communicating helpers);
* the eager face: ``CommunicatorBase`` collectives — read out of
  ``communicators/base.py``'s ``_ACCOUNTED_OPS`` literal plus the
  ``*_obj`` pickle-lane methods defined on the class;
* the raw ``jax.lax`` primitives those lower to.

Parsing (not importing) keeps the AST engine jax-free and means a new
collective added to ``ops/collective.py`` is linted the day it lands —
the same closure property the observability accounting test enforces
(tests/test_observability_fleet.py's completeness guard).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import FrozenSet, Optional

#: ops/collective.py defs that are *not* gang-synchronizing: helpers,
#: per-rank queries, and the static cost-model faces.  Everything else
#: public in that module is treated as a collective.  (axis_index/
#: axis_size read topology, they don't sync; the *_cost functions are
#: pure arithmetic the shard-flow analyzer and bench share.)
_NON_COLLECTIVE_OPS = frozenset({
    "zeros_like_vma", "axis_index", "axis_size",
    "collective_wire_cost", "quantized_ring_cost",
    "quantized_ring_static_groups", "choose_pipeline_depth",
    "block_quantize", "block_dequantize",
})

#: jax.lax collective primitives (the fixed upstream vocabulary the named
#: wrappers lower onto).
JAX_LAX_COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pshuffle", "pswapaxes",
})

#: Expressions whose value differs per rank/process — the taint sources
#: for the collective-deadlock rule.  Attribute tails (``comm.rank``) and
#: call names (``jax.process_index()``) both match by final identifier.
RANK_ATTRS = frozenset({"rank", "intra_rank", "inter_rank"})
RANK_CALLS = frozenset({"axis_index", "process_index"})


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _parse(path: str) -> Optional[ast.Module]:
    try:
        with open(path) as fh:
            return ast.parse(fh.read(), filename=path)
    except (OSError, SyntaxError):
        return None


def _public_defs(tree: ast.Module) -> FrozenSet[str]:
    return frozenset(
        n.name for n in tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and not n.name.startswith("_"))


def _accounted_ops(tree: ast.Module) -> FrozenSet[str]:
    """Evaluate the ``_ACCOUNTED_OPS = (...)`` literal in base.py."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_ACCOUNTED_OPS"):
            try:
                val = ast.literal_eval(node.value)
            except ValueError:
                continue
            return frozenset(v for v in val if isinstance(v, str))
    return frozenset()


def _obj_lane_methods(tree: ast.Module) -> FrozenSet[str]:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "CommunicatorBase":
            for item in node.body:
                if (isinstance(item, ast.FunctionDef)
                        and item.name.endswith("_obj")):
                    out.add(item.name)
    return frozenset(out)


@dataclass(frozen=True)
class CollectiveRegistry:
    """Names the whole collective surface for the rules."""

    ops_collectives: FrozenSet[str]       # ops/collective.py public defs
    comm_methods: FrozenSet[str]          # CommunicatorBase collectives
    lax_collectives: FrozenSet[str] = JAX_LAX_COLLECTIVES
    rank_attrs: FrozenSet[str] = RANK_ATTRS
    rank_calls: FrozenSet[str] = RANK_CALLS
    extra: FrozenSet[str] = field(default_factory=frozenset)

    @property
    def all_collective_names(self) -> FrozenSet[str]:
        """Every identifier that, used as the called name (``psum(...)``)
        or attribute tail (``comm.allreduce(...)``), marks a collective."""
        return (self.ops_collectives | self.comm_methods
                | self.lax_collectives | self.extra)

    def is_collective_call(self, node) -> bool:
        """True when an ``ast.Call``'s target names a collective."""
        import ast as _ast
        fn = node.func
        if isinstance(fn, _ast.Name):
            return fn.id in self.all_collective_names
        if isinstance(fn, _ast.Attribute):
            return fn.attr in self.all_collective_names
        return False


def default_registry(package_root: Optional[str] = None) -> CollectiveRegistry:
    """Build the registry from the shipped sources.  Falls back to a
    minimal lax-only registry when the sources are missing (running the
    engine against a foreign tree is still useful)."""
    root = package_root or _package_root()
    ops_names: FrozenSet[str] = frozenset()
    comm_names: FrozenSet[str] = frozenset()

    ops_tree = _parse(os.path.join(root, "ops", "collective.py"))
    if ops_tree is not None:
        ops_names = _public_defs(ops_tree) - _NON_COLLECTIVE_OPS

    base_tree = _parse(os.path.join(root, "communicators", "base.py"))
    if base_tree is not None:
        comm_names = _accounted_ops(base_tree) | _obj_lane_methods(base_tree)

    return CollectiveRegistry(ops_collectives=ops_names,
                              comm_methods=comm_names)
