"""chainermn_tpu.analysis — SPMD-aware static analyzer for JAX code.

The MPI heritage of this codebase makes collective *ordering and symmetry*
a correctness invariant: a collective executed under rank-dependent control
flow deadlocks the gang (SURVEY.md §3.2), a reused PRNG key silently draws
identical samples (the PR 3 rng trap), and a zero-copy ``asarray`` of a
host buffer that is later mutated in place races async dispatch (the PR 3
serving pos-vector bug).  This package catches that family mechanically.

Two complementary engines:

* **AST engine** (``ast_engine``) — pure stdlib ``ast``; no JAX import
  required, so it runs on any box that can read Python.  Rules:
  collective-deadlock, prng-constant-key, prng-key-reuse, host-alias-race,
  traced-control-flow, inplace-jit-mutation.
* **jaxpr engine** (``jaxpr_engine``) — traces *registered entry points*
  (``entrypoints.py``, tiny shapes, CPU backend) and checks the extracted
  collective sequence for axis names absent from the enclosing mesh spec
  (unbound-axis) and for recompilation hazards (recompile-hazard, with an
  explicit allowlist for the per-prompt-length prefill programs).

A third pass builds on the jaxpr engine: the **shard-flow analyzer**
(``shardflow``) propagates sharding through every registered entry point
to produce a replication report (what is fully materialized per replica
— the ZeRO-1 target), a static collective cost model (wire bytes +
message counts), and a peak-live-memory-per-replica estimate — and
RECONCILES the static predictions against the runtime comm ledger by
executing each entry point under the PR 1 accounting layer (exact byte
equality; the cost model can never silently rot).  Runner:
``scripts/shardflow_report.py`` / ``python -m
chainermn_tpu.analysis.shardflow``; baseline:
``.shardflow-baseline.json``.

The collective surface is *derived*, not hardcoded: ``registry.py`` parses
``ops/collective.py`` and ``communicators/base.py`` so new collectives are
linted the day they land.

Runners: ``python -m chainermn_tpu.analysis <paths>`` and
``scripts/lint_spmd.py`` (exit 0 clean / 1 findings / 2 unusable — the
``check_perf_regression.py`` contract).  Accepted findings live in the
checked-in baseline (``.spmd-lint-baseline.json``); one-off exceptions use
``# spmd-lint: disable=<rule>`` inline.  See docs/ANALYSIS.md.

This module must stay importable WITHOUT jax: only stdlib + relative
imports at top level (``jaxpr_engine`` imports jax lazily).
"""

from .findings import (  # noqa: F401
    Baseline,
    Finding,
    SEVERITIES,
    load_baseline,
)
from .registry import CollectiveRegistry, default_registry  # noqa: F401
from .ast_engine import (  # noqa: F401
    AST_RULES,
    analyze_file,
    analyze_paths,
    analyze_source,
)
from .shardflow import (  # noqa: F401  (stdlib-only at import time)
    SHARDFLOW_RULES,
    ShardflowReport,
)
from .concurrency import CONCURRENCY_RULES  # noqa: F401  (stdlib-only)
from .protocol import ALL_MODELS as PROTOCOL_MODELS  # noqa: F401
from .schedule import (  # noqa: F401  (stdlib+numpy only)
    CALIBRATION_SCHEMA,
    GENERATORS as SCHEDULE_GENERATORS,
    Schedule,
    Topology,
)
from .schedule_check import (  # noqa: F401
    FLEET_PAIRS,
    SCHEDULE_EXEC_SCHEMA,
    SEEDED_FAULTS,
    ScheduleExecProfile,
    verify_schedule,
)
from .calibrate import (  # noqa: F401  (stdlib+numpy only)
    drift_report,
    fit_calibration,
    load_calibration,
    schedule_critical_path,
)

__all__ = [
    "AST_RULES",
    "Baseline",
    "CALIBRATION_SCHEMA",
    "CONCURRENCY_RULES",
    "CollectiveRegistry",
    "FLEET_PAIRS",
    "Finding",
    "PROTOCOL_MODELS",
    "SCHEDULE_EXEC_SCHEMA",
    "SCHEDULE_GENERATORS",
    "SEEDED_FAULTS",
    "SEVERITIES",
    "SHARDFLOW_RULES",
    "Schedule",
    "ScheduleExecProfile",
    "ShardflowReport",
    "Topology",
    "analyze_file",
    "analyze_paths",
    "analyze_source",
    "default_registry",
    "drift_report",
    "fit_calibration",
    "load_baseline",
    "load_calibration",
    "schedule_critical_path",
    "verify_schedule",
]
