"""Static verifier for collective schedule IR — no schedule runs unverified.

Three independent proofs per :class:`~.schedule.Schedule`, in order:

1. **Structural + byte-coverage/permutation** (static, numpy): every op
   and transfer is well-formed, and reconciling against the same
   ``np.array_split`` block math :func:`reshard_host
   <chainermn_tpu.parallel.reshard.reshard_host>` / the shardflow
   statics use, every destination element is written EXACTLY once and
   each written run carries exactly the global elements the destination
   block expects at that offset (wrong-source and permutation bugs are
   the same violation: a global-index mismatch).
2. **Exhaustive BFS model check** (reusing :mod:`.protocol`): the
   schedule's start/done machine is explored under ALL rank
   interleavings for deadlock-freedom, staging-fence ordering
   (start-forwarding-before-landing), and buffer-bound safety
   (outstanding transfers at any rank never exceed the declared
   landing capacity).  Violations come back as minimal counterexample
   traces, PR 15 style.  Delivery timing is absorbed into scheduling
   freedom (``done`` is enabled once the matching ``start`` has
   executed; delaying a delivery is the same as the destination rank
   simply not being scheduled) — sound here because no invariant
   observes in-flight vs landed, and it keeps the state space at the
   product of program counters.
3. **Deterministic interpreter**: the schedule executes on host numpy
   buffers and the result must be byte-exact against the direct
   spec-sliced oracle — this is the execution engine
   ``reshard_host(..., schedule=)`` swaps in, so "verified" and "what
   actually runs" are the same code path.

Seeded-fault mutators (:data:`SEEDED_FAULTS`) produce the broken
candidates the fixture corpus pins at 0 FN / 0 FP: dropped chunk,
double write, send/recv cycle, done-before-start, buffer overrun.

Runner: ``python -m chainermn_tpu.analysis.schedule_check`` verifies
every (src,dst) spec pair reachable from elastic resume, ``heal()``
live shrink, and ``rolling_upgrade()`` (:data:`FLEET_PAIRS`), exits
0/1/2 (the lint contract).
"""

from __future__ import annotations

import argparse
import copy
import itertools
import json
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import protocol
from .schedule import (
    Chunk, CostModel, Op, Schedule, Topology, Transfer,
    block_global_indices, block_shape, candidate_schedules,
    price_schedule,
)

__all__ = [
    "VerifyResult", "structural_check", "coverage_check",
    "make_schedule_model", "run_schedule", "make_input_blocks",
    "expected_output_blocks", "verify_schedule", "seed_fault",
    "SEEDED_FAULTS", "compile_verified", "verified_schedule",
    "SCHEDULE_EXEC_SCHEMA", "ScheduleExecProfile", "execute_profiled",
    "FLEET_PAIRS",
    "fleet_pair_topology", "main",
]


# --------------------------------------------------------------------------
# phase 1: structural + coverage
# --------------------------------------------------------------------------

def _block_elems(sched: Schedule, spec, rank: int, world: int) -> int:
    return int(np.prod(block_shape(sched.shape, spec, rank, world)))


def structural_check(sched: Schedule) -> List[str]:
    v: List[str] = []
    topo = sched.topology
    starts: Dict[str, int] = {}
    dones: Dict[str, int] = {}
    for r, prog in sched.programs.items():
        for op in prog:
            if op.kind == "reduce":
                v.append(f"structural: r{r} {op.render()} — reduce ops "
                         f"are reserved for the item-5 allreduce plane "
                         f"and not yet verifiable")
            elif op.kind in ("copy", "unstage"):
                c = sched.chunks.get(op.arg)
                if c is None:
                    v.append(f"structural: r{r} {op.render()} names an "
                             f"unknown chunk")
                    continue
                if op.kind == "copy" and not (c.src_rank == c.dst_rank
                                              == r):
                    v.append(f"structural: r{r} copy({c.name}) but the "
                             f"chunk is r{c.src_rank}->r{c.dst_rank}")
                if op.kind == "unstage" and c.dst_rank != r:
                    v.append(f"structural: r{r} unstage({c.name}) but "
                             f"the chunk lands at r{c.dst_rank}")
            elif op.kind in ("start", "done"):
                t = sched.transfers.get(op.arg)
                if t is None:
                    v.append(f"structural: r{r} {op.render()} names an "
                             f"unknown transfer")
                    continue
                side = starts if op.kind == "start" else dones
                side[op.arg] = side.get(op.arg, 0) + 1
                want = t.src if op.kind == "start" else t.dst
                if r != want:
                    v.append(f"structural: {op.render()} executed on "
                             f"r{r}, belongs to r{want}")
            else:
                v.append(f"structural: unknown op kind {op.kind!r}")
    for tid, t in sorted(sched.transfers.items()):
        c = sched.chunks.get(t.chunk)
        if c is None:
            v.append(f"structural: transfer {tid} names unknown chunk "
                     f"{t.chunk!r}")
            continue
        if t.src == t.dst:
            v.append(f"structural: transfer {tid} is a self-send")
            continue
        if t.link != topo.link(t.src, t.dst):
            v.append(f"structural: transfer {tid} declares link "
                     f"{t.link} but r{t.src}->r{t.dst} is "
                     f"{topo.link(t.src, t.dst)}")
        if t.dest == "out" and t.dst != c.dst_rank:
            v.append(f"structural: transfer {tid} lands chunk "
                     f"{c.name} at r{t.dst}, chunk wants "
                     f"r{c.dst_rank}")
        if t.via is None:
            if c.src_rank != t.src:
                v.append(f"structural: transfer {tid} gathers chunk "
                         f"{c.name} from r{t.src}'s in-block but the "
                         f"chunk is sourced at r{c.src_rank}")
        else:
            via = sched.chunks.get(t.via)
            if via is None:
                v.append(f"structural: transfer {tid} forwards "
                         f"unknown chunk {t.via!r}")
            elif (via.src_rank != c.src_rank
                  or via.src_side() != c.src_side()):
                v.append(f"structural: transfer {tid} forwards staged "
                         f"chunk {t.via} as {c.name} but their source "
                         f"projections differ — staging may not "
                         f"substitute bytes")
        if starts.get(tid, 0) != 1 or dones.get(tid, 0) != 1:
            v.append(f"structural: transfer {tid} needs exactly one "
                     f"start and one done "
                     f"(has {starts.get(tid, 0)}/{dones.get(tid, 0)})")
    for c in sched.chunks.values():
        s_elems = _block_elems(sched, sched.src_spec, c.src_rank,
                               sched.src_world) \
            if c.src_rank < sched.src_world else None
        d_elems = _block_elems(sched, sched.dst_spec, c.dst_rank,
                               sched.dst_world) \
            if c.dst_rank < sched.dst_world else None
        if s_elems is None:
            v.append(f"structural: chunk {c.name} sourced at r"
                     f"{c.src_rank} outside src world "
                     f"{sched.src_world}")
            continue
        if d_elems is None:
            v.append(f"structural: chunk {c.name} lands at r"
                     f"{c.dst_rank} outside dst world "
                     f"{sched.dst_world}")
            continue
        for so, do, n in c.segments:
            if n <= 0 or so < 0 or do < 0 or so + n > s_elems \
                    or do + n > d_elems:
                v.append(f"structural: chunk {c.name} segment "
                         f"({so},{do},{n}) out of block bounds "
                         f"(src {s_elems}, dst {d_elems})")
    if sched.max_inflight < 1:
        v.append("structural: max_inflight must be >= 1")
    return v


def coverage_check(sched: Schedule) -> List[str]:
    """Every destination element written exactly once, from the right
    source: each landed run's source global indices must equal the
    destination block's expected global indices at that offset."""
    v: List[str] = []
    gsrc = {s: block_global_indices(sched.shape, sched.src_spec, s,
                                    sched.src_world)
            for s in range(sched.src_world)}
    gdst = {d: block_global_indices(sched.shape, sched.dst_spec, d,
                                    sched.dst_world)
            for d in range(sched.dst_world)}
    cover = {d: np.zeros(len(gdst[d]), dtype=np.int32)
             for d in range(sched.dst_world)}

    def land(chunk_name: str, what: str):
        c = sched.chunks.get(chunk_name)
        if c is None or c.src_rank >= sched.src_world \
                or c.dst_rank >= sched.dst_world:
            return  # structural_check already reported
        for so, do, n in c.segments:
            if so + n > len(gsrc[c.src_rank]) \
                    or do + n > len(gdst[c.dst_rank]):
                return  # structural bound violation already reported
            if not np.array_equal(gsrc[c.src_rank][so:so + n],
                                  gdst[c.dst_rank][do:do + n]):
                v.append(
                    f"coverage: {what} chunk {c.name} segment "
                    f"({so},{do},{n}) moves the wrong global elements "
                    f"(permutation/source mismatch vs the "
                    f"array_split statics)")
            cover[c.dst_rank][do:do + n] += 1

    for r, prog in sched.programs.items():
        for op in prog:
            if op.kind in ("copy", "unstage"):
                land(op.arg, f"r{r} {op.kind}")
    for t in sched.transfers.values():
        if t.dest == "out":
            land(t.chunk, f"transfer {t.tid}")
    for d in range(sched.dst_world):
        cnt = cover[d]
        missing = int((cnt == 0).sum())
        if missing:
            first = int(np.argmax(cnt == 0))
            v.append(f"coverage: r{d} has {missing} destination "
                     f"element(s) never written (first gap at local "
                     f"offset {first}) — dropped chunk")
        dup = int((cnt > 1).sum())
        if dup:
            first = int(np.argmax(cnt > 1))
            v.append(f"coverage: r{d} has {dup} destination "
                     f"element(s) written more than once (first at "
                     f"local offset {first}) — double write")
    return v


# --------------------------------------------------------------------------
# phase 2: exhaustive BFS model check (protocol.py machinery)
# --------------------------------------------------------------------------

def make_schedule_model(sched: Schedule) -> protocol.Model:
    """The schedule's start/done machine as a :class:`protocol.Model`.

    State = (pc_0, ..., pc_{n-1}, violation) — one program counter per
    rank plus a sticky violation description.  Every rank interleaving
    is explored; ``done(t)`` is enabled once ``start(t)`` has executed
    anywhere (see module docstring for why that abstraction is sound).
    """
    ranks = sorted(sched.programs)
    rix = {r: i for i, r in enumerate(ranks)}
    progs = {r: list(sched.programs[r]) for r in ranks}
    start_pos: Dict[str, Tuple[int, int]] = {}
    done_pos: Dict[str, Tuple[int, int]] = {}
    for r, prog in progs.items():
        for i, op in enumerate(prog):
            if op.kind == "start":
                start_pos.setdefault(op.arg, (rix[r], i))
            elif op.kind == "done":
                done_pos.setdefault(op.arg, (rix[r], i))
    # staged-chunk landing prefix: chunks landed into r's stage buffer
    # strictly before each pc (done ops with dest == "stage").
    stage_prefix: Dict[int, List[frozenset]] = {}
    for r, prog in progs.items():
        acc, pref = set(), [frozenset()]
        for op in prog:
            if op.kind == "done":
                t = sched.transfers.get(op.arg)
                if t is not None and t.dest == "stage":
                    acc.add(t.chunk)
            pref.append(frozenset(acc))
        stage_prefix[rix[r]] = pref
    by_dst: Dict[int, List[Transfer]] = {}
    for t in sched.transfers.values():
        by_dst.setdefault(t.dst, []).append(t)

    def occupancy(pcs: Tuple[int, ...], d: int) -> int:
        occ = 0
        for t in by_dst.get(d, ()):
            sp = start_pos.get(t.tid)
            dp = done_pos.get(t.tid)
            if sp is not None and pcs[sp[0]] > sp[1] \
                    and (dp is None or pcs[dp[0]] <= dp[1]):
                occ += 1
        return occ

    transitions: List[protocol.Transition] = []
    for r in ranks:
        i = rix[r]
        for pc, op in enumerate(progs[r]):
            name = f"r{r}.{op.render()}@{pc}"

            def guard(s, i=i, pc=pc, op=op):
                if s[-1] is not None or s[i] != pc:
                    return False
                if op.kind == "done":
                    sp = start_pos.get(op.arg)
                    return sp is not None and s[sp[0]] > sp[1]
                return True

            def apply(s, i=i, pc=pc, op=op, r=r):
                pcs = list(s[:-1])
                pcs[i] += 1
                viol = s[-1]
                if op.kind == "start":
                    t = sched.transfers[op.arg]
                    if t.via is not None \
                            and t.via not in stage_prefix[i][pc]:
                        viol = (f"fence: r{r} starts {t.tid} "
                                f"forwarding chunk {t.via} before its "
                                f"staged payload landed")
                    occ = occupancy(tuple(pcs), t.dst)
                    if viol is None and occ > sched.max_inflight:
                        viol = (f"buffer: {occ} outstanding transfers "
                                f"at r{t.dst} exceed the declared "
                                f"landing capacity "
                                f"{sched.max_inflight}")
                elif op.kind == "unstage":
                    if op.arg not in stage_prefix[i][pc]:
                        viol = (f"fence: r{r} unstages chunk {op.arg} "
                                f"before its staged payload landed")
                return tuple(pcs) + (viol,)

            transitions.append(protocol.Transition(name, guard, apply))

    ends = tuple(len(progs[r]) for r in ranks)

    def invariant(s) -> Optional[str]:
        return s[-1]

    def terminal_invariant(s) -> Optional[str]:
        if s[-1] is not None:
            return None  # the state invariant already fired
        if tuple(s[:-1]) == ends:
            return None
        blocked = {}
        for r in ranks:
            i = rix[r]
            if s[i] < len(progs[r]):
                op = progs[r][s[i]]
                why = ""
                if op.kind == "done":
                    sp = start_pos.get(op.arg)
                    why = (" (its start never executes)" if sp is None
                           else f" (waiting on r{ranks[sp[0]]} "
                                f"start@{sp[1]})")
                blocked[f"r{r}"] = op.render() + why
        return f"deadlock: no enabled transition, blocked at {blocked}"

    initial = tuple(0 for _ in ranks) + (None,)
    return protocol.Model(f"schedule:{sched.name}", initial,
                          transitions, invariant, terminal_invariant)


# --------------------------------------------------------------------------
# phase 3: deterministic host interpreter
# --------------------------------------------------------------------------

def make_input_blocks(sched: Schedule,
                      base: Optional[np.ndarray] = None
                      ) -> List[np.ndarray]:
    """Flattened per-source-rank in-blocks (canonical distinct-valued
    base array unless one is given)."""
    total = int(np.prod(sched.shape)) if sched.shape else 1
    if base is None:
        base = np.arange(total, dtype=np.dtype(sched.dtype)
                         ).reshape(sched.shape)
    base = np.asarray(base, dtype=np.dtype(sched.dtype)
                      ).reshape(sched.shape)
    flat = base.reshape(-1)
    return [flat[block_global_indices(sched.shape, sched.src_spec, s,
                                      sched.src_world)].copy()
            for s in range(sched.src_world)]


def expected_output_blocks(sched: Schedule,
                           base: Optional[np.ndarray] = None
                           ) -> List[np.ndarray]:
    total = int(np.prod(sched.shape)) if sched.shape else 1
    if base is None:
        base = np.arange(total, dtype=np.dtype(sched.dtype)
                         ).reshape(sched.shape)
    flat = np.asarray(base, dtype=np.dtype(sched.dtype)).reshape(-1)
    return [flat[block_global_indices(sched.shape, sched.dst_spec, d,
                                      sched.dst_world)].copy()
            for d in range(sched.dst_world)]


#: Schema of one measured schedule-execution op record (ISSUE 20).
#: Fingerprint-keyed so records from many runs of many schedules can be
#: pooled and still attributed; ``run`` disambiguates executions of the
#: SAME schedule (the critical-path extractor must not mix two runs).
SCHEDULE_EXEC_SCHEMA = "chainermn_tpu.schedule_exec.v1"

#: Per-process execution counter feeding ``run`` ids — deliberately NOT
#: wall-clock-derived, so a replayed fit is deterministic.
_EXEC_SEQ = itertools.count()


class ScheduleExecProfile:
    """Measured per-op records for executions of ONE schedule.

    :func:`run_schedule` calls :meth:`on_op` around every executed op;
    each record carries (op, arg, rank, link, bytes, wall_us, t_us)
    under ``SCHEDULE_EXEC_SCHEMA``, keyed by the schedule fingerprint
    and a per-execution ``run`` id.  ``link`` is the transfer's wire
    class for ``start``/``done`` and ``"copy"`` for local
    ``copy``/``unstage`` ops (they never touch a wire but DO consume
    the copy engine the cost model prices via ``copy_bw``).

    The profile is the truth side of the calibration loop: byte
    reconciliation against the IR's declared :meth:`Schedule.wire_bytes`
    is exact (a measured byte that the IR does not declare — or vice
    versa — is a profiler bug, not noise), while walls feed the
    least-squares (alpha, bw) fit in :mod:`.calibrate`.
    """

    def __init__(self, sched: Schedule, clock_ns=None):
        self.sched = sched
        self.schedule = sched.name
        self.kind = sched.kind
        self.fingerprint = sched.fingerprint()
        self.records: List[dict] = []
        self._clock = clock_ns or time.perf_counter_ns
        self._item = sched.itemsize
        self._t0: Optional[int] = None
        self._run_seq = None  # assigned lazily per begin()
        # (kind, arg) -> (link, bytes), precomputed so on_op stays a
        # single dict lookup — this runs inside reshard_host's
        # schedule interpreter and its cost is the profiler_overhead
        # the schedule_truth bench gates < 3%.
        self._info: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for tid, t in sched.transfers.items():
            nb = sched.chunks[t.chunk].nelems * self._item
            self._info[("start", tid)] = (t.link, nb)
            self._info[("done", tid)] = (t.link, nb)
        for cname, c in sched.chunks.items():
            nb = c.nelems * self._item
            self._info[("copy", cname)] = ("copy", nb)
            self._info[("unstage", cname)] = ("copy", nb)

    def now_ns(self) -> int:
        return self._clock()

    def begin(self) -> None:
        """Mark the start of one execution (a new ``run`` id); called
        automatically by :func:`run_schedule` so repeated executions
        through one profile stay distinguishable."""
        self._run_seq = f"{self.fingerprint}-{next(_EXEC_SEQ)}"
        self._t0 = None

    def on_op(self, op: Op, rank: int, t_beg_ns: int,
              t_end_ns: int) -> None:
        if self._run_seq is None:
            self.begin()
        if self._t0 is None:
            self._t0 = t_beg_ns
        link, nbytes = self._info[(op.kind, op.arg)]
        self.records.append({
            "schema": SCHEDULE_EXEC_SCHEMA,
            "fingerprint": self.fingerprint,
            "schedule": self.schedule,
            "sched_kind": self.kind,
            "run": self._run_seq,
            "seq": len(self.records),
            "op": op.kind,
            "arg": op.arg,
            "rank": int(rank),
            "link": link,
            "bytes": int(nbytes),
            "t_us": (t_beg_ns - self._t0) / 1e3,
            "wall_us": (t_end_ns - t_beg_ns) / 1e3,
        })

    # -- aggregation faces ----------------------------------------------

    def runs(self) -> List[str]:
        out: List[str] = []
        for rec in self.records:
            if not out or out[-1] != rec["run"]:
                out.append(rec["run"])
        return out

    def run_records(self, run: Optional[str] = None) -> List[dict]:
        runs = self.runs()
        if not runs:
            return []
        run = run or runs[-1]
        return [r for r in self.records if r["run"] == run]

    def wall_us(self, run: Optional[str] = None) -> float:
        recs = self.run_records(run)
        return max((r["t_us"] + r["wall_us"] for r in recs),
                   default=0.0)

    def measured_wire_bytes(self, run: Optional[str] = None
                            ) -> Dict[str, int]:
        """Bytes that crossed each wire in one run — summed over
        ``start`` records only (a transfer crosses its link once; its
        ``done`` is the landing copy)."""
        out = {"ici": 0, "dcn": 0}
        for r in self.run_records(run):
            if r["op"] == "start" and r["link"] in out:
                out[r["link"]] += r["bytes"]
        return out

    def reconcile(self, run: Optional[str] = None) -> List[str]:
        """Exact byte reconciliation of one run against the IR: summed
        measured transfer bytes must EQUAL the schedule's declared
        :meth:`Schedule.wire_bytes` per link, and every started
        transfer must have exactly one measured ``done``."""
        v: List[str] = []
        declared = self.sched.wire_bytes()
        measured = self.measured_wire_bytes(run)
        for link in sorted(declared):
            if measured.get(link, 0) != declared[link]:
                v.append(
                    f"reconcile: {link} measured {measured.get(link, 0)}"
                    f" B != declared {declared[link]} B")
        starts: Dict[str, int] = {}
        dones: Dict[str, int] = {}
        for r in self.run_records(run):
            if r["op"] == "start":
                starts[r["arg"]] = starts.get(r["arg"], 0) + 1
            elif r["op"] == "done":
                dones[r["arg"]] = dones.get(r["arg"], 0) + 1
        if starts != dones:
            odd = {t for t in set(starts) | set(dones)
                   if starts.get(t, 0) != dones.get(t, 0)}
            v.append(f"reconcile: start/done counts differ for "
                     f"{sorted(odd)}")
        return v


def execute_profiled(sched: Schedule,
                     in_blocks: Optional[Sequence[np.ndarray]] = None,
                     reps: int = 1
                     ) -> Tuple[List[np.ndarray], ScheduleExecProfile]:
    """Run a verified schedule ``reps`` times under a fresh profiler
    and return (last outputs, profile) — the bench/`--measure` face."""
    prof = ScheduleExecProfile(sched)
    ins = in_blocks if in_blocks is not None else make_input_blocks(sched)
    outs: List[np.ndarray] = []
    for _ in range(max(1, int(reps))):
        outs = run_schedule(sched, ins, profiler=prof)
    return outs, prof


def run_schedule(sched: Schedule, in_blocks: Sequence[np.ndarray],
                 profiler: Optional[ScheduleExecProfile] = None
                 ) -> List[np.ndarray]:
    """Execute a VERIFIED schedule on host buffers.  Deterministic
    round-robin over ranks; each rank runs its program in order, a
    ``done`` blocking until the matching ``start`` has produced the
    payload.  Byte-exactness vs the direct path is part of
    :func:`verify_schedule`, so callers may swap schedules freely.

    With a ``profiler`` every op is timed and recorded
    (``SCHEDULE_EXEC_SCHEMA``); without one the only added cost is a
    predicted-taken branch per op — the zero-overhead-off discipline
    the PR 17 journal set."""
    if len(in_blocks) != sched.src_world:
        raise ValueError(f"need {sched.src_world} in-blocks, got "
                         f"{len(in_blocks)}")
    item_dtype = np.dtype(sched.dtype)
    ins = [np.asarray(b).reshape(-1) for b in in_blocks]
    outs = [np.empty(_block_elems(sched, sched.dst_spec, d,
                                  sched.dst_world), dtype=item_dtype)
            for d in range(sched.dst_world)]
    stage: Dict[Tuple[int, str], np.ndarray] = {}
    wire: Dict[str, np.ndarray] = {}
    pcs = {r: 0 for r in sched.programs}
    if profiler is not None:
        profiler.begin()

    def gather(c: Chunk, src_buf: np.ndarray) -> np.ndarray:
        return np.concatenate([src_buf[so:so + n]
                               for so, _, n in c.segments]) \
            if len(c.segments) != 1 else \
            src_buf[c.segments[0][0]:c.segments[0][0]
                    + c.segments[0][2]].copy()

    def scatter(c: Chunk, payload: np.ndarray, out: np.ndarray):
        off = 0
        for _, do, n in c.segments:
            out[do:do + n] = payload[off:off + n]
            off += n

    def ready(r: int, op: Op) -> bool:
        if op.kind == "done":
            return op.arg in wire
        if op.kind == "unstage":
            return (r, op.arg) in stage
        if op.kind == "start":
            t = sched.transfers[op.arg]
            return t.via is None or (r, t.via) in stage
        return True

    progressed = True
    while progressed:
        progressed = False
        for r in sorted(sched.programs):
            prog = sched.programs[r]
            while pcs[r] < len(prog) and ready(r, prog[pcs[r]]):
                op = prog[pcs[r]]
                pcs[r] += 1
                progressed = True
                t_beg = profiler.now_ns() if profiler is not None else 0
                if op.kind == "copy":
                    c = sched.chunks[op.arg]
                    scatter(c, gather(c, ins[r]), outs[r])
                elif op.kind == "unstage":
                    c = sched.chunks[op.arg]
                    scatter(c, stage[(r, op.arg)], outs[r])
                elif op.kind == "start":
                    t = sched.transfers[op.arg]
                    c = sched.chunks[t.chunk]
                    payload = (stage[(r, t.via)]
                               if t.via is not None
                               else gather(c, ins[r]))
                    wire[t.tid] = payload
                elif op.kind == "done":
                    t = sched.transfers[op.arg]
                    payload = wire.pop(t.tid)
                    if t.dest == "stage":
                        stage[(r, t.chunk)] = payload
                    else:
                        scatter(sched.chunks[t.chunk], payload,
                                outs[r])
                else:
                    raise NotImplementedError(
                        f"interpreter: op kind {op.kind!r} reserved")
                if profiler is not None:
                    profiler.on_op(op, r, t_beg, profiler.now_ns())
    stuck = {r: sched.programs[r][pcs[r]].render()
             for r in pcs if pcs[r] < len(sched.programs[r])}
    if stuck:
        raise RuntimeError(f"run_schedule: schedule {sched.name} "
                           f"deadlocked at {stuck} — it was not "
                           f"verified")
    return outs


# --------------------------------------------------------------------------
# the verifier
# --------------------------------------------------------------------------

@dataclass
class VerifyResult:
    ok: bool
    schedule: str
    kind: str
    violations: List[str] = field(default_factory=list)
    #: minimal counterexample trace from the model check (rendered
    #: transition names), empty when the machine is clean.
    counterexample: List[str] = field(default_factory=list)
    n_states: int = 0
    complete: bool = True
    phases: Dict[str, str] = field(default_factory=dict)

    def render(self) -> str:
        head = (f"{self.schedule}: "
                + ("OK" if self.ok else "VIOLATION")
                + f" ({self.n_states} states"
                + ("" if self.complete else ", TRUNCATED")
                + "; " + ", ".join(f"{k}={v}" for k, v in
                                   sorted(self.phases.items()))
                + ")")
        lines = [head]
        for v in self.violations:
            lines.append(f"  - {v}")
        if self.counterexample:
            lines.append("  counterexample (minimal):")
            for i, t in enumerate(self.counterexample, 1):
                lines.append(f"    {i:2d}. {t}")
        return "\n".join(lines)


def verify_schedule(sched: Schedule, max_states: int = 500_000
                    ) -> VerifyResult:
    """Run all three proofs.  The interpreter only runs once structure,
    coverage, and the model check are clean (executing an unverified
    schedule, even on host buffers, is the thing this module exists to
    prevent)."""
    res = VerifyResult(True, sched.name, sched.kind)
    sv = structural_check(sched)
    res.phases["structural"] = "ok" if not sv else "violated"
    res.violations += sv
    if not sv:
        cv = coverage_check(sched)
        res.phases["coverage"] = "ok" if not cv else "violated"
        res.violations += cv
    else:
        res.phases["coverage"] = "skipped"
    model = make_schedule_model(sched)
    cr = protocol.check(model, max_states=max_states)
    res.n_states = cr.n_states
    res.complete = cr.complete
    if not cr.ok:
        res.phases["model"] = "violated"
        res.violations.append(f"model: {cr.violation}")
        res.counterexample = [t for t, _ in cr.counterexample]
    elif not cr.complete:
        res.phases["model"] = "truncated"
        res.violations.append(
            f"model: state space truncated at {cr.n_states} states — "
            f"not exhaustively verified (raise max_states or shrink "
            f"the schedule)")
    else:
        res.phases["model"] = "ok"
    if not res.violations:
        try:
            got = run_schedule(sched, make_input_blocks(sched))
            want = expected_output_blocks(sched)
            bad = [d for d in range(sched.dst_world)
                   if not np.array_equal(got[d], want[d])]
            if bad:
                res.violations.append(
                    f"interpreter: output differs from the statics "
                    f"oracle at dst rank(s) {bad}")
                res.phases["interpreter"] = "violated"
            else:
                res.phases["interpreter"] = "ok"
        except Exception as e:  # pragma: no cover - belt
            res.violations.append(f"interpreter: crashed: {e!r}")
            res.phases["interpreter"] = "crashed"
    else:
        res.phases["interpreter"] = "skipped"
    res.ok = not res.violations
    return res


# --------------------------------------------------------------------------
# seeded faults — the 0 FN / 0 FP corpus generators
# --------------------------------------------------------------------------

def _clone(sched: Schedule, suffix: str) -> Schedule:
    out = copy.deepcopy(sched)
    out.name = f"{sched.name}+{suffix}"
    return out


def _out_transfers(sched: Schedule) -> List[Transfer]:
    return [sched.transfers[tid] for tid in sorted(sched.transfers)
            if sched.transfers[tid].dest == "out"]


def seed_fault(sched: Schedule, fault: str) -> Schedule:
    """A deterministically broken copy of ``sched``.  Each fault class
    maps to the verifier phase that must catch it:

    - ``dropped_chunk``   -> coverage gap
    - ``double_write``    -> coverage multiplicity
    - ``send_recv_cycle`` -> model deadlock
    - ``done_before_start`` -> model fence violation (needs a staged
      hop, i.e. a hierarchical schedule)
    - ``buffer_overrun``  -> model buffer-bound violation
    """
    out = _clone(sched, fault)
    if fault == "dropped_chunk":
        cands = _out_transfers(out) or None
        if cands:
            t = cands[-1]
            del out.transfers[t.tid]
            del out.chunks[t.chunk]
            for r in out.programs:
                out.programs[r] = [
                    op for op in out.programs[r]
                    if not (op.kind in ("start", "done")
                            and op.arg == t.tid)]
        else:
            for r in sorted(out.programs):
                copies = [op for op in out.programs[r]
                          if op.kind == "copy"]
                if copies:
                    out.programs[r].remove(copies[-1])
                    break
        return out
    if fault == "double_write":
        cands = _out_transfers(out)
        if cands:
            t = cands[0]
            c = out.chunks[t.chunk]
            c2 = Chunk(c.name + "_dup", c.src_rank, c.dst_rank,
                       c.segments)
            out.chunks[c2.name] = c2
            t2 = Transfer(t.tid + "_dup", c2.name, t.src, t.dst,
                          t.dest, t.link, t.via)
            out.transfers[t2.tid] = t2
            out.programs[t.src].append(Op("start", t2.tid))
            out.programs[t.dst].append(Op("done", t2.tid))
            out.max_inflight += 1  # keep the buffer bound honest
        else:
            for r in sorted(out.programs):
                copies = [op for op in out.programs[r]
                          if op.kind == "copy"]
                if copies:
                    out.programs[r].append(copies[0])
                    break
        return out
    if fault == "send_recv_cycle":
        pair = None
        for t1 in _out_transfers(out):
            for t2 in _out_transfers(out):
                if t1.src == t2.dst and t1.dst == t2.src \
                        and t1.via is None and t2.via is None:
                    pair = (t1, t2)
                    break
            if pair:
                break
        if pair is None:
            raise ValueError(
                f"{sched.name}: no reciprocal transfer pair to build "
                f"a send/recv cycle from")
        t1, t2 = pair

        def reorder(r, first_tid, then_tid):
            prog = [op for op in out.programs[r]
                    if not (op.kind == "done" and op.arg == first_tid)]
            i = next(j for j, op in enumerate(prog)
                     if op.kind == "start" and op.arg == then_tid)
            prog.insert(i, Op("done", first_tid))
            out.programs[r] = prog

        # t1: a->b, t2: b->a.  a now awaits t2 before sending t1, and
        # b awaits t1 before sending t2 — the classic rendezvous cycle.
        reorder(t1.src, t2.tid, t1.tid)
        reorder(t2.src, t1.tid, t2.tid)
        return out
    if fault == "done_before_start":
        for r in sorted(out.programs):
            prog = out.programs[r]
            for i, op in enumerate(prog):
                if op.kind != "start":
                    continue
                t = out.transfers[op.arg]
                if t.via is None:
                    continue
                lands = [j for j, o in enumerate(prog) if j < i
                         and o.kind == "done"
                         and out.transfers[o.arg].chunk == t.via
                         and out.transfers[o.arg].dest == "stage"]
                if not lands:
                    continue
                j = lands[-1]
                prog[i], prog[j] = prog[j], prog[i]
                return out
        raise ValueError(
            f"{sched.name}: no staged forwarding hop to misorder "
            f"(use a hierarchical schedule)")
    if fault == "buffer_overrun":
        if sched.max_inflight <= 1:
            raise ValueError(f"{sched.name}: declared capacity is "
                             f"already 1")
        out.max_inflight = sched.max_inflight - 1
        return out
    raise KeyError(f"unknown fault {fault!r}; have {SEEDED_FAULTS}")


SEEDED_FAULTS = ("dropped_chunk", "double_write", "send_recv_cycle",
                 "done_before_start", "buffer_overrun")


# --------------------------------------------------------------------------
# verified compilation + the fleet-reachable pair matrix
# --------------------------------------------------------------------------

_COMPILE_CACHE: Dict[tuple, Tuple[Schedule, dict]] = {}


def _calibration_key(calibration: Optional[dict]) -> Optional[str]:
    """Stable identity of a calibration artifact for the compile cache
    (two fits with identical constants share an entry; a re-fit with
    new measurements invalidates)."""
    if not calibration:
        return None
    import hashlib
    blob = json.dumps(calibration, sort_keys=True,
                      separators=(",", ":"), default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def compile_verified(shape, dtype, src_spec, dst_spec, src_world,
                     dst_world, topology: Optional[Topology] = None,
                     n_chunks: int = 2, depth: int = 2,
                     cost_model: Optional[CostModel] = None,
                     calibration: Optional[dict] = None,
                     max_states: int = 500_000
                     ) -> Tuple[Schedule, dict]:
    """Generate candidates, verify every one, and return the cheapest
    VERIFIED schedule plus its price row (with the baseline cost and
    per-candidate table attached).  Results are cached per geometry —
    the ``make_reshard``-style compile-once contract.

    With ``calibration`` (a loaded ``chainermn_tpu.calibration.v1``
    artifact) candidates rank by MEASURED per-link constants instead of
    the stock r04 assumptions; the calibration's identity participates
    in the cache key so a re-fit re-ranks."""
    key = (tuple(shape), str(dtype), src_spec, dst_spec,
           int(src_world), int(dst_world),
           (topology.slices, topology.per_slice) if topology else None,
           int(n_chunks), int(depth), _calibration_key(calibration))
    hit = _COMPILE_CACHE.get(key)
    if hit is not None:
        return hit
    cands = candidate_schedules(shape, dtype, src_spec, dst_spec,
                                src_world, dst_world, topology,
                                n_chunks=n_chunks, depth=depth)
    rows = []
    best = None
    for sc in cands:
        vr = verify_schedule(sc, max_states=max_states)
        if not vr.ok:
            raise RuntimeError(
                f"generator emitted an unverifiable schedule:\n"
                f"{vr.render()}")
        row = price_schedule(sc, cost_model, calibration=calibration)
        row["n_states"] = vr.n_states
        rows.append(row)
        if best is None or row["cost_ms"] < best[1]["cost_ms"]:
            best = (sc, row)
    sched, row = best
    report = dict(row)
    report["baseline_cost_ms"] = rows[0]["cost_ms"]
    report["speedup_vs_single"] = (
        rows[0]["cost_ms"] / row["cost_ms"] if row["cost_ms"] else 1.0)
    report["candidates"] = rows
    _COMPILE_CACHE[key] = (sched, report)
    return sched, report


def verified_schedule(kind: str, shape, dtype, src_spec, dst_spec,
                      src_world, dst_world,
                      topology: Optional[Topology] = None,
                      n_chunks: int = 2, depth: int = 2,
                      max_states: int = 500_000) -> Schedule:
    """One named generator's schedule, verified and cached — or the
    cheapest verified candidate for ``kind="auto"``.  Raises if the
    schedule does not pass the verifier (nothing unverified escapes)."""
    if kind == "auto":
        return compile_verified(shape, dtype, src_spec, dst_spec,
                                src_world, dst_world, topology,
                                n_chunks=n_chunks, depth=depth,
                                max_states=max_states)[0]
    from .schedule import GENERATORS
    gen = GENERATORS.get(kind)
    if gen is None:
        raise KeyError(f"unknown schedule kind {kind!r}; have "
                       f"{sorted(GENERATORS)} or 'auto'")
    key = ("one", kind, tuple(shape), str(dtype), src_spec, dst_spec,
           int(src_world), int(dst_world),
           (topology.slices, topology.per_slice) if topology else None,
           int(n_chunks), int(depth))
    hit = _COMPILE_CACHE.get(key)
    if hit is not None:
        return hit[0]
    kw = {} if kind == "single" else (
        {"n_chunks": n_chunks} if kind != "pipelined"
        else {"n_chunks": n_chunks, "depth": depth})
    if kind == "hierarchical":
        world = max(int(src_world), int(dst_world))
        topology = topology or Topology.flat(world)
        sched = gen(shape, dtype, src_spec, dst_spec, src_world,
                    dst_world, topology, **kw)
    else:
        sched = gen(shape, dtype, src_spec, dst_spec, src_world,
                    dst_world, topology, **kw)
    vr = verify_schedule(sched, max_states=max_states)
    if not vr.ok:
        raise RuntimeError(f"schedule failed verification:\n"
                           f"{vr.render()}")
    _COMPILE_CACHE[key] = (sched, {})
    return sched


#: Every (src,dst) spec pair the fleet actually lowers through
#: ``reshard_host``: elastic resume re-folds a checkpoint across a
#: world change in either direction, ``heal()`` live-shrinks the gang
#: by one rank, and ``rolling_upgrade()`` gathers a sharded checkpoint
#: into full replicated params for each replacement worker (the
#: fan-out row is the whole-fleet upgrade, the ICI+DCN pair where
#: hierarchical staging wins).
FLEET_PAIRS: Tuple[Tuple[str, Optional[int], Optional[int], int, int],
                   ...] = (
    ("elastic_resume_shrink_repl", None, None, 4, 2),
    ("elastic_resume_shrink_sharded", 0, 0, 4, 2),
    ("elastic_resume_grow_sharded", 0, 0, 2, 4),
    ("live_shrink_repl", None, None, 4, 3),
    ("live_shrink_sharded", 0, 0, 4, 3),
    ("rolling_upgrade_gather", 0, None, 2, 1),
    ("rolling_upgrade_repl", None, None, 2, 1),
    ("rolling_upgrade_fanout", 0, None, 4, 4),
)


def fleet_pair_topology(src_world: int, dst_world: int) -> Topology:
    """The wire each fleet pair actually crosses: 4-rank worlds are a
    2-host × 2-chip gang (ICI inside a host, DCN across), 2-rank
    worlds are one chip per host (pure DCN), odd worlds are flat."""
    world = max(int(src_world), int(dst_world))
    if world % 2 == 0 and world >= 4:
        return Topology(2, world // 2)
    if world == 2:
        return Topology(2, 1)
    return Topology.flat(world)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m chainermn_tpu.analysis.schedule_check",
        description="verify collective schedules (exit 0 clean / 1 "
                    "violations / 2 unusable)")
    p.add_argument("schedules", nargs="*",
                   help="schedule JSON artifacts to verify; default = "
                        "the fleet-reachable pair matrix")
    p.add_argument("--shape", default="24,4",
                   help="array shape for the pair matrix")
    p.add_argument("--dtype", default="float32")
    p.add_argument("--chunks", type=int, default=2)
    p.add_argument("--depth", type=int, default=2)
    p.add_argument("--max-states", type=int, default=500_000)
    p.add_argument("--json", action="store_true",
                   help="emit a machine-readable report")
    args = p.parse_args(argv)

    rows = []
    worst = 0
    try:
        if args.schedules:
            for path in args.schedules:
                with open(path) as f:
                    sched = Schedule.from_json(json.load(f))
                vr = verify_schedule(sched,
                                     max_states=args.max_states)
                rows.append({"pair": path, "ok": vr.ok,
                             "report": vr.render()})
                worst = max(worst, 0 if vr.ok else 1)
        else:
            shape = tuple(int(x) for x in args.shape.split(","))
            for name, src, dst, sw, dw in FLEET_PAIRS:
                sched, report = compile_verified(
                    shape, args.dtype, src, dst, sw, dw,
                    fleet_pair_topology(sw, dw),
                    n_chunks=args.chunks, depth=args.depth,
                    max_states=args.max_states)
                rows.append({
                    "pair": name, "ok": True,
                    "chosen": sched.kind,
                    "cost_ms": report["cost_ms"],
                    "speedup_vs_single": report["speedup_vs_single"],
                    "report": f"{name}: OK chosen={sched.kind} "
                              f"cost={report['cost_ms']:.4f}ms "
                              f"speedup={report['speedup_vs_single']:.2f}x",
                })
    except RuntimeError as e:
        print(f"schedule-check: VIOLATION\n{e}", file=sys.stderr)
        return 1
    except Exception as e:  # unusable, not a finding
        print(f"schedule-check: unusable: {e!r}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({"rows": rows, "ok": worst == 0}, indent=2,
                         sort_keys=True))
    else:
        for r in rows:
            print(r["report"])
        n_bad = sum(0 if r["ok"] else 1 for r in rows)
        print(f"schedule-check: {len(rows)} schedule(s), "
              f"{n_bad} violating")
    return worst


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
