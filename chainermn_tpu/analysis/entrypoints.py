"""Registered entry points for the jaxpr engine.

Each entry point names one REAL program of this repo — the collective
vocabulary of ``ops/collective.py``, the TP decode tick the serving
engine drives, and the per-prompt-length prefill family — built at tiny
shapes (d_model=8, one layer, axis size 1) so the whole sweep traces in
seconds on one CPU device.  Axis size 1 is enough: collectives still
appear as jaxpr equations with their axis names, which is all the
unbound-axis check reads; the recompile probes execute for real but on
KB-sized arrays.

Entry points are the extension surface: a new subsystem that adds a
compiled program registers it here and the analyzer owns it from then
on (docs/ANALYSIS.md shows the recipe).
"""

from __future__ import annotations

from typing import Any, Dict

from .jaxpr_engine import EntryPoint

_SEED = 0  # analysis must trace the same program every run


def _tiny_lm(tp: int = 1):
    """Shared tiny TP transformer-LM fixture: (params, specs, mesh)."""
    import jax

    from chainermn_tpu import topology
    from chainermn_tpu.parallel.transformer import (
        init_tp_transformer_lm, transformer_lm_specs)

    params = init_tp_transformer_lm(
        jax.random.PRNGKey(_SEED), 16, 8, 2, 1, max_len=8)
    specs = transformer_lm_specs(params, "model")
    mesh = topology.make_nd_mesh(("model",), (tp,), jax.devices()[:tp])
    return params, specs, mesh


def _build_collective_ring() -> Dict[str, Any]:
    """The ops/collective.py vocabulary under one shard_map binding —
    psum / reduce_scatter / all_gather / shift in the gradient-ring order
    the train CLI demos."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu import topology
    from chainermn_tpu._compat import shard_map
    from chainermn_tpu.ops import collective as C
    from jax.sharding import PartitionSpec as P

    mesh = topology.make_nd_mesh(("mn",), (1,), jax.devices()[:1])

    def body(x):
        g = C.reduce_scatter(x, "mn")
        g = C.all_gather(g, "mn")
        g = C.shift(g, 1, "mn", size=1)
        return C.psum(g, "mn")

    fn = shard_map(body, mesh=mesh, in_specs=(P(),), out_specs=P())
    x = np.ones((4,), np.float32)

    def run(v):
        return fn(jnp.asarray(v))

    return {"trace": (run, (x,)), "bound_axes": {"mn"}}


def _build_decode_tick() -> Dict[str, Any]:
    """One serving decode tick (the pool-lifetime compiled program):
    traced for its collective sequence AND probed for recompilation —
    two calls with different token/pos VALUES must reuse ONE program."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu._compat import shard_map
    from chainermn_tpu.parallel.decode import lm_decode_tick, lm_prefill
    from jax.sharding import PartitionSpec as P

    params, specs, mesh = _tiny_lm()
    head_dim = 4
    total = 8

    prompt = np.zeros((1, 3), np.int32)

    def tick(p, tokens, caches, pos):
        return lm_decode_tick(p, tokens, caches, pos, head_dim=head_dim,
                              axis_name="model")

    def prefill(p, pr):
        return lm_prefill(p, pr, total, head_dim=head_dim,
                          axis_name="model")

    sm_prefill = shard_map(prefill, mesh=mesh, in_specs=(specs, P()),
                           out_specs=(P(), [(P(), P())]))
    _, caches = sm_prefill(params, jnp.asarray(prompt))

    cache_specs = [(P(), P()) for _ in caches]
    sm_tick = jax.jit(shard_map(
        tick, mesh=mesh, in_specs=(specs, P(), cache_specs, P()),
        out_specs=(P(), cache_specs)))

    tokens = np.zeros((1,), np.int32)
    pos = np.asarray([3], np.int32)

    def run(p, t, c, q):
        return sm_tick(p, t, c, q)

    variants = (sm_tick, [
        (params, jnp.asarray(tokens), caches, jnp.asarray(pos)),
        (params, jnp.asarray(tokens + 1), caches,
         jnp.asarray(pos + 1)),
    ])
    return {"trace": (run, (params, jnp.asarray(tokens), caches,
                            jnp.asarray(pos))),
            "bound_axes": {"model"},
            "variants": variants}


def _build_prefill_family() -> Dict[str, Any]:
    """The per-prompt-length prefill programs: one compile PER prompt
    length is the serving engine's documented design (docs/SERVING.md) —
    registered allow_recompile=True so the hazard is named, not flagged."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu._compat import shard_map
    from chainermn_tpu.parallel.decode import lm_prefill
    from jax.sharding import PartitionSpec as P

    params, specs, mesh = _tiny_lm()
    head_dim = 4
    total = 8

    def prefill(p, pr):
        return lm_prefill(p, pr, total, head_dim=head_dim,
                          axis_name="model")

    jfn = jax.jit(shard_map(
        prefill, mesh=mesh, in_specs=(specs, P()),
        out_specs=(P(), [(P(), P())])))

    p2 = np.zeros((1, 2), np.int32)
    p3 = np.zeros((1, 3), np.int32)
    return {"trace": (lambda p, pr: jfn(p, pr), (params, jnp.asarray(p2))),
            "bound_axes": {"model"},
            "variants": (jfn, [(params, jnp.asarray(p2)),
                               (params, jnp.asarray(p3))])}


class _traced_obs_state:
    """Context manager: tracer enabled + flight tee installed for the
    duration of ONE entry-point call, prior state restored after — an
    analysis run must not leave process-global observability state
    flipped on for whatever runs next (the lint tier shares its pytest
    process with the whole suite)."""

    def __enter__(self):
        from chainermn_tpu import observability as obs
        from chainermn_tpu.observability import flight
        self._obs, self._flight = obs, flight
        self._was_enabled = obs.enabled()
        obs.enable()
        flight.install_tracer_tee()
        return self

    def __exit__(self, *exc):
        self._flight.uninstall_tracer_tee()
        if not self._was_enabled:
            self._obs.disable()
        return False


class _TracedVariantProbe:
    """Wraps the variant jit function so every probe call runs under
    the scoped tracer+tee state, while still exposing the underlying
    ``_cache_size`` the recompile gate reads."""

    def __init__(self, jfn):
        self._jfn = jfn

    def __call__(self, *a):
        from chainermn_tpu import observability as obs
        from chainermn_tpu.observability import flight
        with _traced_obs_state():
            with obs.span("serving/tick", cat="serving"):
                out = self._jfn(*a)
            flight.note("phase", name="serving/step")
        return out

    def _cache_size(self):
        return self._jfn._cache_size()


def _build_tick_with_tracing() -> Dict[str, Any]:
    """The ISSUE 5 hazard this entry point pins down: the serving tick
    with the TRACER ENABLED and the FLIGHT-RECORDER TEE installed must
    still be ONE compiled program across value variants — observability
    is host-side bookkeeping and must never leak into trace-time (a
    tracer value captured into the jaxpr would both recompile per call
    and be flagged as a tracer leak)."""
    from chainermn_tpu import observability as obs
    from chainermn_tpu.observability import flight

    base = _build_decode_tick()
    fn, args = base["trace"]

    def run_traced(*a):
        with _traced_obs_state():
            with obs.span("serving/tick", cat="serving"):
                out = fn(*a)
            flight.note("phase", name="serving/step")
        return out

    jfn, variant_args = base["variants"]
    return {"trace": (run_traced, args),
            "bound_axes": base["bound_axes"],
            "variants": (_TracedVariantProbe(jfn), variant_args)}


def _build_flight_ring_program() -> Dict[str, Any]:
    """Flight-recorder entry point: the accounted collective ring run
    UNDER the ring tee (comm deltas -> flight events).  Guards the other
    direction of the ISSUE 5 wiring — the accountant's flight tee fires
    from host callbacks only, so the traced program's collective
    sequence and compile count are byte-identical with the recorder
    on."""
    from chainermn_tpu.observability import flight

    base = _build_collective_ring()
    fn, args = base["trace"]

    def run_teed(*a):
        with _traced_obs_state():
            out = fn(*a)
            flight.note("phase", name="collective/ring")
        return out

    return {"trace": (run_teed, args), "bound_axes": base["bound_axes"]}


ENTRYPOINTS = [
    EntryPoint(
        name="ops.collective.ring",
        build=_build_collective_ring,
        description="reduce_scatter+all_gather+shift+psum gradient ring "
                    "over axis 'mn' (the train CLI's demo reduction)"),
    EntryPoint(
        name="parallel.decode.lm_decode_tick",
        build=_build_decode_tick,
        description="serving decode tick under shard_map('model') — one "
                    "program for the pool's lifetime"),
    EntryPoint(
        name="serving.prefill_family",
        build=_build_prefill_family,
        allow_recompile=True,
        description="per-prompt-length prefill programs (intentional "
                    "program family, see docs/SERVING.md)"),
    EntryPoint(
        name="serving.tick_with_tracing",
        build=_build_tick_with_tracing,
        description="serving decode tick with the tracer enabled and "
                    "the flight-recorder tee installed — observability "
                    "must stay host-side: one program, no tracer leak "
                    "(ISSUE 5)"),
    EntryPoint(
        name="observability.flight_ring",
        build=_build_flight_ring_program,
        description="accounted collective ring under the flight-"
                    "recorder comm tee — the ring records from host "
                    "callbacks only, leaving the traced program "
                    "unchanged (ISSUE 5)"),
]
