"""Registered entry points for the jaxpr engine.

Each entry point names one REAL program of this repo — the collective
vocabulary of ``ops/collective.py``, the TP decode tick the serving
engine drives, and the per-prompt-length prefill family — built at tiny
shapes (d_model=8, one layer, axis size 1) so the whole sweep traces in
seconds on one CPU device.  Axis size 1 is enough: collectives still
appear as jaxpr equations with their axis names, which is all the
unbound-axis check reads; the recompile probes execute for real but on
KB-sized arrays.

Entry points are the extension surface: a new subsystem that adds a
compiled program registers it here and the analyzer owns it from then
on (docs/ANALYSIS.md shows the recipe).
"""

from __future__ import annotations

from typing import Any, Dict

from .jaxpr_engine import EntryPoint

_SEED = 0  # analysis must trace the same program every run


def _tiny_lm(tp: int = 1):
    """Shared tiny TP transformer-LM fixture: (params, specs, mesh)."""
    import jax

    from chainermn_tpu import topology
    from chainermn_tpu.parallel.transformer import (
        init_tp_transformer_lm, transformer_lm_specs)

    params = init_tp_transformer_lm(
        jax.random.PRNGKey(_SEED), 16, 8, 2, 1, max_len=8)
    specs = transformer_lm_specs(params, "model")
    mesh = topology.make_nd_mesh(("model",), (tp,), jax.devices()[:tp])
    return params, specs, mesh


def _build_collective_ring() -> Dict[str, Any]:
    """The ops/collective.py vocabulary under one shard_map binding —
    psum / reduce_scatter / all_gather / shift in the gradient-ring order
    the train CLI demos."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu import topology
    from chainermn_tpu._compat import shard_map
    from chainermn_tpu.ops import collective as C
    from jax.sharding import PartitionSpec as P

    mesh = topology.make_nd_mesh(("mn",), (1,), jax.devices()[:1])

    def body(x):
        g = C.reduce_scatter(x, "mn")
        g = C.all_gather(g, "mn")
        g = C.shift(g, 1, "mn", size=1)
        return C.psum(g, "mn")

    fn = shard_map(body, mesh=mesh, in_specs=(P(),), out_specs=P())
    x = np.ones((4,), np.float32)

    def run(v):
        return fn(jnp.asarray(v))

    return {"trace": (run, (x,)), "bound_axes": {"mn"},
            # shard-flow: the ring's input is replicated by the P() feed
            # — deliberately NOT annotated, so the finding lives in the
            # checked-in .shardflow-baseline.json as the keeper proving
            # the replication gate is live
            "data_axis": "mn", "arg_labels": ("x",)}


def _build_decode_tick() -> Dict[str, Any]:
    """One serving decode tick (the pool-lifetime compiled program):
    traced for its collective sequence AND probed for recompilation —
    two calls with different token/pos VALUES must reuse ONE program."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu._compat import shard_map
    from chainermn_tpu.parallel.decode import lm_decode_tick, lm_prefill
    from jax.sharding import PartitionSpec as P

    params, specs, mesh = _tiny_lm()
    head_dim = 4
    total = 8

    prompt = np.zeros((1, 3), np.int32)

    def tick(p, tokens, caches, pos):
        return lm_decode_tick(p, tokens, caches, pos, head_dim=head_dim,
                              axis_name="model")

    def prefill(p, pr):
        return lm_prefill(p, pr, total, head_dim=head_dim,
                          axis_name="model")

    sm_prefill = shard_map(prefill, mesh=mesh, in_specs=(specs, P()),
                           out_specs=(P(), [(P(), P())]))
    _, caches = sm_prefill(params, jnp.asarray(prompt))

    cache_specs = [(P(), P()) for _ in caches]
    sm_tick = jax.jit(shard_map(
        tick, mesh=mesh, in_specs=(specs, P(), cache_specs, P()),
        out_specs=(P(), cache_specs)))

    tokens = np.zeros((1,), np.int32)
    pos = np.asarray([3], np.int32)

    def run(p, t, c, q):
        return sm_tick(p, t, c, q)

    variants = (sm_tick, [
        (params, jnp.asarray(tokens), caches, jnp.asarray(pos)),
        (params, jnp.asarray(tokens + 1), caches,
         jnp.asarray(pos + 1)),
    ])
    return {"trace": (run, (params, jnp.asarray(tokens), caches,
                            jnp.asarray(pos))),
            "bound_axes": {"model"},
            "variants": variants,
            # shard-flow: TP shards the matmul weights over 'model';
            # norm scales/biases stay replicated by the Megatron layout,
            # and the KV pool rows are whole per replica at this
            # registration's cache specs.  tokens/pos are deliberately
            # UN-annotated: two tiny host-fed vectors kept as baseline
            # keepers (with comments) proving the gate bites.
            "data_axis": "model",
            "arg_labels": ("params", "tokens", "caches", "pos"),
            "expected_replication": {
                "params": "Megatron TP layout: matmul weights shard "
                          "over 'model', norm scales/biases/embedding "
                          "remainders replicate by design",
                "caches": "KV pool rows are whole per replica at the "
                          "registered cache specs (TP>1 shards heads "
                          "inside the flat K/V rows)",
            }}


def _build_prefill_family() -> Dict[str, Any]:
    """The per-prompt-length prefill programs: one compile PER prompt
    length is the serving engine's documented design (docs/SERVING.md) —
    registered allow_recompile=True so the hazard is named, not flagged."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu._compat import shard_map
    from chainermn_tpu.parallel.decode import lm_prefill
    from jax.sharding import PartitionSpec as P

    params, specs, mesh = _tiny_lm()
    head_dim = 4
    total = 8

    def prefill(p, pr):
        return lm_prefill(p, pr, total, head_dim=head_dim,
                          axis_name="model")

    jfn = jax.jit(shard_map(
        prefill, mesh=mesh, in_specs=(specs, P()),
        out_specs=(P(), [(P(), P())])))

    p2 = np.zeros((1, 2), np.int32)
    p3 = np.zeros((1, 3), np.int32)
    return {"trace": (lambda p, pr: jfn(p, pr), (params, jnp.asarray(p2))),
            "bound_axes": {"model"},
            "variants": (jfn, [(params, jnp.asarray(p2)),
                               (params, jnp.asarray(p3))]),
            "data_axis": "model",
            "arg_labels": ("params", "prompt"),
            "expected_replication": {
                "params": "Megatron TP layout: matmul weights shard "
                          "over 'model', norm scales/biases/embedding "
                          "remainders replicate by design",
                "prompt": "every TP rank consumes the full prompt "
                          "(vocab-parallel embedding resolves its own "
                          "vocab range)",
            }}


class _traced_obs_state:
    """Context manager: tracer enabled + flight tee installed for the
    duration of ONE entry-point call, prior state restored after — an
    analysis run must not leave process-global observability state
    flipped on for whatever runs next (the lint tier shares its pytest
    process with the whole suite)."""

    def __enter__(self):
        from chainermn_tpu import observability as obs
        from chainermn_tpu.observability import flight
        self._obs, self._flight = obs, flight
        self._was_enabled = obs.enabled()
        obs.enable()
        flight.install_tracer_tee()
        return self

    def __exit__(self, *exc):
        self._flight.uninstall_tracer_tee()
        if not self._was_enabled:
            self._obs.disable()
        return False


class _TracedVariantProbe:
    """Wraps the variant jit function so every probe call runs under
    the scoped tracer+tee state, while still exposing the underlying
    ``_cache_size`` the recompile gate reads."""

    def __init__(self, jfn):
        self._jfn = jfn

    def __call__(self, *a):
        from chainermn_tpu import observability as obs
        from chainermn_tpu.observability import flight
        with _traced_obs_state():
            with obs.span("serving/tick", cat="serving"):
                out = self._jfn(*a)
            flight.note("phase", name="serving/step")
        return out

    def _cache_size(self):
        return self._jfn._cache_size()


def _build_tick_with_tracing() -> Dict[str, Any]:
    """The ISSUE 5 hazard this entry point pins down: the serving tick
    with the TRACER ENABLED and the FLIGHT-RECORDER TEE installed must
    still be ONE compiled program across value variants — observability
    is host-side bookkeeping and must never leak into trace-time (a
    tracer value captured into the jaxpr would both recompile per call
    and be flagged as a tracer leak)."""
    from chainermn_tpu import observability as obs
    from chainermn_tpu.observability import flight

    base = _build_decode_tick()
    fn, args = base["trace"]

    def run_traced(*a):
        with _traced_obs_state():
            with obs.span("serving/tick", cat="serving"):
                out = fn(*a)
            flight.note("phase", name="serving/step")
        return out

    jfn, variant_args = base["variants"]
    return {"trace": (run_traced, args),
            "bound_axes": base["bound_axes"],
            "variants": (_TracedVariantProbe(jfn), variant_args)}


class _RouterTeeProbe:
    """Variant probe for the ROUTER-driven tick: every call runs under
    the scoped tracer+tee state AND the router's per-request emissions
    (dispatch complete-event, per-slot decode-tick complete-events with
    trace ids) — the full fleet observability surface the replica tick
    lives under in production (ISSUE 7)."""

    def __init__(self, jfn):
        self._jfn = jfn

    def __call__(self, *a):
        from chainermn_tpu import observability as obs
        from chainermn_tpu.observability import flight
        with _traced_obs_state():
            t0 = obs.now_us()
            obs.complete_event("router/dispatch", t0, 1,
                               cat="serving_request",
                               trace_id="req-analysis-rt00000000",
                               replica="replica0", prefix_match_len=0)
            with obs.span("serving/tick", cat="serving"):
                out = self._jfn(*a)
            obs.complete_event("request/decode_tick", t0,
                               obs.now_us() - t0, cat="serving_request",
                               trace_id="req-analysis-rt00000000",
                               request=0, slot=0, active=1)
            flight.note("router", event="dispatched",
                        trace_id="req-analysis-rt00000000",
                        replica="replica0")
            flight.note("phase", name="serving/step")
        return out

    def _cache_size(self):
        return self._jfn._cache_size()


def _build_router_tick() -> Dict[str, Any]:
    """The REPLICA decode tick as the serving router drives it (ISSUE
    7): tracer enabled, flight tee installed, router dispatch +
    per-request decode-tick complete-events emitted around the device
    call.  Registered shardflow=True (unlike the plain tracing tee
    variant) so the fleet path's collective bytes are INDEPENDENTLY
    reconciled against the comm ledger — the router hop must add zero
    device traffic and zero compiles: one program across variants."""
    base = _build_decode_tick()
    fn, args = base["trace"]
    probe = _RouterTeeProbe(base["variants"][0])

    def run_routed(*a):
        return probe(*a)

    return {"trace": (run_routed, args),
            "bound_axes": base["bound_axes"],
            "variants": (probe, base["variants"][1]),
            "data_axis": "model",
            "arg_labels": ("params", "tokens", "caches", "pos"),
            "expected_replication": {
                "params": "Megatron TP layout: matmul weights shard "
                          "over 'model', norm scales/biases/embedding "
                          "remainders replicate by design",
                "caches": "KV pool rows are whole per replica at the "
                          "registered cache specs (TP>1 shards heads "
                          "inside the flat K/V rows)",
                "pos": "per-slot position vector: 4 host-fed bytes "
                       "copied to every TP rank each tick — the same "
                       "replication the base decode-tick entry keeps "
                       "as a baseline keeper",
                # `tokens` deliberately UN-annotated: this entry's
                # keeper finding (with comment) in the regenerated
                # .shardflow-baseline.json proves the replication gate
                # bites on the fleet path too
            }}


def _build_prefix_copy() -> Dict[str, Any]:
    """The prefix cache's copy-on-extend program (ISSUE 7):
    ``DecodeEngine.copy_prefix``'s slab copy over the REAL pool buffers
    at tiny shapes.  The contract under analysis: pure data movement —
    ZERO collectives (each TP rank copies its local columns; the comm
    reconciliation holds it to an empty ledger) and ONE compiled
    program across (src, dst) slot-index variants (the indices are
    traced operands, never static — a recompile per pair would rebuild
    the program on every cache hit)."""
    import jax.numpy as jnp

    from chainermn_tpu.serving.cache_pool import CachePool
    from chainermn_tpu.serving.engine import DecodeEngine

    params, specs, mesh = _tiny_lm()
    head_dim = 4
    n_kv = 2  # _tiny_lm: 2 heads, no GQA
    pool = CachePool(2, 8, 1, n_kv * head_dim, params["embed"].dtype,
                     mesh, "model")
    eng = DecodeEngine(params, pool, mesh, "model", head_dim=head_dim)
    jfn = eng._build_prefix_copy()
    caches = pool.caches

    def run(c, src, dst):
        return jfn(c, src, dst)

    variants = (jfn, [
        (caches, jnp.int32(0), jnp.int32(1)),
        (caches, jnp.int32(1), jnp.int32(0)),
    ])
    return {"trace": (run, (caches, jnp.int32(0), jnp.int32(1))),
            "bound_axes": {"model"},
            "variants": variants,
            "data_axis": "model",
            "arg_labels": ("caches", "src", "dst"),
            # `caches` needs no annotation here: unlike the tick
            # registrations' P() feeds, this entry threads the REAL
            # pool buffers, sharded P(None, None, model) — the
            # replication report sees them sharded, which is itself
            # the regression signal (a future P() slip would flag)
            "expected_replication": {
                "src": "source slot index: one host-fed int32 scalar "
                       "per copy, replicated to every TP rank by "
                       "design",
                "dst": "destination slot index: same 4-byte host-fed "
                       "scalar as src",
            }}


def _build_kv_transfer() -> Dict[str, Any]:
    """The disaggregated fleet's same-process KV-slab transfer (ISSUE
    9): ``KvTransferPlane.local_program`` over two REAL pools — a
    prefill worker's staging pool and a decode worker's pool — at tiny
    shapes.  The contract under analysis: slot indices are traced
    operands, so ONE compiled program serves every (src, dst) slot
    pair (a recompile per pair would rebuild it on every transfer),
    and with both pools sharding the KV columns identically the PR 8
    reshard lowers to IDENTITY — zero collectives, held to an empty
    ledger by the comm reconciliation (the lane-mode path books its
    bytes as a noted ``kv_transfer_lane@dcn`` row instead, reconciled
    in tests/test_serving_disagg.py against ``transfer_cost``)."""
    import jax.numpy as jnp

    from chainermn_tpu.serving.cache_pool import CachePool
    from chainermn_tpu.serving.transfer import KvTransferPlane

    params, specs, mesh = _tiny_lm()
    head_dim = 4
    n_kv = 2  # _tiny_lm: 2 heads, no GQA
    dtype = params["embed"].dtype
    staging = CachePool(2, 8, 1, n_kv * head_dim, dtype, mesh, "model")
    decode = CachePool(3, 8, 1, n_kv * head_dim, dtype, mesh, "model")
    plane = KvTransferPlane()
    jfn = plane.local_program(staging, decode)

    def run(src_caches, dst_caches, src, dst):
        return jfn(src_caches, dst_caches, src, dst)

    args0 = (staging.caches, decode.caches, jnp.int32(0), jnp.int32(1))
    variants = (jfn, [
        args0,
        (staging.caches, decode.caches, jnp.int32(1), jnp.int32(2)),
        (staging.caches, decode.caches, jnp.int32(0), jnp.int32(0)),
    ])
    return {"trace": (run, args0),
            "bound_axes": {"model"},
            "variants": variants,
            "data_axis": "model",
            "arg_labels": ("src_caches", "dst_caches", "src", "dst"),
            # both pools' caches thread in SHARDED P(None, None, model)
            # like the prefix-copy entry; only the host-fed slot scalars
            # replicate by design
            "expected_replication": {
                "src": "source staging-slot index: one host-fed int32 "
                       "scalar per transfer, replicated to every TP "
                       "rank by design",
                "dst": "destination (reserved) slot index: same 4-byte "
                       "host-fed scalar as src",
            }}


def _build_reshard() -> Dict[str, Any]:
    """The portable redistribution primitive (ISSUE 8,
    ``parallel/reshard.py``): BOTH wire-bearing (src, dst) spec pairs —
    S(0)→R (one all_gather) and S(0)→S(1) (one all_to_all) — in ONE
    compiled program, so the shard-flow reconciliation holds the static
    cost of each collective byte-exact against the runtime comm ledger
    (the elastic-resume acceptance: a reshard's cost is never
    invisible).  Spec pairs are static by construction, so value
    variants must reuse the single program."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu import topology
    from chainermn_tpu._compat import shard_map
    from chainermn_tpu.parallel.reshard import reshard
    from jax.sharding import PartitionSpec as P

    mesh = topology.make_nd_mesh(("mn",), (1,), jax.devices()[:1])

    def body(t):
        gathered = reshard(t, 0, None, "mn")       # S(0) -> R
        transposed = reshard(t, 0, 1, "mn")        # S(0) -> S(1)
        return gathered, transposed

    jfn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("mn", None),),
        out_specs=(P(), P(None, "mn"))))

    x = np.arange(32, dtype=np.float32).reshape(4, 8)

    def run(v):
        return jfn(jnp.asarray(v))

    variants = (jfn, [(jnp.asarray(x),), (jnp.asarray(x + 1),)])
    return {"trace": (run, (jnp.asarray(x),)),
            "bound_axes": {"mn"},
            "variants": variants,
            # the input rides in SHARDED (that is the primitive's whole
            # point) — the replication report must stay empty here
            "data_axis": "mn", "arg_labels": ("tree",)}


def _build_flight_ring_program() -> Dict[str, Any]:
    """Flight-recorder entry point: the accounted collective ring run
    UNDER the ring tee (comm deltas -> flight events).  Guards the other
    direction of the ISSUE 5 wiring — the accountant's flight tee fires
    from host callbacks only, so the traced program's collective
    sequence and compile count are byte-identical with the recorder
    on."""
    from chainermn_tpu.observability import flight

    base = _build_collective_ring()
    fn, args = base["trace"]

    def run_teed(*a):
        with _traced_obs_state():
            out = fn(*a)
            flight.note("phase", name="collective/ring")
        return out

    return {"trace": (run_teed, args), "bound_axes": base["bound_axes"]}


def _tiny_mlp_fixture():
    """Shared tiny-MLP (params, batch) for the train-step entry points —
    deterministic numpy, no jax PRNG (analysis must trace the same
    program every run)."""
    import numpy as np

    rng = np.random.RandomState(_SEED)
    params = {
        "w1": rng.randn(8, 16).astype(np.float32) / 4,
        "b1": np.zeros((16,), np.float32),
        "w2": rng.randn(16, 4).astype(np.float32) / 4,
        "b2": np.zeros((4,), np.float32),
    }
    batch = (rng.randn(4, 8).astype(np.float32),
             rng.randint(0, 4, (4,)).astype(np.int32))
    return params, batch


def _build_train_step() -> Dict[str, Any]:
    """The PRODUCTION train-step builder (`make_train_step` +
    `create_multi_node_optimizer`/adam) — the program whose replication
    report must name the full optimizer-state replication ZeRO-1
    (ROADMAP item 2) will remove.  Its gradient all-reduce on the default
    path is AUTODIFF-INSERTED and booked via ``comm.note`` — declared
    here as a ``noted`` row (held byte-exact by the reconciliation) —
    and on legacy jax the transpose of the loss pmean adds one scalar
    psum equation no wrapper books (``ad_transpose_bytes``)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from chainermn_tpu import topology
    from chainermn_tpu.optimizers import create_multi_node_optimizer
    from chainermn_tpu.train import make_train_step

    mesh = topology.make_nd_mesh(("mn",), (1,), jax.devices()[:1])
    params, batch = _tiny_mlp_fixture()

    def loss_fn(p, b):
        x, y = b
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        logits = h @ p["w2"] + p["b2"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, y[:, None], axis=1).mean()

    optimizer = create_multi_node_optimizer(optax.adam(1e-3), "mn")
    # donate=False: the analyzer calls the step repeatedly on the same
    # buffers (ledger run, then make_jaxpr) — donation would poison them
    step = make_train_step(loss_fn, optimizer, mesh=mesh, donate=False)
    opt_state = optimizer.init(params)

    params_bytes = int(sum(
        np.prod(v.shape) * v.dtype.itemsize
        for v in jax.tree_util.tree_leaves(params)))

    def run(p, s, b):
        return step(p, s, b)

    return {"trace": (run, (params, opt_state, batch)),
            "bound_axes": {"mn"},
            "data_axis": "mn",
            "arg_labels": ("params", "opt_state", "batch"),
            "expected_replication": {
                "params": "data parallelism replicates parameters on "
                          "every replica by definition",
                "opt_state": "FULL optimizer-state replication — the "
                             "exact blowup ZeRO-1 weight-update sharding "
                             "(ROADMAP item 2, arxiv 2004.13336) removes; "
                             "delete this annotation when it lands and "
                             "the report diff goes red→green",
            },
            # the AD-inserted gradient psum, booked by train.py's
            # comm.note at exactly the params' byte size
            "noted": {"grad_allreduce_ad@mn": params_bytes},
            # legacy jax: transpose(psum(loss)) is one more scalar psum
            "ad_transpose_bytes": {"psum@mn": 4}}


def _build_quantized_train_step() -> Dict[str, Any]:
    """The QUANTIZED train step (ISSUE 14): `make_train_step` +
    `create_multi_node_optimizer(allreduce_grad_dtype='int8',
    error_feedback=True, double_buffering=True)` — the combined
    quantized+double-buffered mode on a tiny MLP at the largest virtual
    axis this process has (2 under the lint tier's 8-device env; degrades
    to 1 on a bare CPU runner, where the ring short-circuits and the
    entry still pins the one-program discipline).

    Contracts under analysis: ONE compiled program across value variants
    (the EF builder binds shard_map lazily per opt-state structure — a
    per-call rebind would recompile every step), the EF residual rows
    SHARDED over the data axis (inner optimizer state stays replicated —
    annotated as the tracked ZeRO-1 debt), and the hand-written int8
    ring schedule held byte-exact: the composite ledger row
    (``quantized_ring_pmean@mn``, compressed-wire convention) is swapped
    for ``quantized_ring_static_groups``'s per-primitive bytes by the
    reconciliation."""
    import jax
    import numpy as np
    import optax

    from chainermn_tpu import topology
    from chainermn_tpu.ops.collective import (quantized_ring_cost,
                                              quantized_ring_static_groups)
    from chainermn_tpu.optimizers import create_multi_node_optimizer
    from chainermn_tpu.train import make_train_step

    ndev = min(2, len(jax.devices()))
    mesh = topology.make_nd_mesh(("mn",), (ndev,), jax.devices()[:ndev])
    params, batch = _tiny_mlp_fixture()
    block, pipeline = 8, 2

    def loss_fn(p, b):
        import jax.numpy as jnp

        x, y = b
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        logits = h @ p["w2"] + p["b2"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, y[:, None], axis=1).mean()

    optimizer = create_multi_node_optimizer(
        optax.sgd(1e-2, momentum=0.9), "mn",
        allreduce_grad_dtype="int8", error_feedback=True,
        double_buffering=True, quant_block=block,
        quant_pipeline=pipeline, world=ndev)
    # donate=False: the analyzer calls the step repeatedly on the same
    # buffers (ledger run, then make_jaxpr) — donation would poison them
    step = make_train_step(loss_fn, optimizer, mesh=mesh, donate=False,
                           allreduce_grad_dtype="int8",
                           error_feedback=True)
    opt_state = optimizer.init(params)

    n_total = int(sum(np.prod(v.shape)
                      for v in jax.tree_util.tree_leaves(params)))
    spec: Dict[str, Any] = {
        "bound_axes": {"mn"},
        "data_axis": "mn",
        "arg_labels": ("params", "opt_state", "batch"),
        "expected_replication": {
            # `params` deliberately UN-annotated: this entry's keeper
            # finding (with comment) in .shardflow-baseline.json proves
            # the replication gate bites on the quantized path too
            "opt_state.inner": "inner momentum replicates per replica — "
                               "the ZeRO-1 debt, tracked on train.step; "
                               "the EF residual rows (opt_state.ef) are "
                               "the SHARDED exception this entry proves "
                               "out, so they carry NO annotation and the "
                               "report shows them at 0 replicated bytes",
            "opt_state.stale_grads": "the double-buffer's 1-step-stale "
                                     "mean gradients are globally "
                                     "identical by construction — "
                                     "replicated like the params they "
                                     "update",
        },
    }
    if ndev > 1:
        # the hand-written int8 ring: one composite ledger row for the
        # whole gradient bucket, swapped for its per-primitive groups
        spec["composite"] = {
            "quantized_ring_pmean@mn": {
                "ledger_bytes": quantized_ring_cost(
                    n_total, ndev, "int8", block, pipeline)["ledger_bytes"],
                "static_groups": quantized_ring_static_groups(
                    n_total, ndev, "mn", "int8", block, pipeline),
            },
        }

    batch = tuple(np.ascontiguousarray(a[: 2 * ndev]) for a in batch)

    def run(p, s, b):
        return step(p, s, b)

    variants = (step, [
        (params, opt_state, batch),
        ({k: v + 0.01 for k, v in params.items()}, opt_state, batch),
    ])
    spec["trace"] = (run, (params, opt_state, batch))
    spec["variants"] = variants
    return spec


def _build_demo_train_step() -> Dict[str, Any]:
    """The train CLI's demo step (`make_demo_step`): local grads + the
    EXPLICIT accounted ring mean + accounted metric psums — no autodiff-
    inserted collectives at all, so this entry reconciles with zero
    declarations: every ledger row has its equation and vice versa."""
    import jax
    import optax

    from chainermn_tpu import topology
    from chainermn_tpu.train import make_demo_step

    mesh = topology.make_nd_mesh(("mn",), (1,), jax.devices()[:1])
    params, batch = _tiny_mlp_fixture()
    optimizer = optax.sgd(1e-2, momentum=0.9)
    step = make_demo_step(optimizer, mesh=mesh)
    state = (params, optimizer.init(params))

    def run(s, b):
        return step(s, b)

    return {"trace": (run, (state, batch)),
            "bound_axes": {"mn"},
            "data_axis": "mn",
            "arg_labels": ("state", "batch"),
            "expected_replication": {
                "state": "the demo step replicates (params, momentum) "
                         "per replica — same ZeRO-1 debt as train.step, "
                         "tracked there per-argument",
            }}


class _SupervisedTickProbe:
    """Variant probe for the SUPERVISED tick (ISSUE 10): every call
    runs under the scoped tracer+tee state AND one full supervision-
    plane round — heartbeat lease publish, supervisor-side lease read +
    epoch-fence admission, circuit-breaker consult — the host path a
    fleet worker's device call lives under in production.  The health
    plane must add ZERO device traffic and ZERO compiles."""

    def __init__(self, jfn, plane):
        self._jfn = jfn
        self._plane = plane   # (publisher, table, fence, breaker)

    def __call__(self, *a):
        from chainermn_tpu import observability as obs
        from chainermn_tpu.observability import flight
        pub, table, fence, breaker = self._plane
        with _traced_obs_state():
            pub.beat(queue_depth=0, free_slots=1, busy_slots=1)
            with obs.span("serving/tick", cat="serving"):
                out = self._jfn(*a)
            lease = table.read("analysis-worker")
            fence.admit("analysis-worker", lease["epoch"], "lease")
            breaker.allow()
            flight.note("fleet", event="supervisor_tick",
                        worker="analysis-worker",
                        lease_seq=lease["seq"])
            flight.note("phase", name="fleet/supervise")
        return out

    def _cache_size(self):
        return self._jfn._cache_size()


def _build_supervisor_tick() -> Dict[str, Any]:
    """The serving decode tick as a SUPERVISED fleet worker runs it
    (ISSUE 10): heartbeat publish on the loopback lane store, lease
    read + epoch-fence admission + breaker consult on the supervisor
    side, tracer + flight tee installed — all host-side bookkeeping.
    One program across value variants: liveness must never leak into
    trace-time."""
    from chainermn_tpu.serving.health import (CircuitBreaker, EpochFence,
                                              HeartbeatPublisher,
                                              LeaseTable)
    from chainermn_tpu.serving.transfer import InProcessLaneStore

    base = _build_decode_tick()
    fn, args = base["trace"]
    store = InProcessLaneStore()
    fence = EpochFence()
    epoch = fence.new_epoch("analysis-worker")
    plane = (HeartbeatPublisher(store, "analysis-worker", "engine", epoch),
             LeaseTable(store), fence, CircuitBreaker())
    probe = _SupervisedTickProbe(base["variants"][0], plane)

    def run_supervised(*a):
        return probe(*a)

    return {"trace": (run_supervised, args),
            "bound_axes": base["bound_axes"],
            "variants": (probe, base["variants"][1])}


class _AutoscaleTickProbe:
    """Variant probe for the AUTOSCALED tick (ISSUE 11): every call
    runs one full control-loop round around the compiled decode tick —
    degradation-ladder update on an overload pressure signal, tenant
    budget check + admission bookkeeping, and an
    :class:`~chainermn_tpu.serving.autoscale.AutoscalePolicy` decision
    over a synthetic oscillating signal trace (fake receiver clock, so
    the probe is deterministic).  The policy tick is pure host
    bookkeeping: it must add ZERO device traffic and ZERO compiles —
    scaling decisions never leak into trace-time."""

    def __init__(self, jfn, policy, table):
        self._jfn = jfn
        self._policy = policy
        self._table = table
        self._calls = 0

    def __call__(self, *a):
        from chainermn_tpu.observability import flight
        from chainermn_tpu.serving.scheduler import Request

        self._calls += 1
        now = float(self._calls)          # fake receiver clock
        # oscillating synthetic load: hysteresis must absorb it
        backlog = 512 if self._calls % 2 else 0
        self._table.ladder.update(0.5 if backlog else 0.0, now=now)
        tenant = self._table.resolve("analysis-tenant", "best_effort")
        refused = self._table.admission_check(tenant, now=now)
        if refused is None:
            self._table.on_admit(tenant, Request([1], 1), capped=False)
        out = self._jfn(*a)
        dec = self._policy.decide(
            {"live_workers": 1, "backlog_tokens": backlog,
             "queue_depth": 4 if backlog else 0, "shed_rate": 0.0},
            now)
        if dec is not None:
            flight.note("autoscale_decision",
                        **{k: v for k, v in dec.items()
                           if k != "event"})
        flight.note("phase", name="fleet/autoscale_tick")
        return out

    def _cache_size(self):
        return self._jfn._cache_size()


def _build_autoscale_tick() -> Dict[str, Any]:
    """The serving decode tick as the AUTOSCALED fleet runs it
    (ISSUE 11): ladder update + tenant budget bookkeeping + one policy
    decision per call, all host-side.  One program across value
    variants: elasticity must never leak into trace-time."""
    from chainermn_tpu.serving.autoscale import AutoscalePolicy
    from chainermn_tpu.serving.tenancy import TenantTable

    base = _build_decode_tick()
    fn, args = base["trace"]
    policy = AutoscalePolicy(min_workers=1, max_workers=2,
                             up_cooldown_s=3.0, down_cooldown_s=6.0,
                             down_stable_s=6.0)
    table = TenantTable()
    probe = _AutoscaleTickProbe(base["variants"][0], policy, table)

    def run_autoscaled(*a):
        return probe(*a)

    return {"trace": (run_autoscaled, args),
            "bound_axes": base["bound_axes"],
            "variants": (probe, base["variants"][1])}


class _WorkerLaneProbe:
    """Variant probe for the lane LANDING program (ISSUE 10): every
    call runs one worker-lane mailbox round trip (pickled control
    message out, consumed in order on the receiver side) around the
    compiled slab write — the cross-process protocol's host path.  The
    mailbox hop must add zero device traffic and zero compiles."""

    def __init__(self, jfn, sender, receiver):
        self._jfn = jfn
        self._sender = sender
        self._receiver = receiver

    def __call__(self, *a):
        from chainermn_tpu.observability import flight
        with _traced_obs_state():
            self._sender.send({"kind": "install", "epoch": 1,
                               "trace_id": "req-analysis-wl00000000",
                               "tag": "slab/req-analysis-wl00000000"})
            msg = self._receiver.recv()
            out = self._jfn(*a)
            flight.note("worker", event="installed",
                        worker="analysis-decode0",
                        trace_id=msg["trace_id"])
            flight.note("phase", name="worker/step")
        return out

    def _cache_size(self):
        return self._jfn._cache_size()


def _build_worker_lane() -> Dict[str, Any]:
    """The worker lane protocol's device half (ISSUE 10): the
    pool-lifetime compiled slab INJECT program
    (:meth:`KvTransferPlane.inject_program`) that lands every
    cross-process transfer, run under one mailbox round trip per call.
    Contract: pure data movement — ZERO collectives (each TP rank
    writes its local KV columns; held to an empty ledger by the comm
    reconciliation) and ONE compiled program across (slab values, dst
    slot) variants."""
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.serving.cache_pool import CachePool
    from chainermn_tpu.serving.lanes import (MailboxReceiver,
                                             MailboxSender)
    from chainermn_tpu.serving.transfer import (InProcessLaneStore,
                                                KvTransferPlane)

    params, specs, mesh = _tiny_lm()
    head_dim = 4
    n_kv = 2  # _tiny_lm: 2 heads, no GQA
    dtype = params["embed"].dtype
    pool = CachePool(2, 8, 1, n_kv * head_dim, dtype, mesh, "model")
    plane = KvTransferPlane()
    jfn = plane.inject_program(pool)

    rng = np.random.RandomState(_SEED)
    slab = [(jnp.asarray(rng.randn(8, n_kv * head_dim).astype(dtype)),
             jnp.asarray(rng.randn(8, n_kv * head_dim).astype(dtype)))]
    store = InProcessLaneStore()
    probe = _WorkerLaneProbe(
        jfn, MailboxSender(store, "ctl.analysis-decode0"),
        MailboxReceiver(store, "ctl.analysis-decode0"))

    def run(caches, slabs, dst):
        return probe(caches, slabs, dst)

    args0 = (pool.caches, slab, jnp.int32(0))
    variants = (probe, [
        args0,
        (pool.caches, slab, jnp.int32(1)),
    ])
    return {"trace": (run, args0),
            "bound_axes": {"model"},
            "variants": variants,
            "data_axis": "model",
            "arg_labels": ("dst_caches", "slabs", "dst"),
            # dst_caches/slabs thread in SHARDED (P(None, None, model) /
            # P(None, model)); only the host-fed slot scalar replicates
            "expected_replication": {
                "dst": "destination (reserved) slot index: one host-fed "
                       "int32 scalar per landing, replicated to every "
                       "TP rank by design",
            }}


def _is_tracing(args) -> bool:
    """True when ``args`` carry jax tracers (the probe is being traced
    for the jaxpr engine, not called on concrete variant values)."""
    import jax

    return any(isinstance(x, jax.core.Tracer)
               for x in jax.tree_util.tree_leaves(args))


class _KvSpillProbe:
    """Variant probe for the host-RAM spill tier (ISSUE 12): every call
    runs one full spill round trip around the compiled inject program —
    pack the source slot (CRC stamped), put/get through the bounded
    host store, CRC-verified ``unpack_into`` restore into the restore
    slot — and asserts the restore is BYTE-EXACT vs the packed rows
    with its ledger booking equal to the ``transfer_cost`` statics.
    The spill tier is host bookkeeping: one compiled program across
    (slab, slot) variants, zero device-traffic growth."""

    def __init__(self, jfn, pool, plane, spill, length):
        self._jfn = jfn
        self._pool = pool
        self._plane = plane
        self._spill = spill
        self._length = int(length)

    def __call__(self, *a):
        import pickle

        import jax
        import numpy as np

        from chainermn_tpu.observability import flight
        from chainermn_tpu.serving.transfer import (SPILL_AXIS, SPILL_OP,
                                                    transfer_cost)
        if _is_tracing(a):
            # under the jaxpr trace every jax op stages to tracers —
            # the host round trip (device_get inside pack) cannot run;
            # the trace captures the inject program, which is the
            # device contract under analysis
            return self._jfn(*a)
        pool, L = self._pool, self._length
        seq = tuple(range(L))
        with _traced_obs_state():
            payload = self._plane.pack(pool, 0, L,
                                       meta={"seq": list(seq),
                                             "length": L})
            assert self._spill.put(seq, L, payload)
            got = self._spill.get(seq)
            stats = self._plane.unpack_into(
                got, pool, 1, ledger_op=SPILL_OP,
                ledger_axis=SPILL_AXIS)
            want = transfer_cost(pool.n_layers, L, pool.kv_dim,
                                 pool.caches[0][0].dtype, mode="lanes")
            assert stats["ledger_bytes"] == want["ledger_bytes"], (
                stats, want)
            # byte-exact round trip: the restored rows ARE the packed
            # rows (the ISSUE 12 acceptance, held here on every call)
            rows = pickle.loads(payload)["rows"]
            for (ks, vs), (kc, vc) in zip(rows, pool.caches):
                np.testing.assert_array_equal(
                    ks, np.asarray(jax.device_get(kc[1, :L])))
                np.testing.assert_array_equal(
                    vs, np.asarray(jax.device_get(vc[1, :L])))
            out = self._jfn(*a)
            flight.note("serving", event="restore", prefix_len=L)
            flight.note("phase", name="serving/spill_restore")
        return out

    def _cache_size(self):
        return self._jfn._cache_size()


def _build_kv_spill() -> Dict[str, Any]:
    """The host-RAM spill tier's device half (ISSUE 12): the SAME
    pool-lifetime compiled inject program every lane transfer lands
    through, here driven by the spill round trip (pack → bounded host
    LRU store → CRC verify → restore).  Contract: one program across
    (slab, dst slot) variants, byte-exact restores, ledger-reconciled
    against ``transfer_cost`` statics — all asserted in-probe on every
    call."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.serving.cache_pool import CachePool
    from chainermn_tpu.serving.spill import HostSpillStore
    from chainermn_tpu.serving.transfer import KvTransferPlane

    params, specs, mesh = _tiny_lm()
    head_dim = 4
    n_kv = 2  # _tiny_lm: 2 heads, no GQA
    dtype = params["embed"].dtype
    pool = CachePool(2, 8, 1, n_kv * head_dim, dtype, mesh, "model")
    # give slot 0 real (random) K/V so the byte-exact check is honest
    # (keep the pool's sharding — an unsharded replacement would make
    # the first inject call compile a second program)
    from jax.sharding import NamedSharding
    sharding = NamedSharding(mesh, pool.cache_spec)
    rng = np.random.RandomState(_SEED)
    pool.caches = [
        (jax.device_put(rng.randn(2, 8, n_kv * head_dim).astype(dtype),
                        sharding),
         jax.device_put(rng.randn(2, 8, n_kv * head_dim).astype(dtype),
                        sharding))]
    plane = KvTransferPlane()
    spill = HostSpillStore(capacity_bytes=1 << 20)
    jfn = plane.inject_program(pool)
    probe = _KvSpillProbe(jfn, pool, plane, spill, length=6)

    def run(caches, slabs, dst):
        return probe(caches, slabs, dst)

    slab = [(jnp.asarray(rng.randn(8, n_kv * head_dim).astype(dtype)),
             jnp.asarray(rng.randn(8, n_kv * head_dim).astype(dtype)))]
    args0 = (pool.caches, slab, jnp.int32(0))
    variants = (probe, [
        args0,
        (pool.caches, slab, jnp.int32(1)),
    ])
    return {"trace": (run, args0),
            "bound_axes": {"model"},
            "variants": variants,
            "data_axis": "model",
            "arg_labels": ("dst_caches", "slabs", "dst"),
            "expected_replication": {
                "dst": "restore-slot index: one host-fed int32 scalar "
                       "per restore, replicated to every TP rank by "
                       "design",
            }}


class _RemotePullProbe:
    """Variant probe for the fleet remote-pull path (ISSUE 12): every
    call runs the full cross-worker host protocol around the compiled
    inject program — owner pack (CRC stamped) → object lane put/get →
    RESERVED destination slot → CRC-verified ``unpack_into`` →
    reservation commit → recycle — and asserts the lane booking equals
    the ``transfer_cost(mode="lanes")`` statics the router prices the
    pull decision with.  The pull plane is host bookkeeping: one
    compiled program, reservation invariants intact on every call."""

    def __init__(self, jfn, src_pool, dst_pool, plane, length):
        self._jfn = jfn
        self._src = src_pool
        self._dst = dst_pool
        self._plane = plane
        self._length = int(length)
        self._calls = 0

    def __call__(self, *a):
        from chainermn_tpu.observability import flight
        from chainermn_tpu.serving.transfer import transfer_cost
        if _is_tracing(a):
            # see _KvSpillProbe: the host protocol cannot run under
            # the jaxpr trace; the inject program IS the device half
            return self._jfn(*a)
        self._calls += 1
        L = self._length
        tag = f"pfx/req-analysis-pull{self._calls:08d}"
        with _traced_obs_state():
            payload = self._plane.pack(
                self._src, 0, L,
                meta={"seq": list(range(L)), "length": L})
            self._plane.lane_put(tag, payload)
            slot = self._dst.reserve()
            assert slot is not None
            got = self._plane.lane_get(tag, 5.0)
            stats = self._plane.unpack_into(got, self._dst, slot)
            want = transfer_cost(self._dst.n_layers, L,
                                 self._dst.kv_dim,
                                 self._dst.caches[0][0].dtype,
                                 mode="lanes")
            assert stats["ledger_bytes"] == want["ledger_bytes"], (
                stats, want)
            self._dst.commit_reservation(slot)
            self._dst.release(slot)      # recycle for the next call
            self._plane.lane_delete(tag)
            out = self._jfn(*a)
            flight.note("fleet", event="remote_pull_done",
                        prefix_len=L)
            flight.note("phase", name="fleet/remote_pull")
        return out

    def _cache_size(self):
        return self._jfn._cache_size()


def _build_remote_pull() -> Dict[str, Any]:
    """The fleet-global KV economy's remote prefix pull (ISSUE 12):
    owner-side pack → object lane → CRC-verified landing into a
    router-reserved slot through the pool-lifetime compiled inject
    program.  Contract: one program across (slab, slot) variants, the
    reservation state machine exercised on every call, lane bytes
    ledger-reconciled against the same ``transfer_cost`` statics the
    router's transfer-vs-re-prefill decision prices in token units."""
    import jax.numpy as jnp
    import numpy as np

    from chainermn_tpu.serving.cache_pool import CachePool
    from chainermn_tpu.serving.transfer import (InProcessLaneStore,
                                                KvTransferPlane)

    params, specs, mesh = _tiny_lm()
    head_dim = 4
    n_kv = 2  # _tiny_lm: 2 heads, no GQA
    dtype = params["embed"].dtype
    owner = CachePool(2, 8, 1, n_kv * head_dim, dtype, mesh, "model")
    dst = CachePool(2, 8, 1, n_kv * head_dim, dtype, mesh, "model")
    plane = KvTransferPlane(transport=InProcessLaneStore())
    jfn = plane.inject_program(dst)
    probe = _RemotePullProbe(jfn, owner, dst, plane, length=5)

    rng = np.random.RandomState(_SEED)
    slab = [(jnp.asarray(rng.randn(8, n_kv * head_dim).astype(dtype)),
             jnp.asarray(rng.randn(8, n_kv * head_dim).astype(dtype)))]

    def run(caches, slabs, dst_slot):
        return probe(caches, slabs, dst_slot)

    args0 = (dst.caches, slab, jnp.int32(0))
    variants = (probe, [
        args0,
        (dst.caches, slab, jnp.int32(1)),
    ])
    return {"trace": (run, args0),
            "bound_axes": {"model"},
            "variants": variants,
            "data_axis": "model",
            "arg_labels": ("dst_caches", "slabs", "dst_slot"),
            "expected_replication": {
                "dst_slot": "reserved destination-slot index: one "
                            "host-fed int32 scalar per landing, "
                            "replicated to every TP rank by design",
            }}


def select_entrypoints(names=None, for_shardflow: bool = False):
    """Resolve ``--entry`` names against the registry — the ONE resolver
    both runners share (``cli.py`` and ``shardflow.main``).

    Returns ``(entrypoints, error)``.  ``names=None`` selects everything
    (minus ``shardflow=False`` entries when ``for_shardflow``).  An
    unknown name is an error, and so is EXPLICITLY naming a
    ``shardflow=False`` entry under ``for_shardflow`` — silently
    analyzing 0 entry points would read as a clean verdict.
    """
    if not names:
        eps = list(ENTRYPOINTS)
        if for_shardflow:
            eps = [ep for ep in eps if getattr(ep, "shardflow", True)]
        return eps, None
    by_name = {ep.name: ep for ep in ENTRYPOINTS}
    unknown = [n for n in names if n not in by_name]
    if unknown:
        return None, (f"unknown entry point(s): {', '.join(unknown)} "
                      f"(known: {', '.join(sorted(by_name))})")
    eps = [by_name[n] for n in names]
    if for_shardflow:
        skipped = [ep.name for ep in eps
                   if not getattr(ep, "shardflow", True)]
        if skipped:
            return None, (
                f"entry point(s) registered shardflow=False — the base "
                f"entry owns their compiled program's shard-flow "
                f"analysis: {', '.join(skipped)}")
    return eps, None


ENTRYPOINTS = [
    EntryPoint(
        name="ops.collective.ring",
        build=_build_collective_ring,
        description="reduce_scatter+all_gather+shift+psum gradient ring "
                    "over axis 'mn' (the train CLI's demo reduction)"),
    EntryPoint(
        name="train.step",
        build=_build_train_step,
        description="make_train_step + MultiNodeOptimizer(adam) on a "
                    "tiny MLP — the production DP step; replication "
                    "report names the optimizer-state blowup ZeRO-1 "
                    "removes (ROADMAP item 2)"),
    EntryPoint(
        name="train.quantized_step",
        build=_build_quantized_train_step,
        description="make_train_step + MultiNodeOptimizer(int8 wire, "
                    "error feedback, double buffering) — the combined "
                    "quantized+double-buffered step (ISSUE 14): one "
                    "program across value variants, EF residual rows "
                    "sharded per rank, the int8 ring schedule "
                    "reconciled byte-exact via its composite "
                    "declaration"),
    EntryPoint(
        name="train.demo_step",
        build=_build_demo_train_step,
        description="the train CLI's demo step: explicit accounted ring "
                    "mean, fully reconciled with no declarations"),
    EntryPoint(
        name="parallel.reshard",
        build=_build_reshard,
        description="portable redistribution primitive: S(0)->R "
                    "(all_gather) + S(0)->S(1) (all_to_all) in one "
                    "compiled program — static reshard cost reconciled "
                    "byte-exact against the comm ledger (ISSUE 8)"),
    EntryPoint(
        name="parallel.decode.lm_decode_tick",
        build=_build_decode_tick,
        description="serving decode tick under shard_map('model') — one "
                    "program for the pool's lifetime"),
    EntryPoint(
        name="serving.prefill_family",
        build=_build_prefill_family,
        allow_recompile=True,
        description="per-prompt-length prefill programs (intentional "
                    "program family, see docs/SERVING.md)"),
    EntryPoint(
        name="serving.router_tick",
        build=_build_router_tick,
        description="replica decode tick under the ROUTER tee: tracer "
                    "+ flight tee + router dispatch/per-request "
                    "emissions — one program, zero extra device "
                    "traffic, bytes reconciled independently of the "
                    "base entry (ISSUE 7)"),
    EntryPoint(
        name="serving.prefix_copy",
        build=_build_prefix_copy,
        description="prefix-cache copy-on-extend slab copy "
                    "(DecodeEngine.copy_prefix): zero collectives, one "
                    "compiled program across (src, dst) slot variants "
                    "(ISSUE 7)"),
    EntryPoint(
        name="serving.kv_transfer",
        build=_build_kv_transfer,
        description="disaggregated KV-slab transfer "
                    "(KvTransferPlane.local_program): one compiled "
                    "program across (src, dst) slot variants, identity "
                    "reshard at matching pool specs — zero collectives, "
                    "bytes ledger-reconciled (ISSUE 9)"),
    EntryPoint(
        name="serving.supervisor_tick",
        build=_build_supervisor_tick,
        shardflow=False,  # same compiled program as the decode tick —
        #                   the base entry owns its shard-flow analysis
        description="serving decode tick under the fleet supervision "
                    "plane: heartbeat lease publish + supervisor lease "
                    "read + epoch-fence admission + breaker consult — "
                    "liveness is host-side bookkeeping: one program, "
                    "zero extra device traffic (ISSUE 10)"),
    EntryPoint(
        name="serving.autoscale_tick",
        build=_build_autoscale_tick,
        shardflow=False,  # same compiled program as the decode tick —
        #                   the base entry owns its shard-flow analysis
        description="serving decode tick under the autoscale control "
                    "loop: degradation-ladder update + tenant budget "
                    "bookkeeping + one AutoscalePolicy decision per "
                    "call over a synthetic oscillating trace — "
                    "elasticity is host-side bookkeeping: one program, "
                    "zero extra device traffic (ISSUE 11)"),
    EntryPoint(
        name="serving.worker_lane",
        build=_build_worker_lane,
        description="cross-process worker lane landing program "
                    "(KvTransferPlane.inject_program) under a mailbox "
                    "round trip per call: zero collectives, one "
                    "compiled program across (slab, dst slot) variants "
                    "(ISSUE 10)"),
    EntryPoint(
        name="serving.kv_spill",
        build=_build_kv_spill,
        shardflow=False,  # same compiled inject program as
        #                   serving.worker_lane — the base entry owns
        #                   its shard-flow analysis
        description="host-RAM spill tier round trip (pack -> bounded "
                    "LRU store -> CRC verify -> compiled restore): one "
                    "program across (slab, slot) variants, byte-exact "
                    "restores ledger-reconciled against transfer_cost "
                    "statics (ISSUE 12)"),
    EntryPoint(
        name="serving.remote_pull",
        build=_build_remote_pull,
        shardflow=False,  # same compiled inject program as
        #                   serving.worker_lane — the base entry owns
        #                   its shard-flow analysis
        description="fleet remote prefix pull (owner pack -> object "
                    "lane -> reserved-slot CRC-verified landing): one "
                    "program, reservation state machine exercised per "
                    "call, lane bytes reconciled against the pricing "
                    "statics (ISSUE 12)"),
    EntryPoint(
        name="serving.tick_with_tracing",
        build=_build_tick_with_tracing,
        shardflow=False,  # same compiled program as the decode tick —
        #                   the base entry owns its shard-flow analysis
        description="serving decode tick with the tracer enabled and "
                    "the flight-recorder tee installed — observability "
                    "must stay host-side: one program, no tracer leak "
                    "(ISSUE 5)"),
    EntryPoint(
        name="observability.flight_ring",
        build=_build_flight_ring_program,
        shardflow=False,  # same compiled program as ops.collective.ring
        description="accounted collective ring under the flight-"
                    "recorder comm tee — the ring records from host "
                    "callbacks only, leaving the traced program "
                    "unchanged (ISSUE 5)"),
]
