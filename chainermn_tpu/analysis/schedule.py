"""Collective schedule IR — comm programs as compiled, checkable artifacts.

ROADMAP item 3 / GC3 (arxiv 2201.11840): a redistribution between two
sharding specs should not be one opaque monolithic collective but an
explicit PROGRAM of transfers that can be chunked, pipelined, and staged
hierarchically over ICI-then-DCN — and statically verified before it
ever runs.  This module is the IR + the lowering generators + the r04
cost model; the verifier (coverage, exhaustive BFS model check,
deterministic interpreter) lives in :mod:`.schedule_check`.

IR grammar (schema ``chainermn_tpu.schedule.v1``)::

    Schedule  := array geometry (shape/dtype/src_spec/dst_spec/worlds)
                 + Topology + {Chunk} + {Transfer} + per-rank programs
    Chunk     := named payload: (src_rank, dst_rank,
                                 segments=[(src_off, dst_off, n), ...])
                 offsets in ELEMENTS of the flattened local blocks
    Transfer  := (tid, chunk, src, dst, dest∈{out,stage}, link∈{ici,dcn},
                  via=None | staged-chunk-name)
    Op        := copy(chunk)     -- local in-block → out-block
               | unstage(chunk)  -- local stage     → out-block
               | start(tid)      -- async issue on Transfer.src (a "send")
               | done(tid)       -- blocking await on Transfer.dst (a "recv")

``start``/``done`` are the async halves the item-5 bucket-pipelined
allreduce will reuse; a synchronous send/recv pair is simply a start
immediately awaited.  A ``reduce`` op kind is reserved in the grammar
for that plane (parsed, serialized, refused by the verifier until the
accumulation coverage rule lands).

A Transfer with ``via=c`` forwards a previously STAGED chunk ``c`` from
its ``src`` rank instead of gathering from the in-block — that is the
hierarchical staging primitive: cross-slice bytes go over DCN ONCE to a
gateway rank, which fans them out over ICI to its slice peers
(portable-redistribution, arxiv 2112.01075).  The verifier demands the
via chunk's source projection be byte-identical to the forwarded
chunk's (same global elements), so staging can never smuggle wrong
bytes.

Everything here is stdlib + numpy; no jax import (the analysis-package
contract).  Cost constants are the BENCH_r04 ``project_dp_scaling``
assumptions in ``bench.py`` (v5e ICI 1.8e11 B/s, 1 µs/hop; DCN 2.5e10
B/s per host) — the schedule chooser and the scaling projection price
the same wire.
"""

from __future__ import annotations

import hashlib
import json
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "SCHEDULE_SCHEMA", "CALIBRATION_SCHEMA", "Topology", "Chunk",
    "Transfer", "Op", "Schedule",
    "CostModel", "calibrated_cost_model",
    "block_shape", "block_global_indices", "expected_flow",
    "lower_single", "lower_chunked", "lower_pipelined",
    "lower_hierarchical", "GENERATORS", "candidate_schedules",
    "price_schedule",
]

SCHEDULE_SCHEMA = "chainermn_tpu.schedule.v1"

OP_KINDS = ("copy", "unstage", "start", "done", "reduce")
#: synchronous aliases accepted by from_json (GC3 grammar speaks
#: send/recv; our canonical async forms are start/done).
_OP_ALIASES = {"send": "start", "recv": "done"}
LINKS = ("ici", "dcn")
DESTS = ("out", "stage")


# --------------------------------------------------------------------------
# topology
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class Topology:
    """``slices`` pods of ``per_slice`` ranks; intra-slice wire is ICI,
    cross-slice is DCN (the two-tier TPU fabric of
    ``hierarchical_pmean``)."""
    slices: int
    per_slice: int

    @property
    def size(self) -> int:
        return self.slices * self.per_slice

    @classmethod
    def flat(cls, world: int) -> "Topology":
        return cls(1, int(world))

    def slice_of(self, rank: int) -> int:
        return rank // self.per_slice

    def pos_of(self, rank: int) -> int:
        return rank % self.per_slice

    def link(self, a: int, b: int) -> str:
        if a == b:
            raise ValueError("no link from a rank to itself")
        return "ici" if self.slice_of(a) == self.slice_of(b) else "dcn"


# --------------------------------------------------------------------------
# IR nodes
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class Chunk:
    """A named payload: ``segments`` are (src_off, dst_off, n) runs in
    elements of the flattened (C-order) local blocks."""
    name: str
    src_rank: int
    dst_rank: int
    segments: Tuple[Tuple[int, int, int], ...]

    @property
    def nelems(self) -> int:
        return sum(n for _, _, n in self.segments)

    def src_side(self) -> Tuple[Tuple[int, int], ...]:
        """The source projection (src_off, n) — what bytes this chunk
        reads, independent of where they land."""
        return tuple((so, n) for so, _, n in self.segments)


@dataclass(frozen=True)
class Transfer:
    tid: str
    chunk: str
    src: int
    dst: int
    #: "out" lands into the destination block; "stage" parks the payload
    #: in the dst rank's staging buffer for a later forwarding hop.
    dest: str
    link: str
    #: payload source at ``src``: None = gather from the in-block
    #: (requires chunk.src_rank == src); a chunk name = forward that
    #: previously staged chunk's payload.
    via: Optional[str] = None


@dataclass(frozen=True)
class Op:
    kind: str
    arg: str  # chunk name for copy/unstage/reduce, tid for start/done

    def render(self) -> str:
        return f"{self.kind}({self.arg})"


@dataclass
class Schedule:
    name: str
    kind: str
    shape: Tuple[int, ...]
    dtype: str
    src_spec: Optional[int]
    dst_spec: Optional[int]
    src_world: int
    dst_world: int
    topology: Topology
    chunks: Dict[str, Chunk]
    transfers: Dict[str, Transfer]
    #: rank -> ordered op list; rank ids cover max(src_world, dst_world).
    programs: Dict[int, List[Op]]
    #: declared landing-buffer capacity (outstanding started-not-done
    #: transfers targeting any single rank); the model check proves the
    #: reachable maximum never exceeds it.
    max_inflight: int = 0

    @property
    def n_ranks(self) -> int:
        return max(self.src_world, self.dst_world)

    @property
    def itemsize(self) -> int:
        return int(np.dtype(self.dtype).itemsize)

    def wire_bytes(self) -> Dict[str, int]:
        out = {"ici": 0, "dcn": 0}
        for t in self.transfers.values():
            out[t.link] += self.chunks[t.chunk].nelems * self.itemsize
        return out

    def stats(self) -> Dict[str, object]:
        wb = self.wire_bytes()
        return {
            "kind": self.kind,
            "chunks": len(self.chunks),
            "transfers": len(self.transfers),
            "ops": sum(len(p) for p in self.programs.values()),
            "ici_bytes": wb["ici"],
            "dcn_bytes": wb["dcn"],
            "max_inflight": self.max_inflight,
        }

    # -- serialization: the "compiled artifact" face --------------------

    def to_json(self) -> dict:
        return {
            "schema": SCHEDULE_SCHEMA,
            "name": self.name,
            "kind": self.kind,
            "shape": list(self.shape),
            "dtype": self.dtype,
            "src_spec": self.src_spec,
            "dst_spec": self.dst_spec,
            "src_world": self.src_world,
            "dst_world": self.dst_world,
            "topology": [self.topology.slices, self.topology.per_slice],
            "max_inflight": self.max_inflight,
            "chunks": [
                {"name": c.name, "src": c.src_rank, "dst": c.dst_rank,
                 "segments": [list(s) for s in c.segments]}
                for c in self.chunks.values()],
            "transfers": [
                {"tid": t.tid, "chunk": t.chunk, "src": t.src,
                 "dst": t.dst, "dest": t.dest, "link": t.link,
                 "via": t.via}
                for t in self.transfers.values()],
            "programs": {
                str(r): [[op.kind, op.arg] for op in prog]
                for r, prog in sorted(self.programs.items())},
        }

    @classmethod
    def from_json(cls, doc: dict) -> "Schedule":
        if doc.get("schema") != SCHEDULE_SCHEMA:
            raise ValueError(
                f"not a {SCHEDULE_SCHEMA} document: "
                f"schema={doc.get('schema')!r}")
        chunks = {}
        for c in doc["chunks"]:
            chunks[c["name"]] = Chunk(
                c["name"], int(c["src"]), int(c["dst"]),
                tuple(tuple(int(x) for x in s) for s in c["segments"]))
        transfers = {}
        for t in doc["transfers"]:
            transfers[t["tid"]] = Transfer(
                t["tid"], t["chunk"], int(t["src"]), int(t["dst"]),
                t["dest"], t["link"], t.get("via"))
        programs = {}
        for r, prog in doc["programs"].items():
            ops = []
            for kind, arg in prog:
                kind = _OP_ALIASES.get(kind, kind)
                if kind not in OP_KINDS:
                    raise ValueError(f"unknown op kind {kind!r}")
                ops.append(Op(kind, arg))
            programs[int(r)] = ops
        topo = doc.get("topology")
        return cls(
            name=doc["name"], kind=doc.get("kind", "unknown"),
            shape=tuple(int(x) for x in doc["shape"]),
            dtype=doc["dtype"],
            src_spec=doc["src_spec"], dst_spec=doc["dst_spec"],
            src_world=int(doc["src_world"]),
            dst_world=int(doc["dst_world"]),
            topology=(Topology(int(topo[0]), int(topo[1])) if topo
                      else Topology.flat(max(int(doc["src_world"]),
                                             int(doc["dst_world"])))),
            chunks=chunks, transfers=transfers, programs=programs,
            max_inflight=int(doc.get("max_inflight", 0)))

    def fingerprint(self) -> str:
        blob = json.dumps(self.to_json(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


# --------------------------------------------------------------------------
# block geometry: the same np.array_split math as reshard_host, so the
# oracle and the runtime can never disagree about where a byte lives.
# --------------------------------------------------------------------------

def block_shape(shape: Sequence[int], spec: Optional[int], rank: int,
                world: int) -> Tuple[int, ...]:
    shape = tuple(int(x) for x in shape)
    if spec is None:
        return shape
    axis = int(spec)
    if not 0 <= axis < len(shape):
        raise ValueError(f"spec axis {axis} out of range for {shape}")
    lo, hi = _split_bounds(shape[axis], world, rank)
    out = list(shape)
    out[axis] = hi - lo
    return tuple(out)


def _split_bounds(length: int, world: int, rank: int) -> Tuple[int, int]:
    """[lo, hi) of ``rank``'s slice under np.array_split semantics."""
    if not 0 <= rank < world:
        raise ValueError(f"rank {rank} outside world {world}")
    base, extra = divmod(length, world)
    lo = rank * base + min(rank, extra)
    return lo, lo + base + (1 if rank < extra else 0)


def block_global_indices(shape: Sequence[int], spec: Optional[int],
                         rank: int, world: int) -> np.ndarray:
    """Flat C-order GLOBAL element indices of ``rank``'s local block,
    enumerated in the block's own C order (strictly increasing, since a
    slice preserves C-order monotonicity)."""
    shape = tuple(int(x) for x in shape)
    total = int(np.prod(shape)) if shape else 1
    if spec is None:
        return np.arange(total, dtype=np.int64)
    axis = int(spec)
    lo, hi = _split_bounds(shape[axis], world, rank)
    g = np.arange(total, dtype=np.int64).reshape(shape)
    sl = [slice(None)] * len(shape)
    sl[axis] = slice(lo, hi)
    return g[tuple(sl)].reshape(-1)


def _runs(src_pos: np.ndarray, dst_pos: np.ndarray
          ) -> Tuple[Tuple[int, int, int], ...]:
    """Compress aligned position arrays into (src_off, dst_off, n)
    maximal contiguous runs."""
    if len(src_pos) == 0:
        return ()
    brk = np.where((np.diff(src_pos) != 1) | (np.diff(dst_pos) != 1))[0]
    starts = np.concatenate([[0], brk + 1])
    ends = np.concatenate([brk + 1, [len(src_pos)]])
    return tuple((int(src_pos[a]), int(dst_pos[a]), int(e - a))
                 for a, e in zip(starts, ends))


def expected_flow(shape: Sequence[int], src_spec: Optional[int],
                  dst_spec: Optional[int], src_world: int,
                  dst_world: int
                  ) -> Dict[Tuple[int, int], Tuple[Tuple[int, int, int],
                                                   ...]]:
    """The statics oracle: (src_rank, dst_rank) -> segments such that
    every destination element is covered exactly once.

    For a sharded source the owner of each element is unique, so the
    flow is the exact block intersection.  For a replicated source every
    replica holds everything; we pin the single source per destination
    the way ``reshard_host`` does: the destination rank itself when it
    was part of the old world (a pure local copy — the zero-wire R→S
    lowering of ``reshard``), else old rank ``d % src_world``.
    """
    flows: Dict[Tuple[int, int], Tuple[Tuple[int, int, int], ...]] = {}
    gdst = {d: block_global_indices(shape, dst_spec, d, dst_world)
            for d in range(dst_world)}
    if src_spec is None:
        for d in range(dst_world):
            s = d if d < src_world else d % src_world
            # a replicated src block is the full array, so the dst
            # element's global index IS its src offset.
            segs = _runs(gdst[d],
                         np.arange(len(gdst[d]), dtype=np.int64))
            if segs:
                flows[(s, d)] = segs
        return flows
    for s in range(src_world):
        gsrc = block_global_indices(shape, src_spec, s, src_world)
        for d in range(dst_world):
            common, src_pos, dst_pos = np.intersect1d(
                gsrc, gdst[d], assume_unique=True, return_indices=True)
            if len(common) == 0:
                continue
            flows[(s, d)] = _runs(src_pos, dst_pos)
    return flows


def _split_segments(segments: Sequence[Tuple[int, int, int]],
                    n_chunks: int
                    ) -> List[Tuple[Tuple[int, int, int], ...]]:
    """Split a segment list into ``n_chunks`` pieces of near-equal
    element count (np.array_split sizing), cutting inside segments when
    needed.  Deterministic, so identical source projections split
    identically — the alignment hierarchical staging relies on."""
    total = sum(n for _, _, n in segments)
    n_chunks = max(1, min(int(n_chunks), total)) if total else 1
    if n_chunks == 1:
        return [tuple(segments)]
    bounds = [_split_bounds(total, n_chunks, i)[0]
              for i in range(n_chunks)] + [total]
    pieces: List[List[Tuple[int, int, int]]] = [[] for _ in
                                                range(n_chunks)]
    off = 0
    for so, do, n in segments:
        seg_lo, seg_hi = off, off + n
        for i in range(n_chunks):
            lo = max(seg_lo, bounds[i])
            hi = min(seg_hi, bounds[i + 1])
            if lo < hi:
                pieces[i].append((so + (lo - seg_lo),
                                  do + (lo - seg_lo), hi - lo))
        off += n
    return [tuple(p) for p in pieces if p]


# --------------------------------------------------------------------------
# generators
# --------------------------------------------------------------------------

def _declared_inflight(transfers: Dict[str, Transfer]) -> int:
    per_dst: Dict[int, int] = {}
    for t in transfers.values():
        per_dst[t.dst] = per_dst.get(t.dst, 0) + 1
    return max(per_dst.values(), default=0)


def _base(shape, dtype, src_spec, dst_spec, src_world, dst_world,
          topology, kind) -> Schedule:
    world = max(int(src_world), int(dst_world))
    topo = topology or Topology.flat(world)
    if topo.size < world:
        raise ValueError(f"topology {topo} smaller than world {world}")
    name = (f"{kind}:{_spec_name(src_spec)}->{_spec_name(dst_spec)}"
            f"@{src_world}->{dst_world}"
            f"/{'x'.join(map(str, shape))}:{dtype}")
    return Schedule(
        name=name, kind=kind, shape=tuple(int(x) for x in shape),
        dtype=str(dtype), src_spec=src_spec, dst_spec=dst_spec,
        src_world=int(src_world), dst_world=int(dst_world),
        topology=topo, chunks={}, transfers={},
        programs={r: [] for r in range(world)})


def _spec_name(spec) -> str:
    return "R" if spec is None else f"S{int(spec)}"


def _finish(sched: Schedule) -> Schedule:
    sched.max_inflight = max(1, _declared_inflight(sched.transfers))
    return sched


def lower_single(shape, dtype, src_spec, dst_spec, src_world, dst_world,
                 topology: Optional[Topology] = None) -> Schedule:
    """The current monolithic lowering as an explicit program: local
    copies, then every rank posts all its sends, then awaits all its
    receives — exactly the all-posted buffer envelope of the one-shot
    collective."""
    return lower_chunked(shape, dtype, src_spec, dst_spec, src_world,
                         dst_world, topology, n_chunks=1, kind="single")


def lower_chunked(shape, dtype, src_spec, dst_spec, src_world,
                  dst_world, topology: Optional[Topology] = None,
                  n_chunks: int = 4, kind: str = "chunked") -> Schedule:
    """Flat lowering with each pairwise flow split into ``n_chunks``
    pieces (alpha cost up, enables overlap downstream)."""
    sched = _base(shape, dtype, src_spec, dst_spec, src_world,
                  dst_world, topology, kind)
    flows = expected_flow(shape, src_spec, dst_spec, src_world,
                          dst_world)
    copies: Dict[int, List[Op]] = {}
    sends: Dict[int, List[Op]] = {}
    recvs: Dict[int, List[Op]] = {}
    for (s, d), segs in sorted(flows.items()):
        for j, piece in enumerate(_split_segments(segs, n_chunks)):
            cname = f"c{s}_{d}_{j}"
            sched.chunks[cname] = Chunk(cname, s, d, piece)
            if s == d:
                copies.setdefault(s, []).append(Op("copy", cname))
                continue
            tid = f"t{s}_{d}_{j}"
            sched.transfers[tid] = Transfer(
                tid, cname, s, d, "out", sched.topology.link(s, d))
            sends.setdefault(s, []).append(Op("start", tid))
            recvs.setdefault(d, []).append(Op("done", tid))
    for r in sched.programs:
        sched.programs[r] = (copies.get(r, []) + sends.get(r, [])
                             + recvs.get(r, []))
    return _finish(sched)


def lower_pipelined(shape, dtype, src_spec, dst_spec, src_world,
                    dst_world, topology: Optional[Topology] = None,
                    n_chunks: int = 4, depth: int = 2) -> Schedule:
    """Chunked lowering with each rank's program interleaving its sends
    and receives: at most ``depth`` of its own starts run ahead of its
    done stream, so landings drain (and downstream consumers unblock)
    while later pieces are still on the wire."""
    sched = lower_chunked(shape, dtype, src_spec, dst_spec, src_world,
                          dst_world, topology, n_chunks,
                          kind="pipelined")
    depth = max(1, int(depth))
    for r, prog in sched.programs.items():
        copies = [op for op in prog if op.kind == "copy"]
        starts = [op for op in prog if op.kind == "start"]
        dones = [op for op in prog if op.kind == "done"]
        merged = copies + starts[:depth]
        si, di = depth, 0
        while si < len(starts) or di < len(dones):
            if di < len(dones):
                merged.append(dones[di])
                di += 1
            if si < len(starts):
                merged.append(starts[si])
                si += 1
        sched.programs[r] = merged
    return _finish(sched)


def lower_hierarchical(shape, dtype, src_spec, dst_spec, src_world,
                       dst_world, topology: Topology,
                       n_chunks: int = 1) -> Schedule:
    """ICI/DCN staged lowering.  Cross-slice flows whose destinations in
    one slice want the SAME source bytes (replicated destinations —
    elastic expansion, rolling-upgrade gather) cross DCN once to a
    gateway rank and fan out over ICI; everything else goes direct over
    its natural link.  With ``n_chunks > 1`` the gateway forwards piece
    ``j`` over ICI while piece ``j+1`` is still on the DCN wire — the
    pipelined hierarchical candidate."""
    sched = _base(shape, dtype, src_spec, dst_spec, src_world,
                  dst_world, topology, "hierarchical")
    topo = sched.topology
    flows = expected_flow(shape, src_spec, dst_spec, src_world,
                          dst_world)
    copies: Dict[int, List[Op]] = {}
    free_sends: Dict[int, List[Op]] = {}        # via=None starts
    inbound: Dict[int, List[Transfer]] = {}     # ordered dones per rank
    followups: Dict[Tuple[int, str], List[Op]] = {}  # after a landing

    def add_chunk(cname, s, d, piece):
        sched.chunks[cname] = Chunk(cname, s, d, piece)

    def direct(s, d, j, piece):
        cname = f"c{s}_{d}_{j}"
        add_chunk(cname, s, d, piece)
        tid = f"t{s}_{d}_{j}"
        t = Transfer(tid, cname, s, d, "out", topo.link(s, d))
        sched.transfers[tid] = t
        free_sends.setdefault(s, []).append(Op("start", tid))
        inbound.setdefault(d, []).append(t)

    # group cross-slice flows by (src, dst slice) to find shareable fans
    groups: Dict[Tuple[int, int], List[Tuple[int, tuple]]] = {}
    for (s, d), segs in sorted(flows.items()):
        if s == d:
            for j, piece in enumerate(_split_segments(segs, n_chunks)):
                cname = f"c{s}_{d}_{j}"
                add_chunk(cname, s, d, piece)
                copies.setdefault(s, []).append(Op("copy", cname))
        elif topo.link(s, d) == "ici":
            for j, piece in enumerate(_split_segments(segs, n_chunks)):
                direct(s, d, j, piece)
        else:
            groups.setdefault((s, topo.slice_of(d)), []).append(
                (d, segs))

    for (s, dslice), members in sorted(groups.items()):
        src_sides = {tuple((so, n) for so, _, n in segs)
                     for _, segs in members}
        if len(members) == 1 or len(src_sides) != 1:
            # nothing shareable: direct DCN per destination
            for d, segs in members:
                for j, piece in enumerate(
                        _split_segments(segs, n_chunks)):
                    direct(s, d, j, piece)
            continue
        # gateway: the member aligned with the source's in-slice
        # position when present (spreads DCN ingress), else the lowest.
        dsts = [d for d, _ in members]
        aligned = [d for d in dsts if topo.pos_of(d) == topo.pos_of(s)]
        g = aligned[0] if aligned else min(dsts)
        by_dst = dict(members)
        g_pieces = _split_segments(by_dst[g], n_chunks)
        others = sorted(d for d in dsts if d != g)
        for j, g_piece in enumerate(g_pieces):
            carrier = f"c{s}_{g}_{j}"
            add_chunk(carrier, s, g, g_piece)
            tid = f"t{s}_{g}_{j}"
            t = Transfer(tid, carrier, s, g, "stage", "dcn")
            sched.transfers[tid] = t
            free_sends.setdefault(s, []).append(Op("start", tid))
            inbound.setdefault(g, []).append(t)
            fol = followups.setdefault((g, carrier), [])
            fol.append(Op("unstage", carrier))
            for d in others:
                cname = f"c{s}_{d}_{j}"
                piece = _split_segments(by_dst[d], n_chunks)[j]
                add_chunk(cname, s, d, piece)
                ftid = f"t{s}_{d}_{j}"
                ft = Transfer(ftid, cname, g, d, "out", "ici",
                              via=carrier)
                sched.transfers[ftid] = ft
                fol.append(Op("start", ftid))
                inbound.setdefault(d, []).append(ft)

    for r in sched.programs:
        prog = copies.get(r, []) + free_sends.get(r, [])
        for t in inbound.get(r, []):
            prog.append(Op("done", t.tid))
            if t.dest == "stage":
                prog.extend(followups.get((r, t.chunk), []))
        sched.programs[r] = prog
    return _finish(sched)


GENERATORS = {
    "single": lower_single,
    "chunked": lower_chunked,
    "pipelined": lower_pipelined,
    "hierarchical": lower_hierarchical,
}


def candidate_schedules(shape, dtype, src_spec, dst_spec, src_world,
                        dst_world, topology: Optional[Topology] = None,
                        n_chunks: int = 4, depth: int = 2
                        ) -> List[Schedule]:
    """The search space: the monolithic baseline plus the chunked,
    pipelined, and (when the topology has a DCN tier) hierarchical
    candidates, in deterministic order."""
    world = max(int(src_world), int(dst_world))
    topo = topology or Topology.flat(world)
    out = [
        lower_single(shape, dtype, src_spec, dst_spec, src_world,
                     dst_world, topo),
        lower_chunked(shape, dtype, src_spec, dst_spec, src_world,
                      dst_world, topo, n_chunks=n_chunks),
        lower_pipelined(shape, dtype, src_spec, dst_spec, src_world,
                        dst_world, topo, n_chunks=n_chunks,
                        depth=depth),
    ]
    if topo.slices > 1:
        out.append(lower_hierarchical(
            shape, dtype, src_spec, dst_spec, src_world, dst_world,
            topo, n_chunks=n_chunks))
    return out


# --------------------------------------------------------------------------
# r04 cost model + deterministic event pricing
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class CostModel:
    """Wire constants from BENCH_r04 ``project_dp_scaling`` (bench.py):
    v5e ICI 1.8e11 B/s with 1 µs/hop alpha, DCN 2.5e10 B/s per host.
    The DCN alpha and local copy bandwidth are this model's own
    assumptions (cross-host message setup is dominated by the NIC/host
    stack; copies run at HBM-ish speed)."""
    ici_bw: float = 1.8e11
    dcn_bw: float = 2.5e10
    alpha_ici_s: float = 1.0e-6
    alpha_dcn_s: float = 25.0e-6
    copy_bw: float = 4.0e11

    def bw(self, link: str) -> float:
        return self.ici_bw if link == "ici" else self.dcn_bw

    def alpha(self, link: str) -> float:
        return self.alpha_ici_s if link == "ici" else self.alpha_dcn_s


#: Versioned schema of the persisted calibration artifact produced by
#: :mod:`.calibrate` — per-link (alpha, bw) fitted from measured
#: ``schedule_exec`` records.  Lives here (not in calibrate.py) so
#: :func:`price_schedule` can validate it without a circular import.
CALIBRATION_SCHEMA = "chainermn_tpu.calibration.v1"


def calibrated_cost_model(calibration: Optional[dict],
                          base: Optional[CostModel] = None) -> CostModel:
    """A :class:`CostModel` with the fitted per-link constants from a
    calibration artifact substituted over ``base`` (stock r04 constants
    for any link the fit could not resolve).  Refuses an artifact whose
    schema version is not ours — a stale calibration silently priced as
    current is exactly the rot this plane exists to prevent."""
    cm = base or CostModel()
    if not calibration:
        return cm
    schema = calibration.get("schema")
    if schema != CALIBRATION_SCHEMA:
        raise ValueError(
            f"stale/foreign calibration artifact: schema={schema!r}, "
            f"want {CALIBRATION_SCHEMA} (re-fit with "
            f"chainermn_tpu.analysis.calibrate)")
    links = calibration.get("links") or {}
    kw: Dict[str, float] = {}
    ici = links.get("ici") or {}
    if ici.get("bw"):
        kw["ici_bw"] = float(ici["bw"])
        kw["alpha_ici_s"] = float(ici.get("alpha_s", cm.alpha_ici_s))
    dcn = links.get("dcn") or {}
    if dcn.get("bw"):
        kw["dcn_bw"] = float(dcn["bw"])
        kw["alpha_dcn_s"] = float(dcn.get("alpha_s", cm.alpha_dcn_s))
    copy = links.get("copy") or {}
    if copy.get("bw"):
        kw["copy_bw"] = float(copy["bw"])
    if not kw:
        return cm
    from dataclasses import replace
    return replace(cm, **kw)


def price_schedule(sched: Schedule,
                   cost_model: Optional[CostModel] = None,
                   calibration: Optional[dict] = None
                   ) -> Dict[str, object]:
    """Deterministic event simulation of one schedule.

    Resource model: each rank owns one egress and one ingress port per
    link class; transfers on the same port serialize (NIC/ICI-port
    contention — this is what makes the all-posted monolithic schedule
    pay 2·(P-1)/P·bytes/bw like the ring model in
    ``project_dp_scaling``), while different ports and link classes
    overlap freely.  ``start`` is asynchronous (the issuing rank does
    not wait); ``done`` blocks until the wire completes; landings and
    local copies cost bytes/copy_bw on the executing rank.

    ``calibration`` is a loaded ``chainermn_tpu.calibration.v1``
    artifact (see :mod:`.calibrate`): its fitted per-link constants are
    substituted over ``cost_model`` so candidates rank by MEASURED
    costs; a stale-schema artifact raises.
    """
    cm = calibrated_cost_model(calibration, cost_model) \
        if calibration is not None else (cost_model or CostModel())
    item = sched.itemsize
    rank_time = {r: 0.0 for r in sched.programs}
    egress: Dict[Tuple[int, str], float] = {}
    ingress: Dict[Tuple[int, str], float] = {}
    completion: Dict[str, float] = {}
    land_time: Dict[Tuple[int, str], float] = {}  # (rank, chunk)->t
    pcs = {r: 0 for r in sched.programs}
    bytes_by = {"ici": 0, "dcn": 0, "copy": 0}
    msgs_by = {"ici": 0, "dcn": 0}

    def ready(r: int, op: Op) -> bool:
        if op.kind == "done":
            return op.arg in completion
        if op.kind == "unstage":
            return (r, op.arg) in land_time
        if op.kind == "start":
            t = sched.transfers[op.arg]
            return t.via is None or (r, t.via) in land_time
        return True

    progressed = True
    while progressed:
        progressed = False
        for r in sorted(sched.programs):
            prog = sched.programs[r]
            while pcs[r] < len(prog) and ready(r, prog[pcs[r]]):
                op = prog[pcs[r]]
                pcs[r] += 1
                progressed = True
                if op.kind in ("copy", "unstage"):
                    nbytes = sched.chunks[op.arg].nelems * item
                    base = rank_time[r]
                    if op.kind == "unstage":
                        base = max(base, land_time[(r, op.arg)])
                    rank_time[r] = base + nbytes / cm.copy_bw
                    bytes_by["copy"] += nbytes
                elif op.kind == "start":
                    t = sched.transfers[op.arg]
                    nbytes = sched.chunks[t.chunk].nelems * item
                    issue = rank_time[r]
                    if t.via is not None:
                        issue = max(issue, land_time[(r, t.via)])
                    beg = max(issue,
                              egress.get((t.src, t.link), 0.0),
                              ingress.get((t.dst, t.link), 0.0))
                    end = beg + cm.alpha(t.link) + nbytes / cm.bw(t.link)
                    egress[(t.src, t.link)] = end
                    ingress[(t.dst, t.link)] = end
                    completion[t.tid] = end
                    bytes_by[t.link] += nbytes
                    msgs_by[t.link] += 1
                elif op.kind == "done":
                    t = sched.transfers[op.arg]
                    nbytes = sched.chunks[t.chunk].nelems * item
                    rank_time[r] = (max(rank_time[r],
                                        completion[op.arg])
                                    + nbytes / cm.copy_bw)
                    if t.dest == "stage":
                        land_time[(r, t.chunk)] = rank_time[r]
                else:  # pragma: no cover - reduce reserved
                    raise NotImplementedError(
                        f"cost model: op kind {op.kind!r} reserved")
    if any(pcs[r] < len(sched.programs[r]) for r in pcs):
        stuck = {r: sched.programs[r][pcs[r]].render()
                 for r in pcs if pcs[r] < len(sched.programs[r])}
        raise RuntimeError(
            f"price_schedule: schedule {sched.name} does not make "
            f"progress (verify it first); stuck at {stuck}")
    wall = max([0.0] + list(rank_time.values())
               + list(completion.values()))
    return {
        "schedule": sched.name,
        "kind": sched.kind,
        "wall_us": wall * 1e6,
        "cost_ms": wall * 1e3,
        "ici_bytes": bytes_by["ici"],
        "dcn_bytes": bytes_by["dcn"],
        "copy_bytes": bytes_by["copy"],
        "ici_messages": msgs_by["ici"],
        "dcn_messages": msgs_by["dcn"],
    }
