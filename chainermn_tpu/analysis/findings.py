"""Finding model, inline suppressions, and the checked-in baseline.

A finding is identified across commits by a **fingerprint** over (rule,
path, enclosing scope, normalized source line) — deliberately NOT the line
number, so unrelated edits above a finding don't churn the baseline.

Suppression surfaces, most local first:

* ``# spmd-lint: disable=rule1,rule2`` on the offending line;
* ``# spmd-lint: disable-next-line=rule`` on the line above;
* ``# spmd-lint: disable-file=rule`` anywhere in the first 10 lines of a
  file (for e.g. profile scripts whose constant seeds are the point);
* a baseline entry (``.spmd-lint-baseline.json``) carrying a ``comment``
  saying WHY the finding is accepted — regenerate intentionally with
  ``--fix-baseline``.

Pure stdlib: this module must import cleanly without jax.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

#: Ordered weakest → strongest; exit-code policy treats every severity as
#: a finding, severity is for human triage.
SEVERITIES = ("info", "warning", "error")

BASELINE_FILENAME = ".spmd-lint-baseline.json"
_BASELINE_VERSION = 1

_SUPPRESS_RE = re.compile(
    r"#\s*spmd-lint:\s*(disable|disable-next-line|disable-file)\s*="
    r"\s*([A-Za-z0-9_,\- ]+)")


@dataclass
class Finding:
    rule: str
    severity: str
    path: str          # as given to the engine (normalized to repo-relative
    #                    by the CLI before printing/baselining)
    line: int          # 1-based
    message: str
    context: str = ""  # enclosing qualname, e.g. "ServingEngine.step"
    snippet: str = ""  # stripped source of the offending line

    def fingerprint(self) -> str:
        norm = re.sub(r"\s+", " ", self.snippet).strip()
        h = hashlib.sha1(
            "\x1f".join([self.rule, self.path.replace(os.sep, "/"),
                         self.context, norm]).encode()).hexdigest()
        return h[:16]

    def to_dict(self) -> Dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path.replace(os.sep, "/"),
            "line": self.line,
            "context": self.context,
            "message": self.message,
            "snippet": self.snippet,
            "fingerprint": self.fingerprint(),
        }

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        ctx = f" [{self.context}]" if self.context else ""
        return (f"{where}: {self.severity}: {self.rule}{ctx}: "
                f"{self.message}\n    {self.snippet}")


class Suppressions:
    """Per-file inline suppression table, parsed once from source lines."""

    def __init__(self, source: str):
        self._line: Dict[int, set] = {}
        self._file: set = set()
        for i, text in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            kind, rules = m.group(1), {
                r.strip() for r in m.group(2).split(",") if r.strip()}
            if kind == "disable":
                self._line.setdefault(i, set()).update(rules)
            elif kind == "disable-next-line":
                self._line.setdefault(i + 1, set()).update(rules)
            elif kind == "disable-file" and i <= 10:
                self._file.update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self._file or "all" in self._file:
            return True
        rules = self._line.get(line, ())
        return rule in rules or "all" in rules


@dataclass
class Baseline:
    """Accepted findings, keyed by fingerprint; survives line shifts."""

    entries: Dict[str, Dict] = field(default_factory=dict)
    path: Optional[str] = None

    def accepts(self, finding: Finding) -> bool:
        return finding.fingerprint() in self.entries

    def filter(self, findings: Iterable[Finding]
               ) -> Tuple[List[Finding], List[Finding]]:
        """Split into (new, accepted-by-baseline).

        COUNT-AWARE: textually identical violations in the same scope
        share a fingerprint, so each entry accepts at most its recorded
        ``count`` occurrences (default 1) — a new duplicate of a
        baselined line is a NEW finding, not a free pass."""
        new, accepted = [], []
        seen: Dict[str, int] = {}
        for f in findings:
            fp = f.fingerprint()
            entry = self.entries.get(fp)
            allowed = int(entry.get("count", 1)) if entry else 0
            seen[fp] = seen.get(fp, 0) + 1
            (accepted if seen[fp] <= allowed else new).append(f)
        return new, accepted

    @staticmethod
    def from_findings(findings: Iterable[Finding],
                      comments: Optional[Dict[str, str]] = None,
                      path: Optional[str] = None) -> "Baseline":
        entries: Dict[str, Dict] = {}
        for f in findings:
            d = f.to_dict()
            fp = d.pop("fingerprint")
            d.pop("line")  # line numbers churn; fingerprint is the identity
            if fp in entries:
                entries[fp]["count"] += 1
                continue
            d["comment"] = (comments or {}).get(fp, "")
            d["count"] = 1
            entries[fp] = d
        return Baseline(entries=entries, path=path)

    def merge_comments_from(self, other: "Baseline") -> None:
        """Keep human-written comments across --fix-baseline regens."""
        for fp, entry in self.entries.items():
            old = other.entries.get(fp)
            if old and old.get("comment") and not entry.get("comment"):
                entry["comment"] = old["comment"]

    def save(self, path: Optional[str] = None) -> str:
        path = path or self.path
        if not path:
            raise ValueError("baseline has no path")
        doc = {"version": _BASELINE_VERSION,
               "tool": "chainermn_tpu.analysis",
               "findings": [dict(fingerprint=fp, **e)
                            for fp, e in sorted(self.entries.items(),
                                                key=lambda kv: (
                                                    kv[1]["path"],
                                                    kv[1]["rule"],
                                                    kv[0]))]}
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=2, sort_keys=False)
            fh.write("\n")
        os.replace(tmp, path)
        self.path = path
        return path


def load_baseline(path: str) -> Baseline:
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("version") != _BASELINE_VERSION:
        raise ValueError(
            f"unsupported baseline version {doc.get('version')!r} in {path}")
    entries = {}
    for e in doc.get("findings", []):
        e = dict(e)
        fp = e.pop("fingerprint")
        entries[fp] = e
    return Baseline(entries=entries, path=path)


def find_baseline(start: str,
                  filename: str = BASELINE_FILENAME) -> Optional[str]:
    """Walk up from ``start`` looking for a checked-in baseline file —
    linter-config discovery, so the CLI works from any cwd.  One walk
    serves both baselines (``filename``: the spmd-lint default here, the
    shard-flow one via ``shardflow.find_shardflow_baseline``)."""
    d = os.path.abspath(start)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    while True:
        cand = os.path.join(d, filename)
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent
