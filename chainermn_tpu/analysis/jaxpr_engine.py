"""jaxpr engine — trace registered entry points, check what XLA will see.

The AST engine reads source; this engine reads the *program*.  Each
registered entry point (``entrypoints.py``) is traced with tiny shapes on
the CPU backend (``jax.make_jaxpr`` — no device execution for the axis
check) and yields:

* **unbound-axis** (error): a collective inside the traced body names a
  mesh axis absent from the entry point's declared binding.  Two ways to
  trip it: trace-time ``NameError`` ("unbound axis name"), or a collective
  equation whose ``axis_name``/``axes`` parameter escapes the declared
  set (belt and braces — sub-jaxprs are walked recursively through pjit /
  shard_map / scan / cond).
* **recompile-hazard** (warning): the entry point's jitted form compiles
  more than once across its registered call variants (probed with the
  jit cache size), or a declared static argument is unhashable.  Entry
  points that *intend* per-variant programs — the serving engine's
  per-prompt-length prefill family — register ``allow_recompile=True``
  and are reported as allowlisted info instead.

jax is imported lazily inside functions: importing this module costs
nothing and the AST half of the analyzer stays usable on jax-free boxes.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from .findings import Finding

JAXPR_RULES: Dict[str, Tuple[str, str]] = {
    "unbound-axis": (
        "error", "collective names an axis absent from the mesh binding"),
    "recompile-hazard": (
        "warning", "entry point recompiles across registered call variants"),
    "entrypoint-error": (
        "error", "registered entry point failed to build/trace/execute"),
}

#: jax.lax collective primitive names as they appear in jaxprs.
_COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "all_gather", "all_to_all",
    "reduce_scatter", "pgather", "psum_scatter",
})


@dataclass
class EntryPoint:
    """One traceable program the analyzer owns end to end.

    ``build()`` runs lazily (it may import jax and chainermn_tpu) and
    returns a dict with:

    * ``trace``: ``(fn, args)`` — traced via ``jax.make_jaxpr``;
    * ``bound_axes``: set of mesh axis names the binding declares;
    * ``variants`` (optional): ``(jit_fn, [args, ...])`` — every args
      tuple is CALLED on ``jit_fn`` and the jit cache size compared to 1;
    * ``static_values`` (optional): values declared static somewhere in
      the program — probed for hashability.

    Shard-flow keys (read by ``analysis/shardflow.py``; all optional):

    * ``data_axis``: the mesh axis replication is judged against;
    * ``arg_labels``: names for the positional trace args (replication
      findings are grouped per label);
    * ``expected_replication``: ``{label: reason}`` — replication that is
      by design (or a named debt, e.g. optimizer state until ZeRO-1);
      must be DELETED when the sharding lands (stale-annotation check);
    * ``noted``: ``{ledger_row_key: bytes}`` — comm.note() bookings this
      program performs (traffic no wrapper sees), held to account;
    * ``ad_transpose_bytes``: ``{primitive@axis: bytes}`` — equations
      legacy-jax autodiff adds by transposing a wrapped collective,
      which the ledger cannot book (see shardflow module docs).
    """

    name: str
    build: Callable[[], Dict[str, Any]]
    allow_recompile: bool = False
    description: str = ""
    #: False skips the shard-flow pass (for tee variants whose compiled
    #: program an earlier entry already analyzes byte-for-byte).
    shardflow: bool = True


@dataclass
class TraceReport:
    """What the engine learned about one entry point (returned alongside
    findings so callers can print the collective surface)."""

    name: str
    collectives: List[Tuple[str, Tuple[str, ...]]] = field(
        default_factory=list)  # (primitive, axis names) in trace order
    n_compiles: Optional[int] = None
    error: Optional[str] = None


def _axis_names(params: Dict[str, Any]) -> Tuple[str, ...]:
    for key in ("axes", "axis_name"):
        if key in params:
            v = params[key]
            if isinstance(v, str):
                return (v,)
            if isinstance(v, (tuple, list)):
                return tuple(x for x in v if isinstance(x, str))
    return ()


def _iter_eqns(jaxpr) -> Sequence[Any]:
    """All equations, recursing into every sub-jaxpr found in params."""
    out = []
    seen: Set[int] = set()

    def rec(jx):
        if id(jx) in seen:
            return
        seen.add(id(jx))
        inner = getattr(jx, "jaxpr", jx)  # ClosedJaxpr -> Jaxpr
        for eqn in getattr(inner, "eqns", ()):
            out.append(eqn)
            for v in eqn.params.values():
                for sub in _maybe_jaxprs(v):
                    rec(sub)

    rec(jaxpr)
    return out


def _maybe_jaxprs(v) -> List[Any]:
    subs = []
    if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
        subs.append(v)
    elif isinstance(v, (tuple, list)):
        for item in v:
            subs.extend(_maybe_jaxprs(item))
    return subs


def collective_sequence(jaxpr) -> List[Tuple[str, Tuple[str, ...]]]:
    """(primitive name, axis names) for every collective eqn, in order."""
    seq = []
    for eqn in _iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name in _COLLECTIVE_PRIMS:
            seq.append((name, _axis_names(eqn.params)))
    return seq


def check_entrypoint(ep: EntryPoint) -> Tuple[List[Finding], TraceReport]:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    findings: List[Finding] = []
    report = TraceReport(name=ep.name)
    loc = f"entrypoint:{ep.name}"

    def engine_error(stage: str, e: BaseException):
        # a broken entry point is a REPORTED finding, never a crash of
        # the whole lint run (the 0/1/2 exit contract must hold)
        report.error = f"{stage} failed: {type(e).__name__}: {e}"
        findings.append(Finding(
            rule="entrypoint-error", severity="error", path=loc, line=0,
            message=report.error, context=ep.name,
            snippet=ep.description))

    try:
        spec = ep.build()
    except Exception as e:  # noqa: BLE001
        engine_error("build", e)
        return findings, report

    fn, args = spec["trace"]
    bound: Set[str] = set(spec.get("bound_axes", ()))

    # ---- axis binding: trace, then walk the collective eqns ----
    try:
        jaxpr = jax.make_jaxpr(fn)(*args)
    except NameError as e:
        # jax raises NameError("unbound axis name: ...") at trace time
        findings.append(Finding(
            rule="unbound-axis", severity="error", path=loc, line=0,
            message=(f"tracing failed: {e} — the body names a mesh axis "
                     f"the enclosing binding ({sorted(bound)}) does not "
                     "provide; the compiled gang would never agree on "
                     "this collective"),
            context=ep.name, snippet=ep.description))
        report.error = str(e)
        return findings, report
    except Exception as e:  # noqa: BLE001
        engine_error("trace", e)
        return findings, report

    report.collectives = collective_sequence(jaxpr)
    for prim, axes in report.collectives:
        stray = [a for a in axes if a not in bound]
        if stray:
            findings.append(Finding(
                rule="unbound-axis", severity="error", path=loc, line=0,
                message=(f"collective `{prim}` runs over axis "
                         f"{stray} but the declared mesh binding is "
                         f"{sorted(bound)}"),
                context=ep.name, snippet=ep.description))

    # ---- recompilation: count actual compiles across variants ----
    variants = spec.get("variants")
    if variants is not None:
        jit_fn, arg_sets = variants
        try:
            for a in arg_sets:
                r = jit_fn(*a)
                jax.tree_util.tree_map(
                    lambda x: getattr(x, "block_until_ready", lambda: x)(),
                    r)
            n = jit_fn._cache_size()
        except Exception as e:  # noqa: BLE001
            engine_error("variant execution", e)
            return findings, report
        report.n_compiles = n
        if n > 1 and not ep.allow_recompile:
            findings.append(Finding(
                rule="recompile-hazard", severity="warning", path=loc,
                line=0,
                message=(f"{n} compiled programs for {len(arg_sets)} call "
                         "variants that should share one — per-call-"
                         "varying shapes or static args; hoist the varying "
                         "piece into traced inputs, or register "
                         "allow_recompile=True with a reason if the "
                         "program family is intentional (per-prompt-"
                         "length prefill)"),
                context=ep.name, snippet=ep.description))

    # ---- static-arg hashability ----
    for v in spec.get("static_values", ()):
        try:
            hash(v)
        except TypeError:
            findings.append(Finding(
                rule="recompile-hazard", severity="warning", path=loc,
                line=0,
                message=(f"declared static value of type "
                         f"{type(v).__name__} is unhashable — jit will "
                         "raise (or, via workarounds like str(), silently "
                         "recompile per call); use a hashable frozen "
                         "config"),
                context=ep.name, snippet=ep.description))

    return findings, report


def check_entrypoints(eps: Optional[Sequence[EntryPoint]] = None
                      ) -> Tuple[List[Finding], List[TraceReport]]:
    if eps is None:
        from .entrypoints import ENTRYPOINTS
        eps = ENTRYPOINTS
    findings: List[Finding] = []
    reports: List[TraceReport] = []
    for ep in eps:
        f, r = check_entrypoint(ep)
        findings.extend(f)
        reports.append(r)
    return findings, reports
