"""AST rule engine — pure stdlib, no JAX import, runs anywhere.

Every rule here encodes a bug class this repo has actually shipped or that
the reference's MPI heritage makes structural (see docs/ANALYSIS.md for a
real-bug example per rule):

================  ========  ====================================================
rule              severity  fires on
================  ========  ====================================================
collective-       error     a registry collective called under rank-dependent
deadlock                    control flow (branch, loop bound, or after a
                            rank-guarded early return) — some ranks enter the
                            collective, others don't: the gang deadlocks
prng-constant-    warning   ``jax.random.PRNGKey(<literal>)`` / ``key(<lit>)``
key                         — process-constant randomness (the PR 3 rng trap)
prng-key-reuse    warning   the same key consumed by two sampling calls with no
                            ``split``/``fold_in`` between — identical draws
host-alias-race   warning   in-place mutation of a buffer that also flows
                            through ``asarray`` — zero-copy device aliasing +
                            async dispatch races the mutation (PR 3 pos bug)
traced-control-   error     Python ``if``/``while`` on a traced parameter inside
flow                        a jitted function — TracerBoolConversionError at
                            best, silent trace-time specialization at worst
inplace-jit-      warning   in-place mutation of a name that is also passed to
mutation                    a jitted callable in the same scope
mismatched-       error     shard_map whose body reduces over a literal axis
shard-specs                 the same-scope mesh doesn't bind, or whose
                            out_specs shard an axis the returned collective
                            just reduced over
donated-buffer-   warning   a name passed at a ``donate_argnums`` position of
reuse                       a jitted call and READ afterwards — the buffer may
                            alias the output (the serving cache-pool hazard)
================  ========  ====================================================

The linear-flow rules (key reuse, deadlock-after-return) process loop
bodies TWICE — a cheap fixed-point that makes "reused every iteration"
emerge without real dataflow analysis; findings are deduped by line.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding, Suppressions
from .registry import CollectiveRegistry, default_registry

#: rule id -> (severity, one-line summary) — the catalog.
AST_RULES: Dict[str, Tuple[str, str]] = {
    "collective-deadlock": (
        "error", "collective under rank-dependent control flow"),
    "prng-constant-key": (
        "warning", "PRNGKey built from a literal constant"),
    "prng-key-reuse": (
        "warning", "PRNG key consumed twice without split/fold_in"),
    "host-alias-race": (
        "warning", "in-place mutation of an asarray-aliased buffer"),
    "traced-control-flow": (
        "error", "Python branch on a traced value inside jit"),
    "inplace-jit-mutation": (
        "warning", "in-place mutation of an argument of a jitted call"),
    "mismatched-shard-specs": (
        "error", "shard_map specs inconsistent with the axis the body "
                 "reduces over"),
    "donated-buffer-reuse": (
        "warning", "donate_argnums'd buffer read after the jitted call"),
}

_PRNG_CONSUMERS = frozenset({
    "normal", "uniform", "randint", "bernoulli", "categorical", "gumbel",
    "choice", "permutation", "shuffle", "truncated_normal", "exponential",
    "gamma", "beta", "dirichlet", "laplace", "poisson", "rademacher",
    "maxwell", "ball", "orthogonal", "t", "loggamma", "binomial",
})
_PRNG_DERIVERS = frozenset({"split", "fold_in", "clone"})
_STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "aval",
                           "sharding", "weak_type"})
_JIT_NAMES = frozenset({"jit"})  # matched as name or attribute tail


def _name_of(expr: ast.AST) -> Optional[str]:
    """Final identifier of a Name or dotted Attribute chain."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _dotted_name(expr: ast.AST) -> Optional[str]:
    """Full dotted path of a Name/Attribute chain (``pool.caches``), or
    None when the base is computed (``f().x``, ``a[i].x``)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = _dotted_name(expr.value)
        return f"{base}.{expr.attr}" if base is not None else None
    return None


def _is_jit_expr(expr: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` / ``partial(jax.jit, ...)`` /
    ``jax.jit(...)`` (call form) / ``functools.partial(jit, ...)``."""
    if _name_of(expr) in _JIT_NAMES:
        return True
    if isinstance(expr, ast.Call):
        fn = _name_of(expr.func)
        if fn in _JIT_NAMES:
            return True
        if fn == "partial" and expr.args and _is_jit_expr(expr.args[0]):
            return True
    return False


def _is_shard_map_expr(expr: ast.AST) -> bool:
    if _name_of(expr) in ("shard_map", "pmap"):
        return True
    if isinstance(expr, ast.Call):
        return _name_of(expr.func) in ("shard_map", "pmap")
    return False


def _terminates(stmts: Sequence[ast.stmt]) -> bool:
    """Whether a suite unconditionally leaves the enclosing block."""
    return any(isinstance(s, (ast.Return, ast.Raise, ast.Break, ast.Continue))
               for s in stmts)


def _donate_positions(call: ast.Call) -> Tuple[int, ...]:
    """Literal ``donate_argnums`` positions of a jit call, or ()."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            try:
                v = ast.literal_eval(kw.value)
            except ValueError:
                continue
            if isinstance(v, int):
                return (v,)
            if isinstance(v, (tuple, list)):
                return tuple(int(i) for i in v if isinstance(i, int))
    return ()


@dataclass
class _Ctx:
    """Module-wide facts collected in one pre-pass."""
    registry: CollectiveRegistry
    jitted_value_names: Set[str]   # x = jax.jit(f) / partial(jax.jit, ...)
    jitted_def_names: Set[str]     # defs decorated with / passed to jit
    static_params: Dict[str, Set[str]]  # def name -> static_argnames
    donated_callables: Dict[str, Tuple[int, ...]]  # name -> donate_argnums


def _collect_ctx(tree: ast.Module, registry: CollectiveRegistry) -> _Ctx:
    jitted_values: Set[str] = set()
    jitted_defs: Set[str] = set()
    static_params: Dict[str, Set[str]] = {}
    donated: Dict[str, Tuple[int, ...]] = {}

    def static_names_from_call(call: ast.Call) -> Set[str]:
        out: Set[str] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                try:
                    v = ast.literal_eval(kw.value)
                except ValueError:
                    continue
                out.update([v] if isinstance(v, str) else list(v))
        return out

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            statics: Set[str] = set()
            jitted = False
            for dec in node.decorator_list:
                if _is_jit_expr(dec) or _is_shard_map_expr(dec):
                    jitted = True
                    if isinstance(dec, ast.Call):
                        statics |= static_names_from_call(dec)
                        pos = _donate_positions(dec)
                        if pos:
                            donated[node.name] = pos
            if jitted:
                jitted_defs.add(node.name)
                static_params[node.name] = statics
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            # x = jax.jit(f)   |   x = partial(jax.jit, ...)(f)
            if _is_jit_expr(node.value.func) or _is_jit_expr(node.value):
                pos = _donate_positions(node.value)
                if not pos and isinstance(node.value.func, ast.Call):
                    # partial(jax.jit, donate_argnums=...)(f): the
                    # kwarg lives on the INNER partial call
                    pos = _donate_positions(node.value.func)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        jitted_values.add(t.id)
                        if pos:
                            donated[t.id] = pos
        if isinstance(node, ast.Call) and (
                _is_jit_expr(node.func) or _is_shard_map_expr(node.func)):
            # jax.jit(step) / shard_map(body, mesh=...) — the named def
            # becomes a traced body even without a decorator.
            statics = static_names_from_call(node)
            for a in node.args[:1]:
                nm = _name_of(a)
                if nm:
                    jitted_defs.add(nm)
                    static_params.setdefault(nm, set()).update(statics)

    return _Ctx(registry=registry, jitted_value_names=jitted_values,
                jitted_def_names=jitted_defs, static_params=static_params,
                donated_callables=donated)


# --------------------------------------------------------------------------
# per-scope analysis
# --------------------------------------------------------------------------

class _Scope:
    """Analysis of ONE function body (or the module top level).

    Nested defs get their own _Scope; statement-linear rules do not
    descend into them (a nested def does not execute where it is
    defined), but expression-level rules scanning the current statement
    skip nested-def subtrees explicitly.
    """

    def __init__(self, ctx: _Ctx, node, qualname: str,
                 findings: List[Finding]):
        self.ctx = ctx
        self.node = node
        self.qualname = qualname
        self.findings = findings
        self.rank_tainted: Set[str] = set()
        self.key_state: Dict[str, str] = {}      # key name -> fresh|used
        self.aliased: Set[str] = set()           # asarray sources/results
        self.jit_args: Set[str] = set()          # names passed to jitted calls
        self.local_jitted: Set[str] = set(ctx.jitted_value_names)
        self.mutations: List[Tuple[str, int]] = []  # (name, line)
        self._emitted: Set[Tuple[str, int]] = set()
        # donate_argnums tracking: callable name -> donated positions,
        # live buffer name -> line of the donating call
        self.donating: Dict[str, Tuple[int, ...]] = dict(
            ctx.donated_callables)
        self.donated_bufs: Dict[str, int] = {}

    # ---- helpers ----
    def emit(self, rule: str, line: int, message: str) -> None:
        if (rule, line) in self._emitted:
            return
        self._emitted.add((rule, line))
        sev = AST_RULES[rule][0]
        self.findings.append(Finding(
            rule=rule, severity=sev, path="", line=line, message=message,
            context=self.qualname))

    def _exprs(self, node: ast.AST) -> Iterable[ast.AST]:
        """Walk an expression/statement subtree WITHOUT entering nested
        function/class definitions."""
        stack = [node]
        while stack:
            n = stack.pop()
            yield n
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                    continue
                stack.append(child)

    def _is_rank_expr(self, expr: ast.AST) -> bool:
        reg = self.ctx.registry
        for n in self._exprs(expr):
            if isinstance(n, ast.Attribute) and n.attr in reg.rank_attrs:
                return True
            if isinstance(n, ast.Call) and _name_of(n.func) in reg.rank_calls:
                return True
            if isinstance(n, ast.Name) and n.id in self.rank_tainted:
                return True
        return False

    # ---- the linear walk ----
    def run(self) -> None:
        body = self.node.body
        self._walk_block(body, rank_guarded=None)

    def _walk_block(self, stmts: Sequence[ast.stmt],
                    rank_guarded: Optional[str]) -> None:
        """``rank_guarded`` carries the description of the innermost
        rank-dependent control context, or None when symmetric."""
        divergent: Optional[str] = None  # set after a rank-guarded early exit
        for st in stmts:
            guard = rank_guarded or divergent
            self._statement(st, guard)

            if isinstance(st, ast.If) and self._is_rank_expr(st.test):
                if _terminates(st.body) or _terminates(st.orelse):
                    divergent = divergent or (
                        f"after rank-dependent early exit at line {st.lineno}")

    def _statement(self, st: ast.stmt, guard: Optional[str]) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # nested defs are their own scopes (and don't run here)

        # -- rule: donated-buffer-reuse (reads BEFORE this statement's
        # own donations register, so the donating call's own args and a
        # rebinding `x = step(x, ...)` stay clean; names are full dotted
        # paths, so `pool.caches` — the serving cache-pool shape — is
        # tracked like a local) --
        if self.donated_bufs:
            for n in self._iter_own_exprs(st):
                if not (isinstance(n, (ast.Name, ast.Attribute))
                        and isinstance(n.ctx, ast.Load)):
                    continue
                dn = _dotted_name(n)
                if dn is None or dn not in self.donated_bufs:
                    continue
                don_line = self.donated_bufs.pop(dn)
                self.emit(
                    "donated-buffer-reuse", n.lineno,
                    f"`{dn}` was DONATED to the jitted call on line "
                    f"{don_line} (donate_argnums) and is read again — "
                    "the buffer may already be aliased to the output "
                    "(garbage on TPU, use-after-free semantics); "
                    "rebind the result (`x = step(x, ...)`) or pass a "
                    "copy (the serving cache-pool hazard class)")

        # -- rule: collective-deadlock (call sites) --
        if guard is not None:
            for call in self._iter_own_exprs(st):
                if isinstance(call, ast.Call) and \
                        self.ctx.registry.is_collective_call(call):
                    name = _name_of(call.func)
                    self.emit(
                        "collective-deadlock", call.lineno,
                        f"collective `{name}` executed {guard}: ranks that "
                        "skip it leave the gang waiting forever — hoist the "
                        "collective out of the rank-dependent path (guard "
                        "only the host-side work, e.g. printing/IO)")

        # expression-level rules on this statement (not nested blocks)
        for expr in self._iter_own_exprs(st):
            self._expression(expr, st)

        # track taints/aliases introduced by this statement
        self._track(st)

        # recurse into control-flow blocks — the incoming `guard` MUST
        # survive the descent: a collective wrapped in a plain loop/with/
        # try INSIDE a rank-guarded branch is still rank-guarded
        if isinstance(st, ast.If):
            g = (f"under the rank-dependent branch at line {st.lineno}"
                 if self._is_rank_expr(st.test) else guard)
            # donation state is branch-scoped: a call donating in one
            # branch must not flag a read in the mutually-exclusive
            # other branch; after the If, a donation from EITHER branch
            # stays live (either may have executed) — UNLESS that branch
            # terminates (return/raise/break/continue), in which case
            # control past the If can only have come through the other
            # path and the terminated branch's donations are unreachable
            snap = dict(self.donated_bufs)
            self._walk_block(st.body, g)
            after_body = self.donated_bufs
            self.donated_bufs = dict(snap)
            self._walk_block(st.orelse, g)
            after_else = self.donated_bufs
            if _terminates(st.body):
                merged = after_else
            elif _terminates(st.orelse):
                merged = after_body
            else:
                merged = dict(after_else)
                merged.update(after_body)
            self.donated_bufs = merged
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            g = guard
            if self._is_rank_expr(st.iter):
                g = (f"inside a loop with rank-dependent trip count "
                     f"(line {st.lineno})")
            # loop bodies run twice: key reuse across iterations surfaces
            self._walk_block(st.body, g)
            self._walk_block(st.body, g)
            self._walk_block(st.orelse, guard)
        elif isinstance(st, ast.While):
            g = guard
            if self._is_rank_expr(st.test):
                g = (f"inside a while-loop with rank-dependent condition "
                     f"(line {st.lineno})")
            self._walk_block(st.body, g)
            self._walk_block(st.body, g)
            self._walk_block(st.orelse, guard)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            self._walk_block(st.body, guard)
        elif isinstance(st, ast.Try):
            self._walk_block(st.body, guard)
            for h in st.handlers:
                self._walk_block(h.body, guard)
            self._walk_block(st.orelse, guard)
            self._walk_block(st.finalbody, guard)

    def _iter_own_exprs(self, st: ast.stmt) -> Iterable[ast.AST]:
        """Expressions belonging to THIS statement only — for compound
        statements, the header (test/iter/targets), not the body."""
        if isinstance(st, ast.If) or isinstance(st, ast.While):
            yield from self._exprs(st.test)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            yield from self._exprs(st.iter)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                yield from self._exprs(item.context_expr)
        elif isinstance(st, ast.Try):
            return
        else:
            yield from self._exprs(st)

    # ---- expression-level rules ----
    def _expression(self, n: ast.AST, st: ast.stmt) -> None:
        if not isinstance(n, ast.Call):
            return
        fname = _name_of(n.func)

        # -- rule: prng-constant-key --
        if fname == "PRNGKey" or (
                fname == "key" and isinstance(n.func, ast.Attribute)
                and _name_of(n.func.value) == "random"):
            if n.args and isinstance(n.args[0], ast.Constant) and \
                    isinstance(n.args[0].value, (int, bool)):
                self.emit(
                    "prng-constant-key", n.lineno,
                    f"`{fname}({n.args[0].value!r})` builds a process-"
                    "constant key: every run (and every rank) draws the "
                    "SAME randomness — derive the seed from a CLI "
                    "flag/config, and fold in the step/rank for per-call "
                    "freshness (the PR 3 sampling trap)")

        # -- rule: prng-key-reuse --
        if fname in _PRNG_CONSUMERS and self._in_random_ns(n.func):
            if n.args and isinstance(n.args[0], ast.Name):
                key = n.args[0].id
                state = self.key_state.get(key)
                if state == "used":
                    self.emit(
                        "prng-key-reuse", n.lineno,
                        f"key `{key}` already consumed by an earlier "
                        "sampling call in this scope — both calls draw "
                        "IDENTICAL values; `jax.random.split` (or "
                        "`fold_in`) the key between uses")
                else:
                    self.key_state[key] = "used"

    @staticmethod
    def _in_random_ns(func: ast.AST) -> bool:
        """``jax.random.normal`` / ``random.normal`` / bare ``normal``
        (assume a from-import when the name is that distinctive)."""
        if isinstance(func, ast.Attribute):
            return _name_of(func.value) in ("random", "jrandom", "jr")
        return True

    # ---- state tracking ----
    def _track(self, st: ast.stmt) -> None:
        reg = self.ctx.registry

        def taint_targets(targets, value):
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if not names:
                return
            if self._is_rank_expr(value):
                self.rank_tainted.update(names)
            else:
                self.rank_tainted.difference_update(names)

        if isinstance(st, ast.Assign):
            taint_targets(st.targets, st.value)
            self._track_assign_value(st.targets, st.value)
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            taint_targets([st.target], st.value)
            self._track_assign_value([st.target], st.value)
        elif isinstance(st, ast.AugAssign):
            self._record_mutation(st.target, st)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            if self._is_rank_expr(st.iter) and isinstance(st.target, ast.Name):
                self.rank_tainted.add(st.target.id)

        # subscript stores: buf[i] = v
        if isinstance(st, ast.Assign):
            for t in st.targets:
                if isinstance(t, ast.Subscript):
                    self._record_mutation(t, st)

        # scan every expression of this statement for alias/jit-arg facts
        for n in self._iter_own_exprs(st):
            if not isinstance(n, ast.Call):
                continue
            fname = _name_of(n.func)
            if fname == "asarray":
                for a in n.args[:1]:
                    if isinstance(a, ast.Name):
                        self.aliased.add(a.id)
            callee = _name_of(n.func)
            if callee and (callee in self.local_jitted
                           or callee in self.ctx.jitted_def_names):
                for a in list(n.args) + [kw.value for kw in n.keywords]:
                    if isinstance(a, ast.Name):
                        self.jit_args.add(a.id)

        # donate_argnums bookkeeping — donations from this statement's
        # calls register FIRST, then assignment targets clear, so the
        # canonical `params, opt = step(params, opt, b)` rebinding
        # consumes its own donation within one statement
        for n in self._iter_own_exprs(st):
            if isinstance(n, ast.Call):
                callee = _name_of(n.func)
                pos = self.donating.get(callee) if callee else None
                if pos:
                    for i in pos:
                        if i >= len(n.args):
                            continue
                        dn = _dotted_name(n.args[i])
                        if dn is not None:
                            self.donated_bufs[dn] = st.lineno
        rebind_targets: List[ast.AST] = []
        if isinstance(st, ast.Assign):
            rebind_targets = list(st.targets)
        elif isinstance(st, (ast.AnnAssign, ast.AugAssign)):
            rebind_targets = [st.target]
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            rebind_targets = [st.target]
        for t in rebind_targets:
            els = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for el in els:
                dn = _dotted_name(el)
                if dn is None:
                    continue
                # rebinding clears the path AND everything under it —
                # `pool = fresh_pool()` revives `pool.caches` too
                for k in [k for k in self.donated_bufs
                          if k == dn or k.startswith(dn + ".")]:
                    self.donated_bufs.pop(k, None)

        self._check_mutations()

    def _track_assign_value(self, targets, value) -> None:
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        # y = np.asarray(x): y may be a VIEW of x — mutating y mutates x
        if isinstance(value, ast.Call) and _name_of(value.func) == "asarray":
            self.aliased.update(names)
        # k = jax.random.split(key) / fold_in: fresh keys
        if isinstance(value, ast.Call) and \
                _name_of(value.func) in _PRNG_DERIVERS:
            for nm in names:
                self.key_state[nm] = "fresh"
            # tuple-unpack targets too: k1, k2 = split(key)
            for t in targets:
                if isinstance(t, (ast.Tuple, ast.List)):
                    for el in t.elts:
                        if isinstance(el, ast.Name):
                            self.key_state[el.id] = "fresh"
        elif names:
            for nm in names:
                self.key_state[nm] = "fresh"
        # x = jax.jit(f) inside a function scope
        if isinstance(value, ast.Call) and (_is_jit_expr(value.func)
                                            or _is_jit_expr(value)):
            self.local_jitted.update(names)

    def _record_mutation(self, target: ast.AST, st: ast.stmt) -> None:
        base = target
        while isinstance(base, ast.Subscript):
            base = base.value
        nm = _name_of(base)
        if nm:
            self.mutations.append((nm, st.lineno))

    def _check_mutations(self) -> None:
        """Scope-wide (order-insensitive — loops interleave the two sides):
        a name that is both asarray-aliased and mutated, or both passed to
        a jitted call and mutated, is a race."""
        for nm, line in self.mutations:
            if nm in self.aliased:
                self.emit(
                    "host-alias-race", line,
                    f"`{nm}` flows through `asarray` (zero-copy on CPU: the "
                    "device array may ALIAS this buffer) and is mutated in "
                    "place — async dispatch can read the mutated bytes "
                    "(the PR 3 serving pos-vector race); mutate a `.copy()` "
                    "or re-materialize the device array after the write")
            if nm in self.jit_args:
                self.emit(
                    "inplace-jit-mutation", line,
                    f"`{nm}` is passed to a jitted callable and mutated in "
                    "place in the same scope — with donation or zero-copy "
                    "the compiled program may still alias the buffer when "
                    "the mutation lands; pass a copy or make the update "
                    "functional")


# --------------------------------------------------------------------------
# traced-control-flow (per jitted def, separate small pass)
# --------------------------------------------------------------------------

def _check_traced_control_flow(ctx: _Ctx, fn_node, qualname: str,
                               findings: List[Finding]) -> None:
    name = fn_node.name
    if name not in ctx.jitted_def_names:
        return
    statics = ctx.static_params.get(name, set())
    params = {a.arg for a in (fn_node.args.posonlyargs + fn_node.args.args
                              + fn_node.args.kwonlyargs)} - statics - {"self"}
    if not params:
        return

    def dynamic_refs(test: ast.AST) -> List[ast.Name]:
        static_bases: Set[int] = set()
        for n in ast.walk(test):
            if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
                for sub in ast.walk(n.value):
                    static_bases.add(id(sub))
            elif isinstance(n, ast.Call) and \
                    _name_of(n.func) in ("len", "isinstance", "getattr",
                                         "hasattr", "type"):
                for a in n.args:
                    for sub in ast.walk(a):
                        static_bases.add(id(sub))
            elif isinstance(n, ast.Compare) and any(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
                for sub in ast.walk(n):
                    static_bases.add(id(sub))
        return [n for n in ast.walk(test)
                if isinstance(n, ast.Name) and n.id in params
                and id(n) not in static_bases]

    for node in ast.walk(fn_node):
        if isinstance(node, (ast.If, ast.While)) or \
                isinstance(node, ast.IfExp):
            refs = dynamic_refs(node.test)
            if refs:
                kind = "while" if isinstance(node, ast.While) else "if"
                sev_names = ", ".join(sorted({r.id for r in refs}))
                findings.append(Finding(
                    rule="traced-control-flow",
                    severity=AST_RULES["traced-control-flow"][0],
                    path="", line=node.test.lineno,
                    message=(
                        f"Python `{kind}` on traced value(s) `{sev_names}` "
                        f"inside jitted `{name}` — the branch is taken at "
                        "TRACE time (TracerBoolConversionError, or silent "
                        "specialization); use `lax.cond`/`lax.select`/"
                        "`lax.while_loop`, or declare the argument in "
                        "`static_argnames`"),
                    context=qualname))


# --------------------------------------------------------------------------
# mismatched-shard-specs (per scope, separate small pass)
# --------------------------------------------------------------------------

#: ops whose result is reduced/replicated over the named axis — an
#: out_spec that SHARDS the result over that same axis contradicts the
#: body (shard_map's replication checker rejects it at run time; this
#: catches it at lint time, jax-free).
_REDUCING_OPS = frozenset({
    "psum", "pmean", "pmax", "pmin", "pmean_if_bound", "all_gather",
    "bcast", "quantized_ring_pmean", "hierarchical_pmean",
})


def _mesh_literal_axes(call: ast.Call) -> Optional[Set[str]]:
    """Axis names of a mesh-constructing call, when they are literals:
    ``make_nd_mesh(("a", "b"), ...)``, ``make_mesh(axis_name="x")``,
    ``Mesh(devs, ("a",))``.  None = not resolvable (stay silent)."""
    fname = _name_of(call.func)

    def str_literals(expr) -> Optional[Set[str]]:
        try:
            v = ast.literal_eval(expr)
        except (ValueError, SyntaxError):
            return None
        if isinstance(v, str):
            return {v}
        if isinstance(v, (tuple, list)) and all(
                isinstance(x, str) for x in v):
            return set(v)
        return None

    if fname == "make_nd_mesh" and call.args:
        return str_literals(call.args[0])
    if fname == "make_mesh":
        for kw in call.keywords:
            if kw.arg == "axis_name":
                return str_literals(kw.value)
        return None
    if fname == "Mesh":
        if len(call.args) >= 2:
            return str_literals(call.args[1])
        for kw in call.keywords:
            if kw.arg == "axis_names":
                return str_literals(kw.value)
    return None


def _pspec_axes(expr: ast.AST) -> Set[str]:
    """Axis-name string literals inside any ``P(...)``/``PartitionSpec``
    call of an (in|out)_specs expression."""
    out: Set[str] = set()
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and _name_of(n.func) in (
                "P", "PartitionSpec"):
            for a in n.args:
                for sub in ast.walk(a):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        out.add(sub.value)
    return out


def _collective_axis_literals_of_call(call: ast.Call) -> Set[str]:
    """Literal axis names ONE collective call names: the ``axis_name=``
    (or hierarchical ``chip_axis=``/``slice_axis=``) keyword, or any
    positional string literal (``psum(x, "mn")``).  Non-literal axes
    resolve to nothing — the rule only argues from positive evidence."""
    axes: Set[str] = set()
    for kw in call.keywords:
        if kw.arg in ("axis_name", "chip_axis", "slice_axis") and \
                isinstance(kw.value, ast.Constant) and \
                isinstance(kw.value.value, str):
            axes.add(kw.value.value)
    for a in call.args:
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            axes.add(a.value)
    return axes


def _collective_axis_literals(fn_node, registry: CollectiveRegistry
                              ) -> Set[str]:
    """Union of :func:`_collective_axis_literals_of_call` over every
    collective call in a body function."""
    axes: Set[str] = set()
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Call) and registry.is_collective_call(n):
            axes |= _collective_axis_literals_of_call(n)
    return axes


def _check_shard_specs(ctx: _Ctx, scope_node, qualname: str,
                       findings: List[Finding]) -> None:
    """Two inconsistency shapes at a ``shard_map(...)`` call site, argued
    only from same-scope literals (no cross-module resolution — silence
    over speculation):

    * the body's collectives name a LITERAL axis absent from the mesh
      whose axis names are resolvable in this scope — the compiled gang
      would raise (or bind the wrong axis) at run time;
    * the body RETURNS a reducing collective over axis ``a`` (result
      replicated over ``a``) while ``out_specs`` shards over ``a`` —
      shard_map's replication checker rejects exactly this, but only
      once jax runs.
    """
    body = getattr(scope_node, "body", [])
    local_defs: Dict[str, ast.AST] = {}
    mesh_axes_of: Dict[str, Set[str]] = {}
    for st in body:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local_defs[st.name] = st
        if isinstance(st, ast.Assign) and isinstance(st.value, ast.Call):
            ax = _mesh_literal_axes(st.value)
            if ax:
                for t in st.targets:
                    if isinstance(t, ast.Name):
                        mesh_axes_of[t.id] = ax

    # shard_map calls belonging to THIS scope (not nested defs)
    stack: List[ast.AST] = list(body)
    calls: List[ast.Call] = []
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        if isinstance(n, ast.Call) and _is_shard_map_expr(n.func):
            calls.append(n)
        stack.extend(ast.iter_child_nodes(n))

    for call in calls:
        if not call.args:
            continue
        # resolve the body: a same-scope def, possibly through
        # partial(fn, axis_name="x")
        first = call.args[0]
        partial_axes: Set[str] = set()
        if isinstance(first, ast.Call) and _name_of(first.func) == "partial":
            for kw in first.keywords:
                if kw.arg == "axis_name" and \
                        isinstance(kw.value, ast.Constant) and \
                        isinstance(kw.value.value, str):
                    partial_axes.add(kw.value.value)
            first = first.args[0] if first.args else first
        body_def = local_defs.get(_name_of(first) or "")

        mesh_axes: Optional[Set[str]] = None
        out_specs_expr = None
        for kw in call.keywords:
            if kw.arg == "mesh":
                if isinstance(kw.value, ast.Name):
                    mesh_axes = mesh_axes_of.get(kw.value.id)
                elif isinstance(kw.value, ast.Call):
                    mesh_axes = _mesh_literal_axes(kw.value)
            elif kw.arg == "out_specs":
                out_specs_expr = kw.value

        body_axes: Set[str] = set(partial_axes)
        if body_def is not None:
            body_axes |= _collective_axis_literals(body_def, ctx.registry)

        if mesh_axes and body_axes:
            stray = sorted(body_axes - mesh_axes)
            if stray:
                findings.append(Finding(
                    rule="mismatched-shard-specs",
                    severity=AST_RULES["mismatched-shard-specs"][0],
                    path="", line=call.lineno,
                    message=(
                        f"shard_map body reduces over axis "
                        f"{stray} but the mesh built in this scope only "
                        f"binds {sorted(mesh_axes)} — the collective "
                        "would hit an unbound (or wrong) axis at run "
                        "time; make the body's axis_name and the mesh "
                        "agree"),
                    context=qualname))

        if body_def is not None and out_specs_expr is not None:
            out_axes = _pspec_axes(out_specs_expr)
            if out_axes:
                for ret in ast.walk(body_def):
                    if not (isinstance(ret, ast.Return)
                            and isinstance(ret.value, ast.Call)):
                        continue
                    rcall = ret.value
                    rname = _name_of(rcall.func)
                    if rname not in _REDUCING_OPS:
                        continue
                    raxes = _collective_axis_literals_of_call(rcall)
                    clash = sorted(raxes & out_axes)
                    if clash:
                        findings.append(Finding(
                            rule="mismatched-shard-specs",
                            severity=AST_RULES["mismatched-shard-specs"][0],
                            path="", line=call.lineno,
                            message=(
                                f"the body returns `{rname}` over axis "
                                f"{clash} — a value REPLICATED over that "
                                "axis — but out_specs shards the output "
                                f"over {clash}: each rank would keep only "
                                "a slice of an identical value (and the "
                                "replication checker rejects it); use "
                                "P() for reduced outputs, or drop the "
                                "reduction"),
                            context=qualname))


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------

def _iter_scopes(tree: ast.Module):
    """Yield (node, qualname) for the module and every def, tracking the
    enclosing chain."""
    yield tree, "<module>"

    def rec(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                yield child, q
                yield from rec(child, q)
            elif isinstance(child, ast.ClassDef):
                q = f"{prefix}.{child.name}" if prefix else child.name
                yield from rec(child, q)
            else:
                yield from rec(child, prefix)

    yield from rec(tree, "")


def analyze_source(source: str, path: str,
                   registry: Optional[CollectiveRegistry] = None,
                   rules: Optional[Sequence[str]] = None) -> List[Finding]:
    registry = registry or default_registry()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(rule="parse-error", severity="error", path=path,
                        line=e.lineno or 0,
                        message=f"file does not parse: {e.msg}")]
    ctx = _collect_ctx(tree, registry)
    findings: List[Finding] = []
    for node, qualname in _iter_scopes(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _Scope(ctx, node, qualname, findings).run()
            _check_traced_control_flow(ctx, node, qualname, findings)
        else:
            _Scope(ctx, node, qualname, findings).run()
        _check_shard_specs(ctx, node, qualname, findings)

    lines = source.splitlines()
    sup = Suppressions(source)
    out = []
    wanted = set(rules) if rules else None
    for f in findings:
        # parse-error bypasses the rule filter: "this file could not be
        # analyzed at all" must never read as "clean under rule X"
        if wanted is not None and f.rule not in wanted \
                and f.rule != "parse-error":
            continue
        if sup.suppressed(f.rule, f.line):
            continue
        f.path = path
        if 1 <= f.line <= len(lines):
            f.snippet = lines[f.line - 1].strip()
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def analyze_file(path: str,
                 registry: Optional[CollectiveRegistry] = None,
                 rules: Optional[Sequence[str]] = None) -> List[Finding]:
    with open(path) as fh:
        source = fh.read()
    return analyze_source(source, path, registry=registry, rules=rules)


_DEFAULT_EXCLUDES = ("__pycache__", ".git", "build", "dist", ".eggs")


def analyze_paths(paths: Sequence[str],
                  registry: Optional[CollectiveRegistry] = None,
                  rules: Optional[Sequence[str]] = None,
                  exclude: Sequence[str] = _DEFAULT_EXCLUDES
                  ) -> List[Finding]:
    registry = registry or default_registry()
    files: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d not in exclude]
            files.extend(os.path.join(dirpath, f)
                         for f in sorted(filenames) if f.endswith(".py"))
    findings: List[Finding] = []
    for f in sorted(set(files)):
        findings.extend(analyze_file(f, registry=registry, rules=rules))
    return findings
