"""``python -m chainermn_tpu.analysis`` — see cli.py for the contract."""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
