"""Concurrency lint — lock-discipline rules over the threaded fleet.

The serving/observability/health planes are a thread-and-lock system
(submit threads, supervisor ticks, role drivers, heartbeat side
threads; 17 modules hold ``threading.Lock``\\ s), and the PR 10-13
review rounds hand-found ~25 real races in exactly four shapes.  This
engine makes those shapes mechanical (docs/ANALYSIS.md has the real
historical bug behind each rule):

==========================  ========  =====================================
rule                        severity  fires on
==========================  ========  =====================================
lock-order-inversion        error     a cycle in the per-class lock-
                                      acquisition graph (lock B taken
                                      while A held in one path, A while B
                                      held in another), including re-
                                      acquisition of a NON-reentrant lock
                                      through an intra-class call chain
unguarded-shared-write      warning   a field written under ``self._lock``
                                      in one method but written bare in
                                      another — the PR 10 seq-mint and
                                      ``sent_since_lease`` lost-update
                                      class
blocking-call-under-lock    warning   ``lane_call``/lane-store get/put/
                                      ``sleep``/``join``/``wait``/
                                      subprocess/compiled-program calls
                                      while a lock is held — every other
                                      thread needing the lock stalls for
                                      the full I/O (the `_supervise`
                                      lease-poll shape)
callback-under-lock-contract warning  a user-supplied callback (``on_*``/
                                      ``*_hook``/``*_cb``) invoked while a
                                      lock is held without a
                                      ``# holds-lock: <lock>`` declaration
                                      on the call line (or the line
                                      above), OR a declaration that no
                                      longer matches reality — the two-
                                      sided PR 12 PrefixCache hook
                                      contract
==========================  ========  =====================================

Pure stdlib ``ast`` like ``ast_engine.py`` — no jax import, runs on any
box.  Findings ride the same fingerprint/suppression machinery
(``# spmd-lint: disable=<rule>`` works here too); the checked-in
baseline is ``.concurrency-baseline.json``.

What "held" means statically: ``with self._lock:`` blocks (and
``with``-stacked multiples), linear ``.acquire()``/``.release()``
pairs, and whole-body holds via a ``@_locked``-style decorator (any
decorator whose name contains ``locked`` is assumed to wrap the body in
``with self._lock``).  A nested ``def`` does NOT inherit the
enclosing ``with`` — its body runs later, on whatever thread calls it.

The per-class lock graph and the creation-site table are exported
(:func:`lock_graph`, :func:`lock_sites`) for the opt-in
``CHAINERMN_TPU_LOCK_ASSERT=1`` runtime cross-check
(``analysis/lockassert.py``): dynamic acquisition orders the AST cannot
see are recorded at test time and the UNION of both graphs must stay
acyclic.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding, Suppressions

#: rule id -> (severity, one-line summary) — the catalog.
CONCURRENCY_RULES: Dict[str, Tuple[str, str]] = {
    "lock-order-inversion": (
        "error", "cycle in the per-class lock-acquisition graph"),
    "unguarded-shared-write": (
        "warning", "field written both under a lock and bare"),
    "blocking-call-under-lock": (
        "warning", "blocking call while a lock is held"),
    "callback-under-lock-contract": (
        "warning", "callback under a lock without (or with a stale) "
                   "# holds-lock: declaration"),
}

CONCURRENCY_BASELINE_FILENAME = ".concurrency-baseline.json"

_LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition"})
_REENTRANT_KINDS = frozenset({"RLock", "Condition"})  # Condition wraps RLock

_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*([A-Za-z0-9_.,\s]+)")

#: attribute names treated as user-supplied callbacks when invoked.
_CALLBACK_ATTR_RE = re.compile(
    r"^(on_|_on_)|(_hook|_hooks|_cb|_callback|_callbacks)$|callback")

#: containers whose elements are callbacks (``for h in self._hooks:``).
_CALLBACK_CONTAINER_RE = re.compile(
    r"(_hooks|_callbacks|_cbs|_listeners|_sinks)$")

#: lane/store receivers whose get/put/send family blocks on I/O.
_LANE_BASES = frozenset({"store", "sender", "receiver", "outbox", "inbox",
                         "mailbox", "lane", "lanes"})
_LANE_TAILS = frozenset({"send", "recv", "put", "get", "delete", "drain",
                         "tags"})
_SUBPROCESS_TAILS = frozenset({"run", "call", "check_call", "check_output",
                               "Popen", "communicate"})


def _name_of(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _dotted(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = _dotted(expr.value)
        return f"{base}.{expr.attr}" if base is not None else None
    return None


def _terminates(stmts: Sequence[ast.stmt]) -> bool:
    """Whether a suite unconditionally leaves the enclosing block."""
    return any(isinstance(s, (ast.Return, ast.Raise, ast.Break,
                              ast.Continue))
               for s in stmts)


def _is_jit_expr(expr: ast.AST) -> bool:
    if _name_of(expr) == "jit":
        return True
    if isinstance(expr, ast.Call):
        fn = _name_of(expr.func)
        if fn == "jit":
            return True
        if fn == "partial" and expr.args and _is_jit_expr(expr.args[0]):
            return True
    return False


@dataclass(frozen=True)
class LockInfo:
    """One lock object the analyzer tracks."""
    lock_id: str     # "ClassQual.attr" or "<module>.NAME"
    attr: str        # the bare attr/name the source uses
    kind: str        # Lock | RLock | Condition
    line: int        # creation line (the lockassert site key)


@dataclass
class _Edge:
    src: str
    dst: str
    line: int
    context: str


@dataclass
class _Write:
    attr: str
    line: int
    method: str      # method qualname tail ("submit", "start.loop", ...)
    guarded: bool
    locks: Tuple[str, ...]


@dataclass
class _ClassFacts:
    qual: str
    locks: Dict[str, LockInfo] = field(default_factory=dict)  # attr -> info
    edges: List[_Edge] = field(default_factory=list)
    writes: List[_Write] = field(default_factory=list)
    # method name -> {lock attr -> first acquisition line}
    acquires: Dict[str, Dict[str, int]] = field(default_factory=dict)
    # (caller method, callee method, held attrs tuple, line)
    self_calls: List[Tuple[str, str, Tuple[str, ...], int]] = \
        field(default_factory=list)
    # def-level `# holds-lock:` contracts: method -> declared lock attrs
    contracts: Dict[str, Set[str]] = field(default_factory=dict)


class _HoldsDecls:
    """``# holds-lock: a, b`` comment table, parsed once per file from
    REAL comment tokens (``tokenize``) — the marker inside a docstring
    or string literal is prose, not a declaration."""

    def __init__(self, source: str):
        import io
        import tokenize

        self.by_line: Dict[int, Set[str]] = {}
        try:
            toks = tokenize.generate_tokens(
                io.StringIO(source).readline)
            comments = [(t.start[0], t.string) for t in toks
                        if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, SyntaxError,
                IndentationError):   # pragma: no cover - parse-error path
            comments = []
        for i, text in comments:
            m = _HOLDS_RE.search(text)
            if not m:
                continue
            names = {t.strip() for t in m.group(1).split(",")
                     if t.strip()}
            names = {t[5:] if t.startswith("self.") else t
                     for t in names}
            if names:
                self.by_line[i] = names

    def for_def(self, def_line: int,
                first_stmt_line: int) -> Tuple[Set[str], List[int]]:
        """A def-level contract: a declaration on the ``def`` line or
        on a comment line between it and the first statement means
        "callers hold these locks" — the body is analyzed as if they
        were held, and every intra-class call site is checked against
        the contract."""
        out: Set[str] = set()
        used: List[int] = []
        for ln in range(def_line, max(first_stmt_line, def_line + 1)):
            names = self.by_line.get(ln)
            if names:
                out |= names
                used.append(ln)
        return out, used

    def for_call(self, line: int) -> Tuple[Set[str], List[int]]:
        """Declared locks covering a call at ``line`` (own line or the
        line above), plus the declaration lines consumed."""
        out: Set[str] = set()
        used: List[int] = []
        for ln in (line, line - 1):
            toks = self.by_line.get(ln)
            if toks:
                out |= toks
                used.append(ln)
        return out, used


class _FileAnalyzer:
    def __init__(self, source: str, path: str):
        self.source = source
        self.path = path
        self.lines = source.splitlines()
        self.findings: List[Finding] = []
        self.decls = _HoldsDecls(source)
        #: callback-call line -> held lock attr names at that call
        self.callback_calls: Dict[int, Set[str]] = {}
        #: declaration lines consumed by a matching callback call
        self.consumed_decls: Set[int] = set()
        self.module_locks: Dict[str, LockInfo] = {}
        self.classes: List[_ClassFacts] = []
        #: the module-scope pseudo-class (module functions + module
        #: locks) — kept so lock_graph() exports its edges too
        self.mod_facts: Optional[_ClassFacts] = None
        self.jitted_names: Set[str] = set()     # module/local callables
        self.jitted_attrs: Set[str] = set()     # self.X = jit(...)

    # ---- entry ----
    def run(self) -> List[Finding]:
        try:
            tree = ast.parse(self.source, filename=self.path)
        except SyntaxError as e:
            return [Finding(rule="parse-error", severity="error",
                            path=self.path, line=e.lineno or 0,
                            message=f"file does not parse: {e.msg}")]
        self._collect_module_facts(tree)

        # module-level functions run under module locks only
        mod_facts = _ClassFacts(qual="<module>")
        mod_facts.locks = dict(self.module_locks)
        self.mod_facts = mod_facts
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_method(mod_facts, node, node.name, held=[])
        self._emit_graph_findings(mod_facts)

        for cls, qual in self._iter_classes(tree):
            facts = self._class_facts(cls, qual)
            self.classes.append(facts)
            self._emit_graph_findings(facts)
            self._emit_unguarded_writes(facts)

        self._emit_stale_decls()
        return self.findings

    # ---- collection ----
    def _collect_module_facts(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                kind = self._lock_kind(node.value)
                if kind:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.module_locks[t.id] = LockInfo(
                                f"<module>.{t.id}", t.id, kind,
                                node.lineno)
                # (jit-assign detection happens in the full-tree walk
                # below, which also visits these module-level nodes)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_is_jit_expr(d) for d in node.decorator_list):
                    self.jitted_names.add(node.name)
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                if _is_jit_expr(node.value.func) or \
                        _is_jit_expr(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.jitted_names.add(t.id)
                        elif isinstance(t, ast.Attribute) and \
                                _name_of(t.value) == "self":
                            self.jitted_attrs.add(t.attr)

    @staticmethod
    def _lock_kind(call: ast.Call) -> Optional[str]:
        name = _name_of(call.func)
        if name in _LOCK_FACTORIES:
            # threading.Lock() / Lock() / threading.Condition()
            return name
        return None

    def _iter_classes(self, tree: ast.Module):
        def rec(node, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    q = f"{prefix}.{child.name}" if prefix else child.name
                    yield child, q
                    yield from rec(child, q)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    yield from rec(child, prefix)
        yield from rec(tree, "")

    def _class_facts(self, cls: ast.ClassDef, qual: str) -> _ClassFacts:
        facts = _ClassFacts(qual=qual)
        # pre-pass: every `self.X = threading.Lock()` in any method
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                kind = self._lock_kind(node.value)
                if not kind:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            _name_of(t.value) == "self":
                        facts.locks[t.attr] = LockInfo(
                            f"{qual}.{t.attr}", t.attr, kind,
                            node.lineno)
        for meth in cls.body:
            if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                held: List[LockInfo] = []
                if self._locked_decorator(meth) and \
                        "_lock" in facts.locks:
                    held = [facts.locks["_lock"]]
                    facts.acquires.setdefault(meth.name, {}).setdefault(
                        "_lock", meth.lineno)
                held.extend(self._def_contract(facts, meth))
                self._walk_method(facts, meth, meth.name, held=held)
        self._emit_contract_violations(facts)
        return facts

    def _def_contract(self, facts: _ClassFacts, meth) -> List[LockInfo]:
        """Seed the held set from a def-level ``# holds-lock:``
        contract ("callers hold these") and record it for call-site
        verification."""
        first = meth.body[0].lineno if meth.body else meth.lineno + 1
        declared, used = self.decls.for_def(meth.lineno, first)
        if not declared:
            return []
        self.consumed_decls.update(used)
        facts.contracts[meth.name] = declared
        out: List[LockInfo] = []
        for attr in sorted(declared):
            info = facts.locks.get(attr) or self.module_locks.get(attr)
            if info is not None:
                out.append(info)
        return out

    def _emit_contract_violations(self, facts: _ClassFacts) -> None:
        """The stale/violated side of a def-level contract: every
        intra-class call of a contract method must hold the declared
        locks (the caller half of the PR 12 hook discipline)."""
        for caller, callee, held_attrs, line in facts.self_calls:
            declared = facts.contracts.get(callee)
            if not declared:
                continue
            missing = declared - set(held_attrs)
            if missing:
                self.findings.append(Finding(
                    rule="callback-under-lock-contract",
                    severity=CONCURRENCY_RULES[
                        "callback-under-lock-contract"][0],
                    path="", line=line,
                    context=f"{facts.qual}.{caller}",
                    message=(
                        f"`self.{callee}` declares `# holds-lock: "
                        f"{', '.join(sorted(declared))}` but is called "
                        f"here without {sorted(missing)} — the "
                        "contract says callers serialize; take the "
                        "lock at this call site or drop the "
                        "declaration")))

    @staticmethod
    def _locked_decorator(meth) -> bool:
        for dec in meth.decorator_list:
            nm = _name_of(dec if not isinstance(dec, ast.Call)
                          else dec.func)
            if nm and "locked" in nm:
                return True
        return False

    # ---- the statement walk (one method or module function) ----
    def _lock_of_expr(self, facts: _ClassFacts,
                      expr: ast.AST) -> Optional[LockInfo]:
        """Resolve ``self._lock`` / module ``NAME`` to a tracked lock."""
        if isinstance(expr, ast.Attribute) and \
                _name_of(expr.value) == "self":
            return facts.locks.get(expr.attr)
        if isinstance(expr, ast.Name):
            return self.module_locks.get(expr.id)
        return None

    def _walk_method(self, facts: _ClassFacts, fn, method: str,
                     held: List[LockInfo]) -> None:
        # `cb = self.on_evict` rebindings tracked per method scope
        self._cb_names: Set[str] = set()
        self._walk_block(facts, fn.body, method, held)

    def _walk_block(self, facts: _ClassFacts, stmts: Sequence[ast.stmt],
                    method: str, held: List[LockInfo]) -> None:
        for st in stmts:
            self._statement(facts, st, method, held)

    def _statement(self, facts: _ClassFacts, st: ast.stmt, method: str,
                   held: List[LockInfo]) -> None:
        if isinstance(st, ast.ClassDef):
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def's body does NOT run under the enclosing
            # lock — it runs when (and where) someone calls it; walk it
            # with a clean held set so its own `with` blocks count
            saved = self._cb_names
            self._walk_method(facts, st, f"{method}.{st.name}", held=[])
            self._cb_names = saved
            return

        # expression-level checks on this statement's own expressions
        for call in self._own_calls(st):
            self._check_call(facts, call, method, held, st)

        # writes to self.<attr> (class scopes only)
        if facts.qual != "<module>":
            self._record_writes(facts, st, method, held)

        # callback-name rebinding: cb = self.on_evict
        if isinstance(st, ast.Assign) and \
                isinstance(st.value, ast.Attribute) and \
                _CALLBACK_ATTR_RE.search(st.value.attr or ""):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    self._cb_names.add(t.id)

        # linear acquire()/release() tracking
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
            call = st.value
            fname = _name_of(call.func)
            if fname in ("acquire", "release") and \
                    isinstance(call.func, ast.Attribute):
                info = self._lock_of_expr(facts, call.func.value)
                if info is not None:
                    if fname == "acquire":
                        self._note_acquire(facts, info, method,
                                           call.lineno, held)
                        held.append(info)
                    else:
                        for i in range(len(held) - 1, -1, -1):
                            if held[i].lock_id == info.lock_id:
                                del held[i]
                                break

        # control flow
        if isinstance(st, (ast.With, ast.AsyncWith)):
            entered: List[LockInfo] = []
            for item in st.items:
                info = self._lock_of_expr(facts, item.context_expr)
                if info is not None:
                    self._note_acquire(facts, info, method,
                                       st.lineno, held + entered)
                    entered.append(info)
            held.extend(entered)
            # `for h in self._hooks:` loop vars inside a with-block are
            # still visible to the block walk below
            self._walk_block(facts, st.body, method, held)
            for _ in entered:
                held.pop()
        elif isinstance(st, ast.If):
            # the linear acquire()/release() state is BRANCH-SCOPED: an
            # acquire inside the if-body must not read as held while the
            # mutually exclusive else-body is walked (0-FP requirement).
            # After the If, keep the surviving branch's state when the
            # other terminates, else the intersection (a lock released
            # on only one path is conservatively treated as released)
            snap = list(held)
            self._walk_block(facts, st.body, method, held)
            after_body = list(held)
            held[:] = snap
            self._walk_block(facts, st.orelse, method, held)
            after_else = list(held)
            if _terminates(st.body):
                held[:] = after_else
            elif _terminates(st.orelse):
                held[:] = after_body
            else:
                else_ids = {h.lock_id for h in after_else}
                held[:] = [h for h in after_body
                           if h.lock_id in else_ids]
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            # callback containers: for h in self._hooks: h(...)
            if isinstance(st.iter, ast.Attribute) and \
                    _CALLBACK_CONTAINER_RE.search(st.iter.attr or "") \
                    or (isinstance(st.iter, ast.Call)
                        and isinstance(st.iter.func, ast.Name)
                        and st.iter.func.id == "list"
                        and st.iter.args
                        and isinstance(st.iter.args[0], ast.Attribute)
                        and _CALLBACK_CONTAINER_RE.search(
                            st.iter.args[0].attr or "")):
                if isinstance(st.target, ast.Name):
                    self._cb_names.add(st.target.id)
            snap = list(held)
            self._walk_block(facts, st.body, method, held)
            held[:] = snap   # zero-iteration loops: state is branch-scoped
            self._walk_block(facts, st.orelse, method, held)
            held[:] = snap
        elif isinstance(st, ast.While):
            snap = list(held)
            self._walk_block(facts, st.body, method, held)
            held[:] = snap
            self._walk_block(facts, st.orelse, method, held)
            held[:] = snap
        elif isinstance(st, ast.Try):
            self._walk_block(facts, st.body, method, held)
            for h in st.handlers:
                self._walk_block(facts, h.body, method, held)
            self._walk_block(facts, st.orelse, method, held)
            self._walk_block(facts, st.finalbody, method, held)

    def _note_acquire(self, facts: _ClassFacts, info: LockInfo,
                      method: str, line: int,
                      held: Sequence[LockInfo]) -> None:
        facts.acquires.setdefault(method, {}).setdefault(info.attr, line)
        for h in held:
            facts.edges.append(_Edge(h.lock_id, info.lock_id, line,
                                     f"{facts.qual}.{method}"))

    def _own_calls(self, st: ast.stmt) -> Iterable[ast.Call]:
        """Call expressions of THIS statement (headers for compound
        statements), not of nested blocks or nested defs."""
        if isinstance(st, (ast.If, ast.While)):
            roots: List[ast.AST] = [st.test]
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            roots = [st.iter]
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            roots = [item.context_expr for item in st.items]
        elif isinstance(st, ast.Try):
            return
        else:
            roots = [st]
        stack: List[ast.AST] = list(roots)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                yield n
            stack.extend(ast.iter_child_nodes(n))

    # ---- per-call rules ----
    def _check_call(self, facts: _ClassFacts, call: ast.Call,
                    method: str, held: Sequence[LockInfo],
                    st: ast.stmt) -> None:
        ctx = f"{facts.qual}.{method}"
        # intra-class call: self.m(...) — the lock-order closure input
        if isinstance(call.func, ast.Attribute) and \
                _name_of(call.func.value) == "self" and \
                facts.qual != "<module>":
            facts.self_calls.append(
                (method, call.func.attr,
                 tuple(h.attr for h in held), call.lineno))
        elif isinstance(call.func, ast.Name) and \
                facts.qual == "<module>":
            facts.self_calls.append(
                (method, call.func.id,
                 tuple(h.attr for h in held), call.lineno))

        if not held:
            return
        held_attrs = {h.attr for h in held}

        blocked = self._blocking_reason(facts, call, held)
        if blocked:
            self.findings.append(Finding(
                rule="blocking-call-under-lock",
                severity=CONCURRENCY_RULES[
                    "blocking-call-under-lock"][0],
                path="", line=call.lineno, context=ctx,
                message=(
                    f"{blocked} while holding "
                    f"{sorted(held_attrs)} — every thread contending "
                    "for the lock stalls for the full call (and a "
                    "blocking call that re-enters this class can "
                    "deadlock); move the call outside the critical "
                    "section or snapshot under the lock and do the "
                    "I/O after")))

        if self._is_callback_call(call):
            self.callback_calls.setdefault(
                call.lineno, set()).update(held_attrs)
            declared, used = self.decls.for_call(call.lineno)
            self.consumed_decls.update(used)
            missing = held_attrs - declared
            if missing:
                cb = _dotted(call.func) or _name_of(call.func) or "?"
                self.findings.append(Finding(
                    rule="callback-under-lock-contract",
                    severity=CONCURRENCY_RULES[
                        "callback-under-lock-contract"][0],
                    path="", line=call.lineno, context=ctx,
                    message=(
                        f"callback `{cb}` invoked while holding "
                        f"{sorted(missing)} with no `# holds-lock: "
                        f"{', '.join(sorted(missing))}` declaration — "
                        "a hook that takes any lock orderable against "
                        "this one deadlocks (the PR 12 PrefixCache "
                        "hook contract); declare the hold on the call "
                        "line so hook authors can see it, or move the "
                        "invocation outside the lock")))

    def _is_callback_call(self, call: ast.Call) -> bool:
        if isinstance(call.func, ast.Attribute):
            return bool(_CALLBACK_ATTR_RE.search(call.func.attr or ""))
        if isinstance(call.func, ast.Name):
            return call.func.id in self._cb_names
        return False

    def _blocking_reason(self, facts: _ClassFacts, call: ast.Call,
                         held: Sequence[LockInfo]) -> Optional[str]:
        fname = _name_of(call.func)
        dotted = _dotted(call.func) or (fname or "")

        if fname == "sleep":
            return f"`{dotted}` sleeps"
        if fname in ("lane_call", "lane_try_get"):
            return f"`{fname}` does retrying lane I/O"
        if fname == "wait":
            if isinstance(call.func, ast.Attribute):
                recv = self._lock_of_expr(facts, call.func.value)
                if recv is not None and any(
                        h.lock_id == recv.lock_id for h in held):
                    return None   # cv.wait() RELEASES the held lock
            return f"`{dotted}` blocks on an event/thread/process"
        if fname == "join":
            # str.join / os.path.join take an iterable/str args;
            # Thread.join()/Popen.join(timeout) take nothing or a number
            numeric = (len(call.args) == 1
                       and isinstance(call.args[0], ast.Constant)
                       and isinstance(call.args[0].value, (int, float)))
            kw_ok = all(kw.arg == "timeout" for kw in call.keywords)
            if (not call.args or numeric) and kw_ok and \
                    isinstance(call.func, ast.Attribute):
                return f"`{dotted}` joins a thread/process"
            return None
        if fname in _SUBPROCESS_TAILS and isinstance(
                call.func, ast.Attribute) and \
                _name_of(call.func.value) == "subprocess":
            return f"`{dotted}` spawns/waits on a subprocess"
        if fname == "communicate":
            return f"`{dotted}` waits on a subprocess"
        if isinstance(call.func, ast.Attribute) and \
                fname in _LANE_TAILS:
            base = _dotted(call.func.value) or ""
            segs = set(base.split("."))
            if segs & _LANE_BASES:
                return f"`{dotted}` is lane/store I/O"
        # compiled-program calls: self._tick(...) assigned from jit, or
        # a module/local name assigned from jit / a jit-decorated def
        if isinstance(call.func, ast.Attribute) and \
                _name_of(call.func.value) == "self" and \
                call.func.attr in self.jitted_attrs:
            return f"`{dotted}` runs a compiled program"
        if isinstance(call.func, ast.Name) and \
                call.func.id in self.jitted_names:
            return f"`{dotted}` runs a compiled program"
        return None

    # ---- writes ----
    def _record_writes(self, facts: _ClassFacts, st: ast.stmt,
                       method: str, held: Sequence[LockInfo]) -> None:
        targets: List[ast.AST] = []
        if isinstance(st, ast.Assign):
            targets = list(st.targets)
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            targets = [st.target]
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                targets.extend(t.elts)
                continue
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value
            if isinstance(base, ast.Attribute) and \
                    _name_of(base.value) == "self":
                if base.attr in facts.locks:
                    continue   # creating/rebinding the lock itself
                facts.writes.append(_Write(
                    attr=base.attr, line=st.lineno, method=method,
                    guarded=bool(held),
                    locks=tuple(sorted(h.attr for h in held))))

    # ---- emission ----
    def _emit_graph_findings(self, facts: _ClassFacts) -> None:
        if not facts.locks and facts.qual != "<module>":
            return
        # transitive acquisition closure per method (intra-class calls)
        closure: Dict[str, Dict[str, int]] = {}

        def close(m: str, stack: Set[str]) -> Dict[str, int]:
            if m in closure:
                return closure[m]
            if m in stack:
                return {}
            stack.add(m)
            out = dict(facts.acquires.get(m, {}))
            for caller, callee, _held, line in facts.self_calls:
                if caller != m:
                    continue
                for attr in close(callee, stack):
                    out.setdefault(attr, line)
            stack.discard(m)
            closure[m] = out
            return out

        methods = set(facts.acquires) | \
            {c[0] for c in facts.self_calls} | \
            {c[1] for c in facts.self_calls}
        for m in methods:
            close(m, set())

        edges: List[_Edge] = list(facts.edges)
        for caller, callee, held_attrs, line in facts.self_calls:
            if not held_attrs:
                continue
            for attr in close(callee, set()):
                info = facts.locks.get(attr) or \
                    self.module_locks.get(attr)
                if info is None:
                    continue
                for h in held_attrs:
                    hinfo = facts.locks.get(h) or \
                        self.module_locks.get(h)
                    if hinfo is None:
                        continue
                    edges.append(_Edge(hinfo.lock_id, info.lock_id,
                                       line,
                                       f"{facts.qual}.{caller}"))

        # persist the closure edges: lock_graph() (the lockassert union
        # check) must see call-chain orders too, not just direct
        # with-nesting — else a dynamic B->A against a static
        # call-chain A->B would pass the acyclicity assert
        facts.edges = edges
        self._emit_cycles(facts, edges)

    def _emit_cycles(self, facts: _ClassFacts,
                     edges: List[_Edge]) -> None:
        by_id = {i.lock_id: i for i in facts.locks.values()}
        by_id.update({i.lock_id: i for i in self.module_locks.values()})
        graph: Dict[str, Dict[str, _Edge]] = {}
        emitted: Set[Tuple[str, ...]] = set()
        for e in edges:
            if e.src == e.dst:
                info = by_id.get(e.src)
                if info is not None and info.kind in _REENTRANT_KINDS:
                    continue   # RLock/Condition re-entry is legal
                key = (e.src,)
                if key in emitted:
                    continue
                emitted.add(key)
                self.findings.append(Finding(
                    rule="lock-order-inversion",
                    severity=CONCURRENCY_RULES[
                        "lock-order-inversion"][0],
                    path="", line=e.line, context=e.context,
                    message=(
                        f"non-reentrant lock `{e.src}` re-acquired "
                        "while already held (through an intra-class "
                        "call chain) — the thread deadlocks against "
                        "itself; use an RLock, or split the locked "
                        "face from the unlocked `_impl`")))
                continue
            graph.setdefault(e.src, {}).setdefault(e.dst, e)

        # cycle detection (DFS, canonicalized rotation for dedup)
        def find_cycle(start: str) -> Optional[List[str]]:
            stack = [(start, [start])]
            seen: Set[str] = set()
            while stack:
                node, path = stack.pop()
                for nxt in graph.get(node, {}):
                    if nxt == start:
                        return path
                    if nxt in seen:
                        continue
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
            return None

        for start in sorted(graph):
            cyc = find_cycle(start)
            if not cyc:
                continue
            canon = tuple(sorted(cyc))
            if canon in emitted:
                continue
            emitted.add(canon)
            first = graph[cyc[0]][cyc[1] if len(cyc) > 1 else cyc[0]] \
                if len(cyc) > 1 else None
            ring = " -> ".join(cyc + [cyc[0]])
            e = first or next(iter(graph[cyc[0]].values()))
            self.findings.append(Finding(
                rule="lock-order-inversion",
                severity=CONCURRENCY_RULES["lock-order-inversion"][0],
                path="", line=e.line, context=e.context,
                message=(
                    f"lock acquisition cycle {ring}: two threads "
                    "entering from opposite ends deadlock; impose one "
                    "global order (acquire in a fixed sequence) or "
                    "collapse to a single lock")))

    def _emit_unguarded_writes(self, facts: _ClassFacts) -> None:
        if not facts.locks:
            return
        by_attr: Dict[str, List[_Write]] = {}
        for w in facts.writes:
            by_attr.setdefault(w.attr, []).append(w)
        for attr, ws in sorted(by_attr.items()):
            guarded = [w for w in ws if w.guarded]
            if not guarded:
                continue
            bare = [w for w in ws
                    if not w.guarded
                    and w.method.split(".")[0] not in
                    ("__init__", "__new__")]
            if not bare:
                continue
            glock = sorted({lk for w in guarded for lk in w.locks})
            gsites = sorted({f"{w.method} (line {w.line})"
                             for w in guarded})[:2]
            for w in bare:
                self.findings.append(Finding(
                    rule="unguarded-shared-write",
                    severity=CONCURRENCY_RULES[
                        "unguarded-shared-write"][0],
                    path="", line=w.line,
                    context=f"{facts.qual}.{w.method}",
                    message=(
                        f"`self.{attr}` is written under {glock} in "
                        f"{', '.join(gsites)} but written BARE here — "
                        "a concurrent locked read-modify-write loses "
                        "one of the updates (the PR 10 seq-mint / "
                        "sent_since_lease class); take the same lock "
                        "here, or move the field out of the shared "
                        "plane")))

    def _emit_stale_decls(self) -> None:
        for line, toks in sorted(self.decls.by_line.items()):
            calls = self.callback_calls.get(line) or \
                self.callback_calls.get(line + 1)
            if calls is None:
                if line in self.consumed_decls or \
                        (line + 1) in self.callback_calls:
                    continue
                self.findings.append(Finding(
                    rule="callback-under-lock-contract",
                    severity=CONCURRENCY_RULES[
                        "callback-under-lock-contract"][0],
                    path="", line=line, context="",
                    message=(
                        f"stale `# holds-lock: "
                        f"{', '.join(sorted(toks))}` — no callback is "
                        "invoked under a lock on this line (or the "
                        "next): the declaration no longer matches the "
                        "code; delete it (the two-sided contract, like "
                        "shardflow's stale-replication-annotation)")))
                continue
            stale = toks - calls
            if stale:
                self.findings.append(Finding(
                    rule="callback-under-lock-contract",
                    severity=CONCURRENCY_RULES[
                        "callback-under-lock-contract"][0],
                    path="", line=line, context="",
                    message=(
                        f"stale `# holds-lock:` tokens "
                        f"{sorted(stale)} — the callback here runs "
                        f"under {sorted(calls) or '(no lock)'}; "
                        "declarations must name exactly the held "
                        "locks (delete the stale tokens)")))


# --------------------------------------------------------------------------
# public faces
# --------------------------------------------------------------------------

def analyze_source(source: str, path: str,
                   rules: Optional[Sequence[str]] = None
                   ) -> List[Finding]:
    findings = _FileAnalyzer(source, path).run()
    sup = Suppressions(source)
    lines = source.splitlines()
    wanted = set(rules) if rules else None
    out: List[Finding] = []
    for f in findings:
        if wanted is not None and f.rule not in wanted \
                and f.rule != "parse-error":
            continue
        if sup.suppressed(f.rule, f.line):
            continue
        f.path = path
        if 1 <= f.line <= len(lines):
            f.snippet = lines[f.line - 1].strip()
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def analyze_file(path: str,
                 rules: Optional[Sequence[str]] = None) -> List[Finding]:
    with open(path) as fh:
        return analyze_source(fh.read(), path, rules=rules)


_DEFAULT_EXCLUDES = ("__pycache__", ".git", "build", "dist", ".eggs")


def _iter_files(paths: Sequence[str],
                exclude: Sequence[str] = _DEFAULT_EXCLUDES) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d not in exclude]
            files.extend(os.path.join(dirpath, f)
                         for f in sorted(filenames) if f.endswith(".py"))
    return sorted(set(files))


def analyze_paths(paths: Sequence[str],
                  rules: Optional[Sequence[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for f in _iter_files(paths):
        findings.extend(analyze_file(f, rules=rules))
    return findings


def analyze_lock_surface(paths: Sequence[str]
                         ) -> Tuple[Dict[Tuple[str, int],
                                         Tuple[str, str]],
                                    Set[Tuple[str, str]]]:
    """ONE analysis pass over ``paths`` yielding both halves the
    runtime lock-assert needs: the creation-site table ``(abs path,
    line) -> (owner qualname, attr)`` and the static lock-order edge
    set ``(held lock id, acquired lock id)`` — intra-class call-chain
    closure and module-function edges included."""
    sites: Dict[Tuple[str, int], Tuple[str, str]] = {}
    edges: Set[Tuple[str, str]] = set()
    for fpath in _iter_files(paths):
        with open(fpath) as fh:
            source = fh.read()
        an = _FileAnalyzer(source, fpath)
        try:
            an.run()
        except RecursionError:   # pragma: no cover - absurd nesting
            continue
        ap = os.path.abspath(fpath)
        for info in an.module_locks.values():
            sites[(ap, info.line)] = ("<module>", info.attr)
        all_facts = list(an.classes)
        if an.mod_facts is not None:
            all_facts.append(an.mod_facts)
        kinds = {i.lock_id: i.kind for i in an.module_locks.values()}
        for facts in all_facts:
            kinds.update({i.lock_id: i.kind
                          for i in facts.locks.values()})
        for facts in all_facts:
            for info in facts.locks.values():
                sites[(ap, info.line)] = (facts.qual, info.attr)
            for e in facts.edges:
                if e.src == e.dst and \
                        kinds.get(e.src) in _REENTRANT_KINDS:
                    continue   # legal RLock/Condition re-entry (the
                    # PrefixCache insert->evict shape) is not an order
                edges.add((e.src, e.dst))
    return sites, edges


def lock_sites(paths: Sequence[str]
               ) -> Dict[Tuple[str, int], Tuple[str, str]]:
    """(abs path, creation line) -> (owner qualname, attr) for every
    tracked lock — the key the runtime lock-assert recorder uses to name
    the locks it observes (``analysis/lockassert.py``)."""
    return analyze_lock_surface(paths)[0]


def lock_graph(paths: Sequence[str]) -> Set[Tuple[str, str]]:
    """The static lock-order edge set over ``paths``: (held lock id,
    acquired lock id) pairs, intra-class call-chain closure and
    module-level-function edges included."""
    return analyze_lock_surface(paths)[1]


# --------------------------------------------------------------------------
# runner: python -m chainermn_tpu.analysis.concurrency
# --------------------------------------------------------------------------

def find_concurrency_baseline(start: Optional[str] = None
                              ) -> Optional[str]:
    from .findings import find_baseline

    d = start or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return find_baseline(d, filename=CONCURRENCY_BASELINE_FILENAME)


def main(argv: Optional[List[str]] = None) -> int:
    """Concurrency-lint runner.  Exit contract: 0 = clean modulo
    baseline, 1 = findings, 2 = unusable inputs (the
    ``check_perf_regression.py`` / ``lint_spmd.py`` contract)."""
    import argparse
    import json
    import sys

    from .baseline import BaselineGate

    p = argparse.ArgumentParser(
        prog="python -m chainermn_tpu.analysis.concurrency",
        description="Lock-discipline lint: lock-order cycles, unguarded "
                    "shared writes, blocking calls and undeclared "
                    "callbacks under locks (docs/ANALYSIS.md)")
    p.add_argument("paths", nargs="*", default=None)
    p.add_argument("--json", action="store_true")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--baseline", default=None)
    p.add_argument("--no-baseline", action="store_true")
    p.add_argument("--fix-baseline", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule, (sev, desc) in sorted(CONCURRENCY_RULES.items()):
            print(f"{rule:28s} {sev:8s} {desc}")
        return 0

    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = args.paths or [pkg_dir]
    missing = [q for q in paths if not os.path.exists(q)]
    if missing:
        print(f"error: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    if rules:
        unknown = set(rules) - set(CONCURRENCY_RULES)
        if unknown:
            print(f"error: unknown rule(s): "
                  f"{', '.join(sorted(unknown))}", file=sys.stderr)
            return 2

    findings = analyze_paths(paths, rules=rules)

    gate = BaselineGate.resolve(
        args.baseline, paths[0],
        CONCURRENCY_BASELINE_FILENAME, enabled=not args.no_baseline)
    # repo-relative paths for location-independent fingerprints (the
    # cli.py normalization, anchored at the baseline's directory)
    abs_paths = [os.path.abspath(q) for q in paths]
    common = os.path.commonpath(abs_paths)
    if os.path.isfile(common):
        common = os.path.dirname(common)
    root = common
    if gate.path:
        bl_dir = os.path.dirname(os.path.abspath(gate.path))
        if os.path.commonpath([bl_dir, common]) == bl_dir:
            root = bl_dir
    for f in findings:
        if f.path:
            f.path = os.path.relpath(os.path.abspath(f.path), root)

    err = gate.load()
    if err:
        print(f"error: {err}", file=sys.stderr)
        return 2

    if args.fix_baseline:
        def in_scope(entry) -> bool:
            if rules is not None and entry["rule"] not in rules \
                    and entry["rule"] != "parse-error":
                return False
            ap = os.path.normpath(os.path.join(root, entry["path"]))
            return any(ap == sp or ap.startswith(sp + os.sep)
                       for sp in abs_paths)

        gate.fix(findings, in_scope=in_scope,
                 default_target=os.path.join(
                     root, CONCURRENCY_BASELINE_FILENAME))
        return 0

    findings, accepted = gate.filter(findings)

    if args.json:
        print(json.dumps({
            "schema": "chainermn_tpu.concurrency_lint.v1",
            "baseline": (os.path.relpath(gate.path, root)
                         if gate.baseline is not None else None),
            "n_accepted_by_baseline": len(accepted),
            "findings": [f.to_dict() for f in findings],
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        sev: Dict[str, int] = {}
        for f in findings:
            sev[f.severity] = sev.get(f.severity, 0) + 1
        tally = ", ".join(f"{n} {s}" for s, n in sorted(sev.items())) \
            or "no findings"
        extra = (f" ({len(accepted)} accepted by baseline)"
                 if accepted else "")
        print(f"concurrency-lint: {tally}{extra} over "
              f"{len(paths)} path(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":   # pragma: no cover - python -m face
    import sys

    sys.exit(main())
