"""Model registry for the heterogeneous fleet (ISSUE 18 tentpole b).

One :class:`FleetRouter` fronts workers serving DIFFERENT model
variants: each worker carries a ``model_id`` (it rides the hello/lease
wire so the router learns it the same fenced way it learns queue
depth), requests may pin a variant, routing scores only matching
workers, and the KV index refuses cross-model slab claims
(``fleet_cache`` keys records by model).

The registry is pure host bookkeeping — params stay whatever the
caller built (numpy trees here; nothing in this module imports jax).
``generation`` is the WEIGHT generation: a rolling upgrade
(:func:`~.fleet.rolling_upgrade`) registers the same ``model_id`` at
``generation+1`` and installs it worker-by-worker with zero shed.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

DEFAULT_MODEL_ID = "default"


class ModelVariant:
    """One servable variant: id + params + geometry-bearing kwargs.

    ``worker_kwargs`` are the per-variant WorkerRuntime knobs (layer
    count and head_dim live inside ``params``' shapes; pool sizing like
    ``max_total``/``n_slots`` may differ per variant — a small variant
    affords more slots).
    """

    def __init__(self, model_id: str, params, *, head_dim: int,
                 generation: int = 1,
                 worker_kwargs: Optional[Dict[str, Any]] = None):
        if not model_id:
            raise ValueError("model_id must be a non-empty string")
        if int(generation) < 1:
            raise ValueError(f"generation must be >= 1, "
                             f"got {generation}")
        self.model_id = str(model_id)
        self.params = params
        self.head_dim = int(head_dim)
        self.generation = int(generation)
        self.worker_kwargs = dict(worker_kwargs or {})

    def __repr__(self) -> str:
        return (f"ModelVariant({self.model_id!r}, "
                f"gen={self.generation}, head_dim={self.head_dim})")


class ModelRegistry:
    """``model_id`` → newest :class:`ModelVariant`; older generations
    are kept addressable (``get(mid, generation=1)``) so an upgrade can
    compare old/new on the same pinned request.

    Thread-safe: ``rolling_upgrade`` registers generation N+1 from the
    upgrade thread while router scoring / fleet-build threads resolve
    variants concurrently — every ``_variants`` touch happens under
    ``_lock`` (the dict-of-dicts ``setdefault``+insert in
    :meth:`register` is a two-step write; unguarded it races a
    same-model ``get``'s ``max(gens)`` mid-insert)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._variants: Dict[str, Dict[int, ModelVariant]] = {}

    def register(self, variant: ModelVariant) -> ModelVariant:
        with self._lock:
            gens = self._variants.setdefault(variant.model_id, {})
            if variant.generation in gens:
                raise ValueError(
                    f"model {variant.model_id!r} generation "
                    f"{variant.generation} already registered — weight "
                    f"generations are immutable once published")
            gens[variant.generation] = variant
            return variant

    def get(self, model_id: str,
            generation: Optional[int] = None) -> ModelVariant:
        with self._lock:
            gens = self._variants.get(str(model_id))
            if not gens:
                known = sorted(self._variants)
                raise KeyError(f"unknown model_id {model_id!r}; "
                               f"registered: {known}")
            g = max(gens) if generation is None else int(generation)
            if g not in gens:
                raise KeyError(f"model {model_id!r} has no generation "
                               f"{g} (has {sorted(gens)})")
            return gens[g]

    def latest_generation(self, model_id: str) -> int:
        return self.get(model_id).generation

    def ids(self) -> List[str]:
        with self._lock:
            return sorted(self._variants)

    def __contains__(self, model_id: str) -> bool:
        with self._lock:
            return str(model_id) in self._variants

    def __len__(self) -> int:
        with self._lock:
            return len(self._variants)
