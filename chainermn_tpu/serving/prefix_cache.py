"""Radix-trie prefix cache: shared prompt prefixes reuse KV slots.

Production prompt traffic is prefix-heavy — the same system prompt (or
the same conversation history) fronts thousands of requests — and the
engine's prefill recomputes it every time.  This module is the
SGLang-RadixAttention idea adapted to the repo's slot-granular pool
(``cache_pool.py``): finished requests DONATE their slot to the cache
instead of freeing it, a compressed radix trie indexes the token
sequences those slots hold, and a new request's prompt is matched
against the trie for its longest cached prefix.  On a hit the engine
copies the cached slot's K/V rows into the request's own slot (ONE
compiled slab-copy program, ``DecodeEngine.copy_prefix``) and only the
un-cached suffix is computed — the shared prefix is never re-prefilled.

Why one slot can serve EVERY prefix of its sequence: causal attention
makes row ``i`` of a slot's K/V depend only on tokens ``[0, i]``, so a
slot holding the K/V of sequence ``S`` holds, in rows ``[0, k)``, the
exact K/V of any prefix ``S[:k]``.  The trie therefore needs no
per-token granularity bookkeeping — matching walks edges and any entry
below the deepest matched point supplies the slot.

Matches are capped at ``len(prompt) - 1``: the FIRST GENERATED token
comes from the last prompt position's hidden state, which is not
cached — at least one prompt token always runs through the engine, and
its tick output IS the first token (token-exactness needs no replay).

Lifecycle and refcounts (the ``cache_pool.SlotAllocator`` extension):

* **donate** — a finishing request's slot moves busy → cached (rc=0)
  keyed by ``prompt + generated[:-1]`` (every K/V row actually written:
  each decode tick writes the CONSUMED token's row, and the final
  emitted token was never consumed).  Sequences already covered by an
  existing entry are dropped (dedup); entries subsumed by a longer
  donation are evicted when unpinned.
* **retain/release** — a request admitted on a hit pins its source
  entry for its whole lifetime; all refcounts return to zero at drain
  (the fuzz invariant) and a pinned entry can never be evicted under it.
* **evict** — admission pressure reclaims cached slots LRU-first among
  rc==0 entries; the cache is scavengeable capacity, never a reserve
  that could starve decoding.

Pure host Python, jax-free (fuzzable without a backend); the device
copy lives in ``engine.py`` and the policy wiring in ``frontend.py``.
See docs/SERVING.md "Router, prefix cache & admission".
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional, Tuple


class PrefixEntry:
    """One cached sequence: ``seq[:length]``'s K/V lives in ``slot``."""

    _ids = itertools.count()

    def __init__(self, seq: Tuple[int, ...], slot: int, length: int):
        self.id = next(PrefixEntry._ids)
        self.seq = tuple(int(t) for t in seq)
        self.slot = int(slot)
        self.length = int(length)      # valid K/V rows: [0, length)
        self.node: Optional["_Node"] = None   # terminal trie node
        self.last_used = 0             # logical LRU clock

    def __repr__(self):
        return (f"PrefixEntry(id={self.id}, slot={self.slot}, "
                f"len={self.length})")


class _Node:
    """Compressed-trie node: ``edges`` maps first token → (label,
    child); at most one entry terminates at a node."""

    __slots__ = ("edges", "entry", "parent")

    def __init__(self, parent: Optional["_Node"] = None):
        self.edges: Dict[int, Tuple[Tuple[int, ...], "_Node"]] = {}
        self.entry: Optional[PrefixEntry] = None
        self.parent = parent


def _locked(fn):
    """Hold the cache's reentrant lock across a public method (trie
    reads race donations/evictions from other threads otherwise)."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, *a, **k):
        with self._lock:
            return fn(self, *a, **k)
    return wrapper


def _common_len(a, b) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class PrefixCache:
    """Radix-trie index over donated read-only prefix slots.

    The cache OWNS no device memory: slots belong to the pool's
    allocator and move busy → cached → free through the
    ``SlotAllocator.cache/retain/unretain/uncache`` faces the frontend
    wires in via ``retain_slot``/``release_slot``/``evict_slot``
    callbacks.  Keeping it callback-based leaves the trie and refcount
    policy standalone-fuzzable (tests/test_serving_router.py).

    ``min_prefix_len``: hits shorter than this are treated as misses —
    copying a 1-token prefix saves one embedding lookup and costs a
    slab copy; the knob keeps the trade explicit.
    """

    def __init__(self, retain_slot=None, release_slot=None,
                 evict_slot=None, min_prefix_len: int = 2,
                 on_insert=None, on_evict=None):
        # one reentrant lock around every trie/entry mutation AND read:
        # with Replica.start() the engine's driver thread donates and
        # evicts while the router's caller thread peeks for affinity —
        # an unlocked dict iteration mid-edge-split would raise (or
        # match an entry being evicted).  Host-side microseconds; the
        # device path never holds it.  RLock because insert() evicts
        # subsumed entries through the same public face.
        self._lock = threading.RLock()
        self._root = _Node()
        self._entries: Dict[int, PrefixEntry] = {}      # id -> entry
        self._by_slot: Dict[int, PrefixEntry] = {}      # slot -> entry
        self._pins: Dict[int, int] = {}                 # entry id -> rc
        self._clock = 0
        self.min_prefix_len = max(int(min_prefix_len), 1)
        self._retain_slot = retain_slot or (lambda slot: None)
        self._release_slot = release_slot or (lambda slot: None)
        self._evict_slot = evict_slot or (lambda slot: None)
        # lifecycle hooks (ISSUE 12): ``on_insert(entry)`` after a
        # donation lands, ``on_evict(entry)`` BEFORE the slot is handed
        # back (the spill tier must pack the rows while they still
        # exist; the fleet worker announces both over the mailbox wire
        # so the router's global index tracks this cache).  Hooks run
        # UNDER the cache lock by design — the pre-evict spill has to
        # read the slab before the slot frees, and that ordering only
        # exists inside the eviction.  The cost is bounded (one slab's
        # device→host copy + small lane writes) but it does extend the
        # lock hold on the eviction path; hooks must never take a lock
        # that can be held while calling INTO this cache (deadlock).
        self.on_insert = on_insert
        self.on_evict = on_evict
        # counters (the frontend's metrics() / introspect surface)
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0
        self.insertions = 0
        self.rejected_insertions = 0
        self.evictions = 0

    # ---- matching ----
    def _walk(self, seq) -> Tuple["_Node", int, Optional["_Node"]]:
        """Deepest match of ``seq`` along the trie: returns ``(node,
        matched_len, partial_child)`` where ``partial_child`` is the
        edge child when the walk died MID-edge (its subtree still
        shares the matched prefix)."""
        node, depth = self._root, 0
        while depth < len(seq):
            edge = node.edges.get(seq[depth])
            if edge is None:
                return node, depth, None
            label, child = edge
            k = _common_len(label, seq[depth:])
            depth += k
            if k < len(label):
                return node, depth, child
            node = child
        return node, depth, None

    def _subtree_entry(self, node: "_Node") -> Optional[PrefixEntry]:
        """Most-recently-used entry in ``node``'s subtree (entry count
        is bounded by n_slots, so the DFS is trivially cheap)."""
        best: Optional[PrefixEntry] = None
        stack = [node]
        while stack:
            n = stack.pop()
            if n.entry is not None and (best is None
                                        or n.entry.last_used
                                        > best.last_used):
                best = n.entry
            stack.extend(child for _, child in n.edges.values())
        return best

    @_locked
    def match(self, prompt) -> Tuple[Optional[PrefixEntry], int]:
        """Longest cached prefix of ``prompt``: ``(entry, match_len)``
        with ``entry.seq[:match_len] == prompt[:match_len]`` and K/V
        rows ``[0, match_len)`` valid in ``entry.slot`` — or
        ``(None, 0)``.  Capped at ``len(prompt) - 1`` (the last prompt
        token must run live to produce the first generated token) and
        at the entry's own valid length."""
        prompt = tuple(int(t) for t in prompt)
        if len(prompt) < 2:
            self.misses += 1
            return None, 0
        node, depth, partial = self._walk(prompt[:len(prompt) - 1])
        entry = self._subtree_entry(partial if partial is not None
                                    else node)
        if entry is None or depth < self.min_prefix_len:
            self.misses += 1
            return None, 0
        match_len = min(depth, entry.length, len(prompt) - 1)
        if match_len < self.min_prefix_len:
            self.misses += 1
            return None, 0
        self.hits += 1
        self.tokens_reused += match_len
        self._clock += 1
        entry.last_used = self._clock
        return entry, match_len

    @_locked
    def peek_len(self, prompt) -> int:
        """Length the next :meth:`match` of ``prompt`` would return,
        WITHOUT touching hit/miss counters or the LRU clock — the
        router's affinity scorer probes every replica and must not
        distort the stats or eviction order of the ones it rejects."""
        prompt = tuple(int(t) for t in prompt)
        if len(prompt) < 2:
            return 0
        node, depth, partial = self._walk(prompt[:len(prompt) - 1])
        entry = self._subtree_entry(partial if partial is not None
                                    else node)
        if entry is None or depth < self.min_prefix_len:
            return 0
        match_len = min(depth, entry.length, len(prompt) - 1)
        return match_len if match_len >= self.min_prefix_len else 0

    @_locked
    def pin_covering(self, seq) -> Optional[PrefixEntry]:
        """Entry whose K/V rows COVER ``seq`` exactly (``entry.seq[:
        len(seq)] == seq`` and ``entry.length >= len(seq)``), RETAINED
        atomically — the remote-pull serving face (ISSUE 12): the owner
        must pin the entry across the pack so a concurrent eviction
        cannot free the slot mid-read.  Returns None (no pin taken)
        when nothing covers the sequence anymore — the announced claim
        went stale and the pull degrades to re-prefill."""
        seq = tuple(int(t) for t in seq)
        if not seq:
            return None
        node, depth, partial = self._walk(seq)
        if depth < len(seq):
            return None
        entry = self._subtree_entry(partial if partial is not None
                                    else node)
        if entry is None or entry.length < len(seq) \
                or entry.seq[: len(seq)] != seq:
            return None
        self.retain(entry)
        return entry

    # ---- pinning (request lifetime) ----
    @_locked
    def retain(self, entry: PrefixEntry) -> None:
        if entry.id not in self._entries:
            raise ValueError(f"unknown entry {entry!r}")
        self._pins[entry.id] = self._pins.get(entry.id, 0) + 1
        self._retain_slot(entry.slot)

    @_locked
    def release(self, entry: PrefixEntry) -> None:
        rc = self._pins.get(entry.id, 0)
        if rc <= 0:
            raise ValueError(f"refcount underflow on {entry!r}")
        if rc == 1:
            self._pins.pop(entry.id)
        else:
            self._pins[entry.id] = rc - 1
        self._release_slot(entry.slot)

    @_locked
    def refcount(self, entry: PrefixEntry) -> int:
        return self._pins.get(entry.id, 0)

    # ---- insertion (donation) ----
    @_locked
    def insert(self, seq, slot: int, length: int
               ) -> Optional[PrefixEntry]:
        """Index ``seq[:length]``'s K/V (already in ``slot``) — or
        return None when the donation adds nothing: an existing entry
        already covers the sequence (dedup), or it is too short to ever
        produce a usable hit.  The CALLER keeps slot ownership on
        rejection (and releases it to the free list)."""
        seq = tuple(int(t) for t in seq)[: int(length)]
        if len(seq) < self.min_prefix_len:
            self.rejected_insertions += 1
            return None
        node, depth, partial = self._walk(seq)
        if depth == len(seq):
            # every entry in the subtree below the matched point passes
            # through all of seq — rows [0, len(seq)) of its slot
            # already hold this exact K/V, so the donation adds nothing
            covering = self._subtree_entry(
                partial if partial is not None else node)
            if covering is not None:
                self.rejected_insertions += 1
                return None
        entry = PrefixEntry(seq, slot, len(seq))
        self._clock += 1
        entry.last_used = self._clock
        self._insert_node(entry)
        self._entries[entry.id] = entry
        self._by_slot[slot] = entry
        self.insertions += 1
        if self.on_insert is not None:
            self.on_insert(entry)   # holds-lock: _lock
        # a strictly-shorter entry whose seq prefixes the new one is
        # subsumed: every hit it could serve, the new entry serves
        # better.  Evict the unpinned ones now (their slot frees up).
        for other in list(self._entries.values()):
            if other.id != entry.id and other.length < entry.length \
                    and entry.seq[: other.length] == other.seq \
                    and self._pins.get(other.id, 0) == 0:
                self.evict_entry(other)
        return entry

    def _insert_node(self, entry: PrefixEntry) -> None:
        seq = entry.seq
        node, depth = self._root, 0
        while True:
            if depth == len(seq):
                entry.node = node
                if node.entry is None:
                    node.entry = entry
                # else: duplicate terminal (same seq twice) — keep the
                # older one as terminal; both remain in _entries
                return
            edge = node.edges.get(seq[depth])
            if edge is None:
                child = _Node(parent=node)
                node.edges[seq[depth]] = (seq[depth:], child)
                child.entry = entry
                entry.node = child
                return
            label, child = edge
            k = _common_len(label, seq[depth:])
            if k == len(label):
                node, depth = child, depth + k
                continue
            # split the edge at k: node -[label[:k]]-> mid -[label[k:]]->
            mid = _Node(parent=node)
            node.edges[seq[depth]] = (label[:k], mid)
            mid.edges[label[k]] = (label[k:], child)
            child.parent = mid
            node, depth = mid, depth + k

    # ---- eviction ----
    @_locked
    def evictable_count(self) -> int:
        return sum(1 for e in self._entries.values()
                   if self._pins.get(e.id, 0) == 0)

    @_locked
    def evict_entry(self, entry: PrefixEntry) -> int:
        """Remove one entry and hand its slot back via ``evict_slot``;
        returns the freed slot.  Pinned entries are a hard error (the
        allocator would refuse the uncache anyway)."""
        if self._pins.get(entry.id, 0) > 0:
            raise ValueError(f"{entry!r} is pinned; refusing eviction")
        if self.on_evict is not None:
            # BEFORE the slot goes back: the spill tier packs the rows
            # while the slot still holds them (evict_slot resets pos)
            self.on_evict(entry)   # holds-lock: _lock
        del self._entries[entry.id]
        self._by_slot.pop(entry.slot, None)
        node = entry.node
        if node is not None and node.entry is entry:
            node.entry = None
            self._prune(node)
        entry.node = None
        self.evictions += 1
        self._evict_slot(entry.slot)
        return entry.slot

    @_locked
    def evict_lru(self) -> Optional[int]:
        """Evict the least-recently-used rc==0 entry; returns its slot
        (for the admission path to acquire) or None when everything is
        pinned or the cache is empty."""
        victims = [e for e in self._entries.values()
                   if self._pins.get(e.id, 0) == 0]
        if not victims:
            return None
        victim = min(victims, key=lambda e: (e.last_used, e.id))
        return self.evict_entry(victim)

    def _prune(self, node: "_Node") -> None:
        """Drop entry-less leaf chains so the trie stays proportional
        to what it indexes."""
        while node is not None and node is not self._root \
                and node.entry is None and not node.edges:
            parent = node.parent
            for tok, (label, child) in list(parent.edges.items()):
                if child is node:
                    del parent.edges[tok]
                    break
            node = parent

    # ---- introspection ----
    @property
    @_locked
    def n_entries(self) -> int:
        return len(self._entries)

    @_locked
    def entries(self) -> List[PrefixEntry]:
        return list(self._entries.values())

    @_locked
    def total_refcount(self) -> int:
        return sum(self._pins.values())

    @_locked
    def stats(self) -> Dict[str, float]:
        return {
            "entries": float(len(self._entries)),
            "pinned": float(len(self._pins)),
            "hits": float(self.hits),
            "misses": float(self.misses),
            "tokens_reused": float(self.tokens_reused),
            "insertions": float(self.insertions),
            "evictions": float(self.evictions),
        }

    @_locked
    def check_invariants(self) -> None:
        """Entry/trie/slot agreement: every entry reachable, one slot
        per entry, pins only on live entries, trie terminals match."""
        slots = [e.slot for e in self._entries.values()]
        assert len(set(slots)) == len(slots), f"slot aliasing: {slots}"
        assert set(self._by_slot) == set(slots)
        for eid in self._pins:
            assert eid in self._entries, (eid, self._entries)
            assert self._pins[eid] > 0
        for e in self._entries.values():
            node, depth, partial = self._walk(e.seq)
            assert depth == len(e.seq) and partial is None, e
            sub = self._subtree_entry(node)
            assert sub is not None, e
