"""Compiled per-tick decode programs over the slot pool.

The closed-batch generator (``parallel/decode.py::lm_generate``) fuses
prefill + a ``lax.scan`` over new tokens into ONE program — great for
offline batches, useless for serving: nothing can join or leave until
the whole scan retires.  This engine splits the same numerics into two
programs driven from the host, one tick at a time:

* **prefill_into_slot** — full-prompt forward (``lm_prefill``), greedy
  first token from the LAST REAL prompt position, and a
  ``dynamic_update_slice`` of the prompt's K/V slab into the target
  slot's rows of the pool.  Compiled once per padded prompt length.
  ``prefill_bucket > 1`` right-pads prompts to bucket multiples to
  bound the number of compiles under mixed lengths: causal attention
  never lets a real token see a pad, and pad rows in the cache sit
  above ``pos`` where the per-row mask — and the occupant's own later
  writes — keep them unreachable.  The default is 1 (no padding):
  padding is mathematically exact but changes the attention reduction's
  length, which can reassociate float sums and flip a machine-eps
  argmax tie, and the engine's contract is TOKEN-exactness against
  ``lm_generate``.
* **tick** — one token for EVERY slot (``lm_decode_tick`` with the
  per-row position vector + ``_greedy_token``), caches appended in
  place per row.  Compiled ONCE for the pool's lifetime: admission and
  eviction change only the host-side position/token vectors, never the
  program.

Token-exactness vs ``lm_generate`` row-by-row is a test invariant
(tests/test_serving.py): both paths run the identical per-row ops — the
batch dimension and the pool's extra cache rows are masked out with
exact zeros, so a request decoded in a shared pool emits bit-identical
tokens to the same request decoded alone.

TP composes exactly as in the closed-batch path: params stay in
``transformer_lm_specs`` layout, pool caches are sharded ``P(None,
None, model)`` (each chip holds its local heads' columns), and the
greedy pick is the (pmax, pmin) pair — the full logits never gather.
Inactive slots still burn FLOPs (their output is discarded); a
real-traffic engine keeps the pool near-full, which is the scheduler's
job.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class DecodeEngine:
    """Device half of the serving engine: owns the sharded params and the
    compiled prefill/tick programs; the :class:`~chainermn_tpu.serving
    .cache_pool.CachePool` owns the buffers the programs thread through.

    ``params`` are GLOBAL arrays in ``init_tp_transformer_lm`` layout;
    ``mesh`` must carry ``axis_name`` (default: a fresh 1-D mesh over
    all local devices, like ``make_lm_generator``).
    """

    def __init__(self, params, pool, mesh=None, axis_name: str = "model",
                 *, head_dim: int, prefill_bucket: int = 1):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from .._compat import shard_map
        from ..parallel.decode import _kv_heads
        from ..parallel.transformer import transformer_lm_specs

        if mesh is None:
            from ..topology import make_mesh
            mesh = make_mesh(axis_name=axis_name)
        self.mesh = mesh
        self.axis_name = axis_name
        self.head_dim = int(head_dim)
        self.pool = pool
        self.prefill_bucket = max(int(prefill_bucket), 1)
        self.n_kv_heads = _kv_heads(params, head_dim)
        self.rope = "pos_embed" not in params
        self.max_positions = (None if self.rope
                              else int(params["pos_embed"].shape[0]))
        self._specs = transformer_lm_specs(params, axis_name)
        self._params = jax.tree_util.tree_map(
            lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
            params, self._specs)
        self._shard_map = shard_map
        self._P = P
        self._cache_specs = [(pool.cache_spec, pool.cache_spec)
                             for _ in range(pool.n_layers)]
        self._prefill_progs = {}   # padded prompt length -> compiled fn
        self._tick_prog = self._build_tick()
        self._prefix_copy_prog = None   # built lazily on first hit
        # program/compile accounting (flight bundles + /statusz report
        # these: a growing prefill-family or a tick_calls≈compile count
        # mismatch is the recompile postmortem signal)
        self.prefill_compiles = 0
        self.prefill_calls = 0
        self.tick_calls = 0
        self.prefix_copies = 0

    # ---- program builders ----
    def _build_tick(self):
        import jax

        from ..parallel.decode import _next_token, lm_decode_tick

        axis, head_dim = self.axis_name, self.head_dim
        P = self._P

        def tick_inner(params, caches, tokens, pos, keys, temps):
            h_last, new_caches = lm_decode_tick(
                params, tokens, caches, pos, head_dim=head_dim,
                axis_name=axis)
            # the consumed token sits at row ``pos``; the selected next
            # token is position ``pos + 1`` — lm_generate's step_pos
            # salt, so sampling stays token-exact per request
            nxt = _next_token(params["embed"], h_last, axis, keys, temps,
                              pos + 1)
            return nxt, new_caches

        return jax.jit(self._shard_map(
            tick_inner, mesh=self.mesh,
            in_specs=(self._specs, self._cache_specs, P(), P(), P(), P()),
            out_specs=(P(), self._cache_specs)))

    def _build_prefill(self, s_pad: int):
        import jax

        from ..parallel.decode import _next_token, lm_prefill

        axis, head_dim = self.axis_name, self.head_dim
        P = self._P

        def prefill_inner(params, caches, prompt, s_real, slot, key, temp):
            # slab caches sized to the padded prompt only; pads are above
            # every real row and never read back (causal + pos mask)
            h, slabs = lm_prefill(params, prompt, s_pad, head_dim=head_dim,
                                  axis_name=axis)
            h_last = jax.lax.dynamic_index_in_dim(h, s_real - 1, axis=1,
                                                  keepdims=False)
            # first generated token = position s_real (lm_generate's
            # first = logits_next(h[:, -1], s_p) salt)
            tok = _next_token(params["embed"], h_last, axis, key[None],
                              temp[None], s_real[None])
            new_caches = []
            for (kc, vc), (ks, vs) in zip(caches, slabs):
                start = (slot, 0, 0)
                new_caches.append(
                    (jax.lax.dynamic_update_slice(kc, ks.astype(kc.dtype),
                                                  start),
                     jax.lax.dynamic_update_slice(vc, vs.astype(vc.dtype),
                                                  start)))
            return tok, new_caches

        return jax.jit(self._shard_map(
            prefill_inner, mesh=self.mesh,
            in_specs=(self._specs, self._cache_specs, P(), P(), P(), P(),
                      P()),
            out_specs=(P(), self._cache_specs)))

    def _build_prefix_copy(self):
        """Slot-to-slot K/V slab copy — the prefix cache's copy-on-
        extend device half (ISSUE 7).  Copies the ENTIRE src slot row
        into dst for every layer: rows beyond the matched prefix length
        carry stale K/V, but they are unreachable by the standard
        above-``pos`` masking argument and the next occupant's writes
        land below its own pos first — so the program needs no length
        operand and compiles ONCE for the pool's lifetime (src/dst are
        tiny traced scalars, never static)."""
        import jax

        def copy_inner(caches, src, dst):
            new_caches = []
            for kc, vc in caches:
                k_row = jax.lax.dynamic_index_in_dim(kc, src, axis=0,
                                                     keepdims=True)
                v_row = jax.lax.dynamic_index_in_dim(vc, src, axis=0,
                                                     keepdims=True)
                start = (dst, 0, 0)
                new_caches.append(
                    (jax.lax.dynamic_update_slice(kc, k_row, start),
                     jax.lax.dynamic_update_slice(vc, v_row, start)))
            return new_caches

        P = self._P
        return jax.jit(self._shard_map(
            copy_inner, mesh=self.mesh,
            in_specs=(self._cache_specs, P(), P()),
            out_specs=self._cache_specs))

    # ---- serving faces (host-driven, one call per engine iteration) ----
    def padded_len(self, s_real: int) -> int:
        b = self.prefill_bucket
        return ((int(s_real) + b - 1) // b) * b

    def prefill_into_slot(self, prompt_tokens, slot: int, *,
                          rng=None, temperature: float = 0.0) -> int:
        """Prefill ``prompt_tokens (S,)`` into ``slot``: writes the K/V
        slab into the pool's caches, sets ``pool.pos[slot]``, and returns
        the FIRST generated token — greedy at ``temperature <= 0``,
        Gumbel-sampled with the request's ``rng`` key otherwise (the
        ``lm_generate`` sampling contract, ISSUE 9).  One compile per
        padded length, cached; rng/temperature are traced operands, so
        greedy and sampled requests share the program."""
        import jax.numpy as jnp

        prompt = np.asarray(prompt_tokens, np.int32).reshape(1, -1)
        s_real = prompt.shape[1]
        s_pad = self.padded_len(s_real)
        if s_pad > self.pool.max_total:
            raise ValueError(
                f"padded prompt length {s_pad} exceeds pool max_total "
                f"{self.pool.max_total}")
        if self.max_positions is not None and s_pad > self.max_positions:
            raise ValueError(
                f"padded prompt length {s_pad} exceeds the learned "
                f"pos_embed max_len {self.max_positions}")
        if s_pad > s_real:
            prompt = np.pad(prompt, ((0, 0), (0, s_pad - s_real)))
        prog = self._prefill_progs.get(s_pad)
        if prog is None:
            prog = self._prefill_progs[s_pad] = self._build_prefill(s_pad)
            self.prefill_compiles += 1
            from ..observability import flight as _flight
            _flight.note("compile", program="serving_prefill",
                         padded_len=s_pad,
                         family_size=len(self._prefill_progs))
        self.prefill_calls += 1
        key = (np.zeros(2, np.uint32) if rng is None
               else np.asarray(rng, np.uint32).reshape(2))
        tok, self.pool.caches = prog(
            self._params, self.pool.caches, jnp.asarray(prompt),
            jnp.int32(s_real), jnp.int32(slot), jnp.asarray(key),
            jnp.float32(temperature))
        self.pool.pos[slot] = s_real
        return int(np.asarray(tok)[0])

    def copy_prefix(self, src_slot: int, dst_slot: int,
                    prefix_len: int) -> None:
        """Copy-on-extend entry: clone ``src_slot``'s K/V slab into
        ``dst_slot`` and set ``pool.pos[dst_slot] = prefix_len`` so the
        occupant's next write lands at the first un-cached position.
        The source slot is READ-ONLY shared state (refcounted by the
        prefix cache); jax arrays are immutable, so the 'copy' is a
        functional update producing new pool caches — the cached rows
        can never be corrupted by the reader.  One compiled program for
        the pool's lifetime (asserted by the ``serving.prefix_copy``
        analysis entry point)."""
        import jax.numpy as jnp

        if not (0 < int(prefix_len) <= self.pool.max_total):
            raise ValueError(
                f"prefix_len {prefix_len} out of range (0, "
                f"{self.pool.max_total}]")
        if self._prefix_copy_prog is None:
            self._prefix_copy_prog = self._build_prefix_copy()
            from ..observability import flight as _flight
            _flight.note("compile", program="serving_prefix_copy")
        self.prefix_copies += 1
        self.pool.caches = self._prefix_copy_prog(
            self.pool.caches, jnp.int32(src_slot), jnp.int32(dst_slot))
        self.pool.pos[dst_slot] = int(prefix_len)

    def tick(self, last_tokens: np.ndarray, keys=None,
             temps=None) -> np.ndarray:
        """One decode tick for ALL slots: consume ``last_tokens
        (n_slots,)`` at the pool's per-slot positions, append K/V in
        place, advance every position, and return the next token per
        slot (the caller keeps only the active rows).  ``keys (n_slots,
        2) uint32`` / ``temps (n_slots,)`` carry each slot's request rng
        and temperature (ISSUE 9 sampling plumbing); None = all-greedy
        (dummy keys, never consumed)."""
        import jax.numpy as jnp

        self.tick_calls += 1
        tokens = jnp.asarray(np.array(last_tokens, np.int32, copy=True))
        # COPY at the jax boundary: on CPU ``jnp.asarray`` may zero-copy
        # alias the host buffer, and dispatch is ASYNC — an in-place
        # ``pos += 1`` below would race the still-executing tick (seen as
        # a repeated first token under cold-compile latency).
        pos = jnp.asarray(np.array(self.pool.pos, np.int32, copy=True))
        if keys is None:
            keys = np.zeros((self.pool.n_slots, 2), np.uint32)
        if temps is None:
            temps = np.zeros(self.pool.n_slots, np.float32)
        nxt, self.pool.caches = self._tick_prog(
            self._params, self.pool.caches, tokens, pos,
            jnp.asarray(np.array(keys, np.uint32, copy=True)),
            jnp.asarray(np.array(temps, np.float32, copy=True)))
        self.pool.pos = self.pool.pos + 1  # out-of-place: never mutate a
        #                                    buffer jax might still read
        return np.asarray(nxt)
