"""Trace-driven workload engine: seeded, replayable serving scenarios
(ISSUE 18, ROADMAP item 4 — the planet-scale scenario plane).

Every serving bench section used to hand-roll its arrival loop
(``submit every k steps``, an inline diurnal phase table); real traffic
is diurnal, bursty, adversarial, and faulty, and none of those loops
could be replayed or cross-checked.  This module makes the WORKLOAD a
first-class artifact:

* **Generators** — pure host Python, jax-free, seeded: diurnal curves,
  flash crowds, prefix-sniping/long-prompt adversarial tenants, mixed
  deadline classes, and composed chaos (worker kill + burst + SIGSTOP
  zombie in one stream).  Same seed ⇒ byte-identical event stream
  (:func:`stream_digest` is the proof the tests and the bench gate on).
* **Event stream** — schema ``chainermn_tpu.scenario.v1``: one record
  per arrival (virtual time, tenant, priority, prompt SPEC, deadline)
  or fault injection.  Prompts ride as specs (seed + length + prefix
  group), not token lists: :func:`materialize_prompt` derives the exact
  tokens deterministically, so a 10⁶-request trace is a few MB and two
  replays of the same trace submit identical prompts.
* **Driver** — :func:`run_scenario` replays a stream in scaled
  wall-clock against a REAL fleet (:class:`~.fleet.FleetRouter` + its
  autoscale/tenancy/chaos planes as the system under test), applies
  the fault events to the live workers, and records the per-scenario
  SLO / shed / autoscale / degradation-rung matrix the bench gates.

The stream is deterministic; the REPLAY is wall-clock (scheduling
jitter, compile stalls) — which is exactly the split the robustness
arc needs: reproducible offered load, measured real behavior.

Fault events name workers by INDEX into the driver's runtime list:
``kill`` is the SIGKILL face (:meth:`~.worker.WorkerRuntime.kill` —
heartbeats stop dead), ``pause``/``resume`` the SIGSTOP/SIGCONT zombie
(beats silenced, then resumed under a fenced epoch — the zombie-fencing
plane refuses the corpse's writes and the breaker governs
re-admission).  Process fleets get the same actions as real signals.

See docs/SERVING.md "Scenario engine & heterogeneous fleet".
"""

from __future__ import annotations

import hashlib
import json
import random
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

#: Event-stream schema tag; every record carries it (receivers refuse
#: foreign streams the same way the worker lanes refuse foreign
#: mailboxes).
SCENARIO_SCHEMA = "chainermn_tpu.scenario.v1"

EVENT_KINDS = ("request", "fault")

#: Fault vocabulary: ``kill`` = SIGKILL (permanent silence), ``pause``/
#: ``resume`` = SIGSTOP/SIGCONT (the zombie drill: silence, then stale
#: writes under a fenced epoch).
FAULT_ACTIONS = ("kill", "pause", "resume")

#: The default diurnal curve (night → morning → PEAK+BURST → evening →
#: night): (phase name, requests, interarrival seconds) — the shape the
#: ``serving_autoscale`` bench section drove inline before ISSUE 18.
DIURNAL_PHASES: Tuple[Tuple[str, int, float], ...] = (
    ("night", 3, 0.05), ("morning", 10, 0.005),
    ("peak_burst", 20, 0.0), ("evening", 6, 0.02),
    ("night2", 3, 0.05))


def _stable_seed(*parts) -> int:
    """Deterministic 64-bit seed from arbitrary parts — NEVER Python's
    ``hash`` (randomized per process, which would break the same-seed ⇒
    same-stream contract across runs)."""
    h = hashlib.sha256("\x1f".join(str(p) for p in parts).encode())
    return int.from_bytes(h.digest()[:8], "big")


# ---------------------------------------------------------------------------
# events: construction + validation + canonical bytes
# ---------------------------------------------------------------------------

def request_event(t: float, *, tenant: Optional[str] = None,
                  priority: Optional[str] = None,
                  prompt_seed: int = 0, prompt_len: int = 8,
                  prefix_group: Optional[str] = None,
                  prefix_len: int = 0,
                  max_new_tokens: int = 8,
                  deadline_s: Optional[float] = None,
                  phase: Optional[str] = None) -> Dict[str, Any]:
    """One arrival record (``seq`` is assigned by :func:`finalize`)."""
    ev: Dict[str, Any] = {
        "schema": SCENARIO_SCHEMA, "kind": "request",
        "t": round(float(t), 9),
        "tenant": tenant, "priority": priority,
        "prompt": {"seed": int(prompt_seed), "len": int(prompt_len),
                   "prefix_group": prefix_group,
                   "prefix_len": int(prefix_len)},
        "max_new_tokens": int(max_new_tokens),
        "deadline_s": (None if deadline_s is None else float(deadline_s)),
    }
    if phase is not None:
        ev["phase"] = str(phase)
    return ev


def fault_event(t: float, action: str, target: int) -> Dict[str, Any]:
    """One fault-injection record: ``target`` indexes the driver's
    worker list (NOT a name — the stream must replay against any fleet
    of sufficient size)."""
    if action not in FAULT_ACTIONS:
        raise ValueError(f"fault action must be one of {FAULT_ACTIONS}, "
                         f"got {action!r}")
    return {"schema": SCENARIO_SCHEMA, "kind": "fault",
            "t": round(float(t), 9),
            "fault": {"action": str(action), "target": int(target)}}


def validate_event(ev: Dict[str, Any]) -> None:
    """Schema check one record; raises ``ValueError`` with the exact
    field that is wrong (the refuse-don't-guess lane discipline)."""
    if not isinstance(ev, dict):
        raise ValueError(f"event must be a dict, got {type(ev).__name__}")
    if ev.get("schema") != SCENARIO_SCHEMA:
        raise ValueError(f"refusing scenario event: schema "
                         f"{ev.get('schema')!r} != {SCENARIO_SCHEMA!r}")
    kind = ev.get("kind")
    if kind not in EVENT_KINDS:
        raise ValueError(f"event kind must be one of {EVENT_KINDS}, "
                         f"got {kind!r}")
    if not isinstance(ev.get("t"), (int, float)) or ev["t"] < 0:
        raise ValueError(f"event t must be a non-negative number, "
                         f"got {ev.get('t')!r}")
    if "seq" in ev and not isinstance(ev["seq"], int):
        raise ValueError(f"event seq must be an int, got {ev['seq']!r}")
    if kind == "request":
        spec = ev.get("prompt")
        if not isinstance(spec, dict):
            raise ValueError("request event needs a prompt spec dict")
        if int(spec.get("len", 0)) < 1:
            raise ValueError(f"prompt len must be >= 1, got "
                             f"{spec.get('len')!r}")
        if int(spec.get("prefix_len", 0)) < 0:
            raise ValueError("prompt prefix_len must be >= 0")
        if int(ev.get("max_new_tokens", 0)) < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{ev.get('max_new_tokens')!r}")
        dl = ev.get("deadline_s")
        if dl is not None and (not isinstance(dl, (int, float))
                               or dl <= 0):
            raise ValueError(f"deadline_s must be positive or None, "
                             f"got {dl!r}")
    else:
        fault = ev.get("fault")
        if not isinstance(fault, dict) \
                or fault.get("action") not in FAULT_ACTIONS \
                or not isinstance(fault.get("target"), int):
            raise ValueError(f"fault event needs "
                             f"{{action ∈ {FAULT_ACTIONS}, target: int}}, "
                             f"got {fault!r}")


def finalize(events: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Order a raw event list into a valid stream: stable sort by
    arrival time (ties keep construction order — the determinism the
    composed-chaos interleave test pins), assign ``seq``, validate
    every record."""
    out = sorted((dict(ev) for ev in events), key=lambda e: e["t"])
    for i, ev in enumerate(out):
        ev["seq"] = i
        validate_event(ev)
    return out


def merge(*streams: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Deterministic interleave of finalized streams: sort by
    ``(t, stream index, position)`` — byte-stable however the inputs
    overlap — and re-assign ``seq`` over the union."""
    tagged = []
    for k, stream in enumerate(streams):
        for i, ev in enumerate(stream):
            tagged.append((float(ev["t"]), k, i, ev))
    tagged.sort(key=lambda row: row[:3])
    return finalize([ev for _, _, _, ev in tagged])


def check_stream(events: Sequence[Dict[str, Any]]) -> int:
    """Validate a whole stream (schema per record, ``seq`` dense and
    ordered, ``t`` non-decreasing); returns the event count."""
    last_t = 0.0
    for i, ev in enumerate(events):
        validate_event(ev)
        if ev.get("seq") != i:
            raise ValueError(f"stream seq must be dense 0..N-1: "
                             f"position {i} carries seq {ev.get('seq')!r}")
        if ev["t"] < last_t:
            raise ValueError(f"stream t must be non-decreasing: "
                             f"event {i} at t={ev['t']} after t={last_t}")
        last_t = ev["t"]
    return len(events)


def canonical_bytes(ev: Dict[str, Any]) -> bytes:
    """One record's canonical JSON line (sorted keys, minimal
    separators) — what :func:`stream_digest` hashes and what the
    byte-identical determinism acceptance means literally."""
    return json.dumps(ev, sort_keys=True,
                      separators=(",", ":")).encode()


def stream_digest(events: Sequence[Dict[str, Any]]) -> str:
    """SHA-256 over the stream's canonical bytes: two generator runs
    with the same seed must produce the SAME digest (gated in bench and
    fuzzed in tests/test_scenarios.py)."""
    h = hashlib.sha256()
    for ev in events:
        h.update(canonical_bytes(ev))
        h.update(b"\n")
    return h.hexdigest()


def materialize_prompt(spec: Dict[str, Any], vocab: int) -> List[int]:
    """Deterministic token list for a prompt spec: ``prefix_len``
    tokens drawn from the ``prefix_group``'s own stable stream (every
    request in a group shares them EXACTLY — the prefix-cache /
    prefix-sniping surface), then a tail from the spec's ``seed``."""
    n = int(spec["len"])
    plen = min(int(spec.get("prefix_len") or 0), n)
    toks: List[int] = []
    if plen > 0 and spec.get("prefix_group") is not None:
        rng = random.Random(_stable_seed("prefix", spec["prefix_group"]))
        toks = [rng.randrange(int(vocab)) for _ in range(plen)]
    rng = random.Random(_stable_seed("tail", int(spec["seed"])))
    toks += [rng.randrange(int(vocab)) for _ in range(n - len(toks))]
    return toks


# ---------------------------------------------------------------------------
# generators (each: same seed ⇒ byte-identical stream)
# ---------------------------------------------------------------------------

def staggered(n: int, interarrival: float, *, seed: int = 0,
              tenant: Optional[str] = None,
              priority: Optional[str] = None,
              prompt_len: int = 8, max_new_tokens: int = 8,
              deadline_s: Optional[float] = None,
              prefix_group: Optional[str] = None, prefix_len: int = 0,
              t0: float = 0.0, phase: Optional[str] = None
              ) -> List[Dict[str, Any]]:
    """The primitive arrival source: ``n`` requests, one every
    ``interarrival`` virtual units.  The unit is the REPLAYER's choice
    — wall seconds under :func:`run_scenario`, engine steps under the
    ``bench_serving`` loop (which is how the bench sections and the
    scenario plane share ONE seeded source, ISSUE 18 satellite)."""
    rng = random.Random(_stable_seed("staggered", seed))
    return finalize([
        request_event(
            t0 + i * float(interarrival), tenant=tenant,
            priority=priority, prompt_seed=rng.getrandbits(32),
            prompt_len=prompt_len, prefix_group=prefix_group,
            prefix_len=prefix_len, max_new_tokens=max_new_tokens,
            deadline_s=deadline_s, phase=phase)
        for i in range(int(n))])


def diurnal(seed: int = 0, *,
            phases: Sequence[Tuple[str, int, float]] = DIURNAL_PHASES,
            tenants: Sequence[str] = ("gold", "free"),
            prompt_len: int = 16, max_new_tokens: int = 12,
            deadline_s: Optional[float] = None,
            jitter_frac: float = 0.0) -> List[Dict[str, Any]]:
    """Diurnal offered-load curve: ``phases`` of (name, requests,
    interarrival seconds), tenants alternating deterministically per
    arrival, optional ±``jitter_frac`` seeded jitter on each gap.  The
    ``serving_autoscale`` bench drives exactly this shape (scale-up on
    the peak, no-flap scale-down on the nights)."""
    rng = random.Random(_stable_seed("diurnal", seed))
    events, t, k = [], 0.0, 0
    for name, n_req, gap in phases:
        for _ in range(int(n_req)):
            events.append(request_event(
                t, tenant=tenants[k % len(tenants)],
                prompt_seed=rng.getrandbits(32), prompt_len=prompt_len,
                max_new_tokens=max_new_tokens, deadline_s=deadline_s,
                phase=name))
            k += 1
            g = float(gap)
            if jitter_frac:
                g *= 1.0 + jitter_frac * (2.0 * rng.random() - 1.0)
            t += max(g, 0.0)
    return finalize(events)


def flash_crowd(seed: int = 0, *, n_background: int = 8,
                background_gap: float = 0.03, crowd_at: float = 0.1,
                crowd_n: int = 16, crowd_gap: float = 0.0,
                crowd_prefix_len: int = 12, prompt_len: int = 16,
                max_new_tokens: int = 8,
                deadline_s: Optional[float] = None
                ) -> List[Dict[str, Any]]:
    """Flash crowd: steady background traffic plus a sudden burst of
    ``crowd_n`` near-simultaneous arrivals all sharing one long prefix
    (the crowd is asking the same question) — the prefix cache and the
    autoscaler's scale-up band are both on the measured path."""
    background = staggered(
        n_background, background_gap, seed=_stable_seed("bg", seed),
        tenant="steady", prompt_len=prompt_len,
        max_new_tokens=max_new_tokens, deadline_s=deadline_s,
        phase="background")
    crowd = staggered(
        crowd_n, crowd_gap, seed=_stable_seed("crowd", seed),
        tenant="crowd", prompt_len=prompt_len,
        max_new_tokens=max_new_tokens, deadline_s=deadline_s,
        prefix_group=f"crowd-{seed}", prefix_len=crowd_prefix_len,
        t0=crowd_at, phase="crowd")
    return merge(background, crowd)


def adversarial(seed: int = 0, *, n_paid: int = 8,
                paid_gap: float = 0.02, paid_deadline_s: float = 30.0,
                n_snipe: int = 10, snipe_gap: float = 0.004,
                n_long: int = 4, long_prompt_len: int = 48,
                prompt_len: int = 16, max_new_tokens: int = 8
                ) -> List[Dict[str, Any]]:
    """Adversarial tenants against a paid one: ``sniper`` (best-effort)
    floods cheap requests that SHARE the paid tenant's prefix group —
    prefix-sniping: riding and churning the cache the paid tenant
    earned — while ``hog`` (best-effort) submits near-capacity long
    prompts.  The acceptance is QoS isolation: the paid tenant stays
    un-degraded (no rung ever clamps it) while best-effort absorbs the
    ladder."""
    group = f"paid-{seed}"
    paid = staggered(
        n_paid, paid_gap, seed=_stable_seed("paid", seed),
        tenant="gold", priority="paid", prompt_len=prompt_len,
        prefix_group=group, prefix_len=max(prompt_len // 2, 1),
        max_new_tokens=max_new_tokens, deadline_s=paid_deadline_s,
        phase="paid")
    snipe = staggered(
        n_snipe, snipe_gap, seed=_stable_seed("snipe", seed),
        tenant="sniper", priority="best_effort",
        prompt_len=prompt_len, prefix_group=group,
        prefix_len=max(prompt_len // 2, 1),
        max_new_tokens=max_new_tokens, phase="snipe")
    hog = staggered(
        n_long, snipe_gap * 3, seed=_stable_seed("hog", seed),
        tenant="hog", priority="best_effort",
        prompt_len=long_prompt_len, max_new_tokens=max_new_tokens,
        t0=0.01, phase="hog")
    return merge(paid, snipe, hog)


def mixed_deadlines(seed: int = 0, *, n: int = 16, gap: float = 0.01,
                    prompt_len: int = 16, max_new_tokens: int = 8,
                    classes: Sequence[Tuple[Optional[float], float]] = (
                        (0.5, 0.25), (5.0, 0.25), (None, 0.5))
                    ) -> List[Dict[str, Any]]:
    """Mixed deadline classes: each arrival draws its deadline from
    ``classes`` (deadline seconds or None, weight) via the seeded rng —
    the deadline-aware scheduling surface (tight deadlines evict, slack
    ones queue) under one reproducible stream."""
    rng = random.Random(_stable_seed("deadlines", seed))
    deadlines = [c for c, _ in classes]
    weights = [w for _, w in classes]
    return finalize([
        request_event(
            i * float(gap), tenant="mixed",
            prompt_seed=rng.getrandbits(32), prompt_len=prompt_len,
            max_new_tokens=max_new_tokens,
            deadline_s=rng.choices(deadlines, weights=weights)[0])
        for i in range(int(n))])


def composed_chaos(seed: int = 0, *, kill_at: float = 0.08,
                   kill_target: int = 0, pause_at: float = 0.12,
                   pause_target: int = 1, resume_at: float = 0.3,
                   **crowd_kwargs) -> List[Dict[str, Any]]:
    """Composed chaos: a flash crowd UNDER a worker kill and a
    SIGSTOP/SIGCONT zombie in one stream — detection, failover, the
    zombie fence, and the breaker all fire while the burst is live.
    The interleave is deterministic (:func:`merge`'s stable order), so
    two replays inject the same faults between the same arrivals."""
    load = flash_crowd(_stable_seed("chaos-load", seed), **crowd_kwargs)
    faults = finalize([
        fault_event(kill_at, "kill", kill_target),
        fault_event(pause_at, "pause", pause_target),
        fault_event(resume_at, "resume", pause_target)])
    return merge(load, faults)


#: Named scenario registry (``scripts/run_scenario.py`` and the bench
#: matrix build from here): name → zero-config builder(seed).
SCENARIOS: Dict[str, Callable[..., List[Dict[str, Any]]]] = {
    "diurnal": diurnal,
    "flash_crowd": flash_crowd,
    "adversarial": adversarial,
    "mixed_deadlines": mixed_deadlines,
    "composed_chaos": composed_chaos,
}


def build_scenario(name: str, seed: int = 0,
                   **overrides) -> List[Dict[str, Any]]:
    """Build a registry scenario by name (machine-readable refusal on
    an unknown one)."""
    if name not in SCENARIOS:
        raise ValueError(f"unknown scenario {name!r}; known: "
                         f"{sorted(SCENARIOS)}")
    return SCENARIOS[name](seed=seed, **overrides)


# ---------------------------------------------------------------------------
# replay driver
# ---------------------------------------------------------------------------

def apply_fault(ev: Dict[str, Any], runtimes: Sequence[Any]) -> str:
    """Apply one fault event to a worker list.  In-process
    :class:`~.worker.WorkerRuntime` targets use the chaos face
    (``kill()`` silences everything incl. heartbeats; ``pause`` is the
    same silence, ``resume`` re-opens it — the SIGSTOP zombie: stale
    beats under a fenced epoch).  Popen-bearing targets get the real
    signals.  Returns the applied action for the trace."""
    fault = ev["fault"]
    action = fault["action"]
    if not runtimes:
        return "skipped"
    rt = runtimes[int(fault["target"]) % len(runtimes)]
    proc = getattr(rt, "proc", None)
    if proc is not None:          # a real worker process: real signals
        import signal
        sig = {"kill": signal.SIGKILL, "pause": signal.SIGSTOP,
               "resume": signal.SIGCONT}[action]
        proc.send_signal(sig)
        return action
    if action == "kill":
        rt.kill()
    elif action == "pause":
        rt.killed = True          # kill()'s mechanism, reversibly held
    elif action == "resume":
        rt.killed = False
    return action


def run_scenario(events: Sequence[Dict[str, Any]], router, *,
                 vocab: int, time_scale: float = 1.0,
                 runtimes: Sequence[Any] = (),
                 tenancy=None, model_id: Optional[str] = None,
                 max_attempts: int = 2,
                 settle_timeout_s: float = 60.0,
                 sleep: Callable[[float], None] = time.sleep
                 ) -> Dict[str, Any]:
    """Replay a finalized stream against a live fleet in scaled
    wall-clock; returns the per-scenario matrix row the bench gates.

    Each request event materializes its prompt, submits through
    :func:`~.fleet.submit_with_retry` (tenant/priority/deadline ride
    the event), and counts machine-readable sheds; each fault event
    lands on ``runtimes``.  ``time_scale`` compresses or stretches the
    stream's virtual clock (0 replays as fast as admission allows).
    The caller owns warm-up and ``router.reset_stats()`` — this
    function measures, it does not prepare.

    Matrix keys (direction under scripts/check_perf_regression.py):
    ``shed_rate``/``slo_burn``/``max_rung``/``flap``/``drain_shed``/
    ``*_degraded`` lower-is-better, ``terminal_frac`` higher.
    """
    from .fleet import submit_with_retry
    from .scheduler import AdmissionError

    check_stream(events)
    jitter_rng = random.Random(_stable_seed("retry-jitter",
                                            stream_digest(events)))
    handles: List[Tuple[Dict[str, Any], Any, float]] = []
    shed_by_tenant: Dict[str, int] = {}
    shed_with_deadline = 0
    fault_log: List[Dict[str, Any]] = []
    worker_trace: List[Dict[str, Any]] = []
    n_requests = n_faults = 0

    def live_count() -> int:
        return sum(1 for w in list(router.workers.values())
                   if w.state in ("starting", "live"))

    def sample(phase: Optional[str]) -> None:
        row = {"phase": phase, "t": round(t_virtual, 4),
               "live_workers": live_count()}
        if not worker_trace or worker_trace[-1]["phase"] != phase \
                or worker_trace[-1]["live_workers"] != row["live_workers"]:
            worker_trace.append(row)

    t0 = time.monotonic()
    t_virtual = 0.0
    for ev in events:
        t_virtual = float(ev["t"])
        due = t0 + t_virtual * float(time_scale)
        delay = due - time.monotonic()
        if delay > 0:
            sleep(delay)
        if ev["kind"] == "fault":
            n_faults += 1
            applied = apply_fault(ev, runtimes)
            fault_log.append({"t": t_virtual, "action": applied,
                              "target": ev["fault"]["target"]})
            sample(f"fault:{applied}")
            continue
        n_requests += 1
        tenant = ev.get("tenant")
        prompt = materialize_prompt(ev["prompt"], vocab)
        kwargs: Dict[str, Any] = {
            "tenant": tenant, "priority": ev.get("priority"),
            "deadline_s": ev.get("deadline_s")}
        if model_id is not None:
            kwargs["model_id"] = model_id
        try:
            h = submit_with_retry(
                router.submit, prompt, ev["max_new_tokens"],
                max_attempts=max_attempts, jitter_rng=jitter_rng,
                **kwargs)
        except AdmissionError:
            shed_by_tenant[str(tenant)] = \
                shed_by_tenant.get(str(tenant), 0) + 1
            if ev.get("deadline_s") is not None:
                shed_with_deadline += 1
        else:
            handles.append((ev, h, time.monotonic()))
        sample(ev.get("phase"))

    # settle: every accepted request reaches exactly one outcome
    deadline = time.monotonic() + float(settle_timeout_s)
    while (any(h.status not in ("done", "evicted")
               for _, h, _ in handles)
           and time.monotonic() < deadline):
        sleep(0.005)
    sample("settled")

    # SLO burn: of the deadline-carrying requests, the fraction that
    # missed (deadline eviction, wall overrun, or shed before start)
    with_deadline = [row for row in handles
                     if row[0].get("deadline_s") is not None]
    missed = 0
    for ev, h, t_sub in with_deadline:
        took = time.monotonic() - t_sub
        if h.status not in ("done", "evicted"):
            missed += 1
        elif h.finish_reason in ("deadline", "shed"):
            missed += 1
        elif took > float(ev["deadline_s"]) \
                and h.finish_reason != "eos" and not h.tokens:
            missed += 1
    n_with_deadline = len(with_deadline) + shed_with_deadline
    slo_burn = ((missed + shed_with_deadline) / n_with_deadline
                if n_with_deadline else 0.0)

    m = router.metrics()
    terminal = sum(h.status in ("done", "evicted") for _, h, _ in handles)
    out: Dict[str, Any] = {
        "digest": stream_digest(events),
        "n_events": len(events),
        "n_requests": n_requests,
        "n_faults": n_faults,
        "offered_shed": int(sum(shed_by_tenant.values())),
        "shed_rate": round(float(m.get("fleet/shed_rate", 0.0)), 4),
        "slo_burn": round(float(slo_burn), 4),
        "terminal_frac": round(terminal / max(len(handles), 1), 4),
        "drain_shed": int(m.get("fleet/shed_inflight_total", 0)),
        "worker_lost_detections": int(m.get("fleet/dead_workers", 0)),
        "fenced_refusals": int(sum(
            v for k, v in m.items()
            if k.startswith("fleet/fenced_refusals/"))),
        "peak_workers": max((r["live_workers"] for r in worker_trace),
                            default=0),
        "final_workers": (worker_trace[-1]["live_workers"]
                          if worker_trace else 0),
        "worker_trace": worker_trace,
        "fault_log": fault_log,
        "shed_by_tenant": dict(sorted(shed_by_tenant.items())),
    }
    autoscaler = getattr(router, "autoscaler", None)
    if autoscaler is not None:
        out["flap"] = int(sum(p.flap_count()
                              for p in autoscaler.policies.values()))
    tenancy = tenancy if tenancy is not None else router.tenancy
    if tenancy is not None:
        tm = tenancy.metrics()
        out["max_rung"] = max(
            (i for i, name in enumerate(tenancy.ladder.RUNGS)
             if tenancy.ladder.state()["rung_entries"].get(name)),
            default=0)
        for tname in sorted({str(ev.get("tenant")) for ev in events
                             if ev["kind"] == "request"
                             and ev.get("tenant") is not None}):
            out[f"tenant_{tname}_shed"] = int(
                tm.get(f"tenant/{tname}/shed_total", 0))
            out[f"tenant_{tname}_degraded"] = int(
                tm.get(f"tenant/{tname}/degraded_total", 0))
            ttft = tm.get(f"tenant/{tname}/ttft_p99_ms")
            if ttft is not None:
                out[f"tenant_{tname}_ttft_p99_ms"] = round(
                    float(ttft), 2)
    return out
