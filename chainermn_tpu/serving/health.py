"""Heartbeat/lease health plane for the cross-process serving fleet.

ISSUE 10 built these primitives for the serving fleet; ISSUE 13
promoted them into the transport-agnostic core
:mod:`chainermn_tpu.health` so the TRAINING gang's self-healing plane
(``extensions/gang.py``) runs the exact same lease/epoch/breaker
machinery.  This module re-exports the full original surface — every
existing import path (fleet, workers, tests, analysis entry points)
keeps working unchanged; see the core module for the semantics
(detection-window math, receiver-side clocking, epoch fencing, the
circuit breaker) and docs/ROBUSTNESS.md "Serving failure domains".
"""

from __future__ import annotations

from ..health import (  # noqa: F401
    LEASE_SCHEMA,
    CircuitBreaker,
    EpochFence,
    HeartbeatPublisher,
    LeaseTable,
    detection_window_s,
    make_lease,
)

__all__ = [
    "LEASE_SCHEMA",
    "CircuitBreaker",
    "EpochFence",
    "HeartbeatPublisher",
    "LeaseTable",
    "detection_window_s",
    "make_lease",
]
