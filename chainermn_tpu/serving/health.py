"""Heartbeat/lease health plane for the cross-process serving fleet.

ISSUE 10's supervision layer, jax-free and fuzzable standalone:

* **Leases** — each worker publishes a heartbeat lease (role, epoch,
  seq, queue depth, free slots, backlog, draining flag) under its OWN
  lane tag (``lease/<worker>``), overwritten every beat.  That is the
  ``allgather_obj_eventual`` pattern applied to liveness: a bounded
  per-publisher side channel, deliberately NOT a gang collective — a
  dead worker is simply ABSENT (its lease stops refreshing), it can
  never wedge the readers.
* **Detection-window math** — the supervisor clocks a lease by when IT
  saw a new sequence number (receiver-side monotonic time, so worker
  clock skew is irrelevant).  A worker beating every ``beat_interval_s``
  that misses ``miss_beats`` consecutive beats is declared dead after
  at most ``beat_interval_s * (miss_beats + 1)`` seconds — the ``+1``
  covers the worst-case phase offset between the last accepted beat and
  the first missed one (docs/ROBUSTNESS.md "Serving failure domains").
* **Epoch fencing** — every worker admission mints a monotonic epoch;
  marking a worker dead FENCES its epoch, and every lease, token,
  result, or slab stamped with a fenced epoch is refused and counted
  (:class:`EpochFence`).  A paused-then-resumed zombie can therefore
  never land anything: its writes carry the old epoch, and re-admission
  always mints a new one.
* **Circuit breaker** — re-admission of a flapping worker is governed
  by :class:`CircuitBreaker`: each failure doubles the hold-off
  (exponential backoff, capped), and a bounded retry budget turns a
  serial flapper into a permanent removal instead of an infinite
  flap-readmit loop.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, Dict, Optional

#: Wire schema of one published lease.
LEASE_SCHEMA = "chainermn_tpu.lease.v1"


def detection_window_s(beat_interval_s: float, miss_beats: int) -> float:
    """Worst-case seconds from death to detection: ``miss_beats``
    missed beats plus one interval of phase offset (the worker may die
    immediately after a beat the supervisor just accepted)."""
    return float(beat_interval_s) * (int(miss_beats) + 1)


def make_lease(worker: str, role: str, epoch: int, seq: int,
               **state) -> Dict[str, Any]:
    """One heartbeat lease payload (plain dict: the wire shape)."""
    lease = {
        "schema": LEASE_SCHEMA,
        "worker": str(worker),
        "role": str(role),
        "epoch": int(epoch),
        "seq": int(seq),
        "pid": os.getpid(),
        "t_wall": time.time(),
    }
    lease.update(state)
    return lease


class HeartbeatPublisher:
    """Worker-side half: publish this worker's lease on the lane store
    every ``beat_interval_s`` (callers invoke :meth:`maybe_beat` from
    their loop — a wedged loop then misses leases, which is exactly the
    liveness semantics the supervisor wants to observe).

    Thread-safe: a worker may beat from both its step loop and a side
    heartbeat thread, so seq minting + the put serialize under a lock
    (concurrent unlocked beats could publish duplicate/out-of-order
    seqs and regress lease contents).  :meth:`release` latches the
    publisher closed under the same lock, so a racing beat can never
    resurrect the lease of a worker that just drained."""

    def __init__(self, store, worker: str, role: str, epoch: int,
                 beat_interval_s: float = 0.05, lane_config=None):
        self.store = store
        self.worker = str(worker)
        self.role = str(role)
        self.epoch = int(epoch)
        self.beat_interval_s = float(beat_interval_s)
        self.lane_config = lane_config
        self.seq = 0
        self._last_beat = 0.0
        self._lock = threading.Lock()
        self._released = False

    def beat(self, **state) -> Optional[Dict[str, Any]]:
        """Publish one lease; returns it (None once released)."""
        from ..communicators.base import lane_call

        with self._lock:
            if self._released:
                return None
            self.seq += 1
            lease = make_lease(self.worker, self.role, self.epoch,
                               self.seq, **state)
            payload = pickle.dumps(lease,
                                   protocol=pickle.HIGHEST_PROTOCOL)
            lane_call(f"health/{self.worker}/beat",
                      lambda: self.store.put(f"lease/{self.worker}",
                                             payload),
                      self.lane_config)
            self._last_beat = time.monotonic()
            return lease

    def maybe_beat(self, **state) -> Optional[Dict[str, Any]]:
        """Publish iff a beat interval elapsed since the last one."""
        if time.monotonic() - self._last_beat >= self.beat_interval_s:
            return self.beat(**state)
        return None

    def release(self) -> None:
        """Graceful exit (drain): delete this worker's lease so the
        supervisor sees an explicit departure, not a missed window.
        Latches the publisher: later beats are refused."""
        from ..communicators.base import lane_call

        with self._lock:
            self._released = True
            lane_call(f"health/{self.worker}/release",
                      lambda: self.store.delete(f"lease/{self.worker}"),
                      self.lane_config)


class LeaseTable:
    """Supervisor-side half: read leases and clock them by RECEIVER
    monotonic time — ``age_s`` is seconds since this process last saw a
    NEW sequence number, immune to cross-process clock skew."""

    def __init__(self, store, lane_config=None):
        self.store = store
        self.lane_config = lane_config
        # worker -> (last seen lease dict, t_seen of last NEW seq)
        self._seen: Dict[str, Any] = {}

    def read(self, worker: str) -> Optional[Dict[str, Any]]:
        """Latest lease for ``worker`` (schema-checked), or None when
        the worker never published / released its lease."""
        from .lanes import lane_try_get

        payload = lane_try_get(self.store, f"health/{worker}/read",
                               f"lease/{worker}", self.lane_config)
        if payload is None:
            return None
        lease = pickle.loads(payload)
        if lease.get("schema") != LEASE_SCHEMA:
            raise ValueError(
                f"refusing lease with schema {lease.get('schema')!r} "
                f"for worker {worker!r} (this supervisor speaks "
                f"{LEASE_SCHEMA})")
        prev = self._seen.get(worker)
        if prev is None or lease["seq"] != prev[0]["seq"]:
            self._seen[worker] = (lease, time.monotonic())
        return self._seen[worker][0]

    def age_s(self, worker: str) -> Optional[float]:
        """Seconds since the last NEW lease seq from ``worker`` was
        observed, or None before any lease arrived."""
        self.read(worker)
        prev = self._seen.get(worker)
        if prev is None:
            return None
        return time.monotonic() - prev[1]

    def forget(self, worker: str) -> None:
        self._seen.pop(worker, None)


class EpochFence:
    """Monotonic per-worker epochs + the fence refusing stale writes.

    The router mints ``new_epoch(worker)`` at every (re-)admission and
    ``fence(worker)`` on death.  Receivers gate every inbound artifact
    with :meth:`admit` — a stale-epoch lease/token/result/slab is
    refused AND counted per kind, which is the zombie-fencing
    acceptance evidence (ISSUE 10)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._epoch: Dict[str, int] = {}     # worker -> current epoch
        self._fenced: Dict[str, bool] = {}
        self.refusals: Dict[str, int] = {}   # kind -> refused count

    def new_epoch(self, worker: str) -> int:
        with self._lock:
            e = self._epoch.get(worker, 0) + 1
            self._epoch[worker] = e
            self._fenced[worker] = False
            return e

    def fence(self, worker: str) -> None:
        with self._lock:
            self._fenced[worker] = True

    def current(self, worker: str) -> Optional[int]:
        with self._lock:
            return self._epoch.get(worker)

    def is_fenced(self, worker: str) -> bool:
        with self._lock:
            return bool(self._fenced.get(worker, False))

    def admit(self, worker: str, epoch, kind: str) -> bool:
        """Whether an artifact stamped ``epoch`` from ``worker`` may
        land.  Refusals (stale epoch, or the worker's current epoch is
        fenced) are counted under ``kind``."""
        with self._lock:
            cur = self._epoch.get(worker)
            ok = (cur is not None and int(epoch) == cur
                  and not self._fenced.get(worker, False))
            if not ok:
                self.refusals[kind] = self.refusals.get(kind, 0) + 1
            return ok

    def refusal_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.refusals)


class CircuitBreaker:
    """Per-worker re-admission governor: retry budget + exponential
    backoff.  ``record_failure`` opens the circuit for ``backoff_base_s
    * 2^(failures-1)`` (capped at ``backoff_max_s``); :meth:`allow`
    half-opens it after the hold-off; ``record_success`` closes it and
    refunds the budget.  Past ``max_failures`` consecutive failures the
    circuit opens PERMANENTLY — a serial flapper is removed from the
    fleet rather than re-admitted forever."""

    def __init__(self, max_failures: int = 4, backoff_base_s: float = 0.5,
                 backoff_max_s: float = 30.0,
                 clock=time.monotonic):
        self.max_failures = int(max_failures)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self._clock = clock
        self.failures = 0
        self._open_until: Optional[float] = None
        self.permanently_open = False

    def record_failure(self) -> None:
        self.failures += 1
        if self.failures >= self.max_failures:
            self.permanently_open = True
            self._open_until = None
            return
        delay = min(self.backoff_base_s * (2 ** (self.failures - 1)),
                    self.backoff_max_s)
        self._open_until = self._clock() + delay

    def record_success(self) -> None:
        self.failures = 0
        self._open_until = None
        self.permanently_open = False

    def allow(self) -> bool:
        """May the worker be re-admitted now?"""
        if self.permanently_open:
            return False
        if self._open_until is None:
            return True
        return self._clock() >= self._open_until

    def state(self) -> Dict[str, Any]:
        return {
            "failures": self.failures,
            "permanently_open": self.permanently_open,
            "open_for_s": (None if self._open_until is None
                           else max(self._open_until - self._clock(), 0.0)),
        }
