"""Worker-process role loops for the cross-process serving fleet.

ISSUE 10 tentpole (a): each fleet member runs a role loop in its OWN
process on its own mesh, speaking to the router exclusively over the
hardened object lanes — request submit, streamed tokens, results, and
KV-slab transfer all ride the same wire (``lanes.py`` mailboxes +
``transfer.py`` slab tags), so a worker death severs lanes, never
shared memory.  Three roles:

* ``engine`` — a full :class:`~chainermn_tpu.serving.frontend
  .ServingEngine` replica (the ``serve --fleet-procs N`` gang member):
  ``submit`` messages admit into its own scheduler/pool, every emitted
  token streams back as a ``token`` message, and the terminal ``result``
  message carries the AUTHORITATIVE token list (streamed tokens are
  hints; the result is what the router reconciles — token-exactness
  survives message loss).
* ``prefill`` / ``decode`` — the PR 9 role split across processes
  (``serve --disagg P:D --procs``): a prefill worker runs ONLY the
  prefill programs, publishes each finished slab on the lane
  (``slab/<trace_id>``) and announces it with ``slab_ready``; a decode
  worker receives router-forwarded ``install`` messages, reserves a
  slot, lands the slab through the pool-lifetime compiled inject
  program (:meth:`~chainermn_tpu.serving.transfer.KvTransferPlane
  .unpack_into`), and ticks — its prefill-program family stays empty.

Every loop iteration drains the control inbox, does one round of role
work, and publishes a heartbeat lease (``health.py``) — a wedged loop
therefore misses leases, which IS the liveness signal the supervisor
watches.  Every outbound message and lease is stamped with the worker's
EPOCH; the router's :class:`~chainermn_tpu.serving.health.EpochFence`
refuses stale stamps, so a paused-then-resumed zombie cannot land
slabs, tokens, or leases.  ``drain`` stops admission, finishes
in-flight work, reports ``drained``, releases the lease, and exits 0 —
the graceful half of a rolling restart.

``python -m chainermn_tpu.serving.worker --role engine --name w0
--lane-dir D --params P.pkl`` is the process entry the fleet spawner
execs; :class:`WorkerRuntime` is transport-agnostic so tests and the
bench drive the same loop in-process over the loopback store.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from ..observability import flight as _flight
from ..observability import journal as _journal
from .health import HeartbeatPublisher
from .lanes import MailboxReceiver, MailboxSender
from .scheduler import AdmissionError, Request, Scheduler
from .transfer import KvTransferPlane

ROLES = ("engine", "prefill", "decode")


def ctl_mailbox(worker: str) -> str:
    """Router → worker control mailbox name (single writer: router)."""
    return f"ctl.{worker}"


def out_mailbox(worker: str) -> str:
    """Worker → router outbox name (single writer: the worker)."""
    return f"out.{worker}"


def request_from_wire(wire: Dict[str, Any], *, on_token=None) -> Request:
    """Rebuild a host-side :class:`Request` from the submit/install wire
    dict (deadline rides RELATIVE — monotonic clocks do not cross
    processes)."""
    rel = wire.get("deadline_rel_s")
    rng = wire.get("rng")
    req = Request(
        [int(t) for t in wire["prompt"]],
        int(wire["max_new_tokens"]),
        eos_id=wire.get("eos_id"),
        deadline_t=(None if rel is None else time.monotonic() + float(rel)),
        on_token=on_token,
        trace_id=wire["trace_id"],
        temperature=float(wire.get("temperature", 0.0)),
        rng=(None if rng is None
             else np.asarray(rng, np.uint32).reshape(2)),
        tenant=wire.get("tenant"))
    # a decode-installed request never passes Scheduler.submit (the
    # only other place this is stamped) — TTFT/emit paths need it
    req.timestamps["submitted"] = time.monotonic()
    return req


class WorkerRuntime:
    """One fleet member's role loop (transport-agnostic).

    ``store`` is any object lane (``FileLaneStore`` across processes,
    ``InProcessLaneStore`` for in-process tests/bench — same protocol,
    same fault discipline).  ``kill()`` is the chaos face: the runtime
    stops doing ANY work, including heartbeats — to the supervisor it
    is indistinguishable from a SIGKILL'd process.
    """

    def __init__(self, name: str, role: str, params, store, *,
                 head_dim: int, epoch: int = 1,
                 beat_interval_s: float = 0.05,
                 lane_config=None, lane_timeout_s: float = 10.0,
                 bundle_dir: Optional[str] = None,
                 n_slots: int = 4, max_total: int = 128,
                 queue_capacity: int = 16, staging_slots: int = 2,
                 max_prefills_per_tick: int = 1, prefill_bucket: int = 1,
                 mesh=None, axis_name: str = "model",
                 prefix_cache: bool = True,
                 spill_bytes: int = 32 << 20,
                 model_id: str = "default",
                 weights_generation: int = 1):
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        self.name = str(name)
        self.role = str(role)
        # heterogeneous-fleet identity (ISSUE 18): which model variant
        # this worker serves and which weight generation it holds; both
        # ride every lease so the router routes/upgrades per-model
        self.model_id = str(model_id)
        self.weights_generation = int(weights_generation)
        self.store = store
        self.epoch = int(epoch)
        self.lane_config = lane_config
        self.lane_timeout_s = float(lane_timeout_s)
        self.bundle_dir = bundle_dir
        self.inbox = MailboxReceiver(store, ctl_mailbox(name), lane_config)
        self.outbox = MailboxSender(store, out_mailbox(name), lane_config)
        self.heart = HeartbeatPublisher(
            store, name, role, self.epoch,
            beat_interval_s=beat_interval_s, lane_config=lane_config)
        self.plane = KvTransferPlane(transport=store,
                                     lane_config=lane_config)
        self.draining = False
        self.finished = False
        self.killed = False
        self._local: Dict[str, Any] = {}   # trace_id -> RequestHandle
        self._steps = 0
        self._beat_thread = None
        self._t_last_step = time.monotonic()

        # fleet KV-economy counters (ISSUE 12): ride every lease so the
        # router's /metricsz can aggregate them fleet-wide
        self.cache_counters: Dict[str, int] = {
            "pull_serves": 0, "pull_stale": 0, "pull_installs": 0,
            "crc_refusals": 0}

        if role in ("engine", "decode"):
            from .frontend import ServingEngine
            self.engine = ServingEngine(
                params, head_dim=head_dim, n_slots=n_slots,
                max_total=max_total, mesh=mesh, axis_name=axis_name,
                queue_capacity=(queue_capacity if role == "engine" else 1),
                max_prefills_per_tick=max_prefills_per_tick,
                prefill_bucket=prefill_bucket,
                prefix_cache=(prefix_cache and role == "engine"),
                spill_bytes=(spill_bytes if role == "engine" else 0))
            self.pool = self.engine.pool
            self.scheduler = self.engine.scheduler
            if self.engine.prefix_cache is not None:
                # announce every cache lifecycle event over the wire:
                # the router's global index mirrors this worker's trie
                self.engine.on_cache_insert = self._announce_insert
                self.engine.on_cache_evict = self._announce_evict
                self.engine.on_spill_evict = self._announce_spill_evict
        else:  # prefill: staging pool + prefill programs ONLY
            from ..parallel.decode import _kv_heads
            from .cache_pool import CachePool
            from .engine import DecodeEngine
            if mesh is None:
                from ..topology import make_mesh
                mesh = make_mesh(axis_name=axis_name)
            n_kv = _kv_heads(params, head_dim)
            self.pool = CachePool(
                staging_slots, max_total, len(params["blocks"]),
                n_kv * head_dim, params["embed"].dtype, mesh, axis_name)
            self.dec_engine = DecodeEngine(
                params, self.pool, mesh, axis_name, head_dim=head_dim,
                prefill_bucket=prefill_bucket)
            self.scheduler = Scheduler(
                queue_capacity, max_total,
                max_prefills_per_tick=max_prefills_per_tick,
                max_positions=self.dec_engine.max_positions)
            self.engine = None

    # ---- outbound (every message stamped worker + epoch) ----
    def _send(self, kind: str, **fields) -> None:
        self.outbox.send(dict(fields, kind=kind, worker=self.name,
                              epoch=self.epoch))

    def _on_token(self, trace_id: str):
        def cb(tok: int, _rid: int) -> None:
            self._send("token", trace_id=trace_id, token=int(tok))
        return cb

    # ---- fleet KV economy: cache announces + pull serving (ISSUE 12) ----
    def _geom(self) -> Dict[str, Any]:
        """Slab geometry the router needs to price a pull of this
        worker's prefixes in token units (transfer_cost statics)."""
        pool = self.engine.pool
        return {"n_layers": pool.n_layers, "kv_dim": pool.kv_dim,
                "dtype": str(pool.caches[0][0].dtype),
                "model_id": self.model_id}

    def _announce_insert(self, entry) -> None:
        try:
            self._send("cache_announce", op="insert",
                       prefix=[int(t) for t in entry.seq],
                       length=int(entry.length), slot=int(entry.slot),
                       geom=self._geom())
        except Exception as e:  # noqa: BLE001 — the index is soft
            # state; a failed announce costs a missed pull opportunity,
            # never correctness
            _flight.note("worker", event="announce_failed",
                         worker=self.name, error=str(e))

    def _announce_evict(self, entry, spilled: bool) -> None:
        try:
            self._send("cache_announce", op="evict",
                       prefix=[int(t) for t in entry.seq],
                       length=int(entry.length), spilled=bool(spilled))
        except Exception as e:  # noqa: BLE001
            _flight.note("worker", event="announce_failed",
                         worker=self.name, error=str(e))

    def _announce_spill_evict(self, seq, length) -> None:
        try:
            # tier-scoped: the device trie may hold this sequence HOT
            # again (re-donated since the spill) — only a spill-tier
            # index record may be dropped by a spill-store eviction
            self._send("cache_announce", op="evict",
                       prefix=[int(t) for t in seq], length=int(length),
                       spilled=False, tier="spill")
        except Exception as e:  # noqa: BLE001
            _flight.note("worker", event="announce_failed",
                         worker=self.name, error=str(e))

    def _announce_snapshot(self) -> None:
        """Full index rebuild, riding the ``hello`` re-admission
        handshake: everything the router believed about this worker's
        cache died with the fenced epoch — replace it with what this
        incarnation actually holds (device trie + spill tier)."""
        eng = self.engine
        if eng is None or eng.prefix_cache is None:
            return
        entries = [
            {"seq": [int(t) for t in e.seq], "length": int(e.length),
             "tier": "hot"}
            for e in eng.prefix_cache.entries()]
        if eng.spill is not None:
            hot = {tuple(e["seq"]) for e in entries}
            entries += [
                {"seq": [int(t) for t in seq], "length": int(length),
                 "tier": "spill"}
                for seq, length in eng.spill.entries()
                if tuple(seq) not in hot]
        try:
            self._send("cache_announce", op="snapshot",
                       entries=entries, geom=self._geom())
        except Exception as e:  # noqa: BLE001
            _flight.note("worker", event="announce_failed",
                         worker=self.name, error=str(e))

    # ---- inbound control ----
    def _handle(self, msg: Dict[str, Any]) -> None:
        kind = msg.get("kind")
        if kind == "hello":
            # (re-)admission: adopt the router's freshly minted epoch —
            # everything this worker publishes from here on carries it,
            # so the fence re-opens for exactly this incarnation
            self.epoch = int(msg["epoch"])
            self.heart.epoch = self.epoch
            # the hello's HLC was already merged at mbx_recv; this event
            # marks the instant the new epoch takes effect worker-side —
            # the conformance monitor's worker.process_hello action
            _journal.emit("hello_processed", worker=self.name,
                          epoch=self.epoch, model_id=self.model_id,
                          weights_generation=self.weights_generation)
            self.heart.beat(**self._lease_state())
            # full cache-index rebuild rides the handshake (ISSUE 12):
            # the router dropped every fenced-epoch entry at death,
            # and this incarnation re-announces what it holds NOW
            self._announce_snapshot()
            return
        if kind == "stop":
            self.finished = True
            return
        if kind == "drain":
            self.draining = True
            _flight.note("worker", event="draining", worker=self.name)
            return
        # work-bearing messages must match the epoch the router thinks
        # this worker is on (a hello is in flight otherwise)
        if int(msg.get("epoch", -1)) != self.epoch:
            _flight.note("worker", event="stale_ctl_refused",
                         worker=self.name, msg_kind=kind,
                         msg_epoch=msg.get("epoch"), epoch=self.epoch)
            return
        if kind == "submit":
            self._handle_submit(msg["req"])
        elif kind == "install":
            self._handle_install(msg)
        elif kind == "cache_pull":
            self._handle_cache_pull(msg)
        elif kind == "install_prefix":
            self._handle_install_prefix(msg)
        else:
            _flight.note("worker", event="unknown_ctl", worker=self.name,
                         msg_kind=kind)

    def _handle_submit(self, wire: Dict[str, Any]) -> None:
        if self.draining:
            self._send("shed", trace_id=wire["trace_id"],
                       payload=AdmissionError(
                           "worker_lost",
                           f"worker {self.name} is draining").to_dict())
            return
        trace_id = wire["trace_id"]
        if self.role == "engine":
            try:
                h = self.engine.submit(
                    wire["prompt"], wire["max_new_tokens"],
                    eos_id=wire.get("eos_id"),
                    deadline_s=wire.get("deadline_rel_s"),
                    on_token=self._on_token(trace_id),
                    trace_id=trace_id,
                    temperature=float(wire.get("temperature", 0.0)),
                    rng=wire.get("rng"),
                    tenant=wire.get("tenant"))
            except AdmissionError as e:
                self._send("shed", trace_id=trace_id, payload=e.to_dict())
                return
            self._local[trace_id] = h
        else:  # prefill role: queue for the prefill-only loop
            req = request_from_wire(wire)
            try:
                s_pad = self.dec_engine.padded_len(req.prompt_len)
                cap = self.pool.max_total
                if self.dec_engine.max_positions is not None:
                    cap = min(cap, self.dec_engine.max_positions)
                if s_pad > cap:
                    raise AdmissionError(
                        "too_long",
                        f"prompt {req.prompt_len} pads to {s_pad}, "
                        f"exceeding staging capacity {cap}")
                self.scheduler.submit(req, time.monotonic())
            except AdmissionError as e:
                self._send("shed", trace_id=trace_id, payload=e.to_dict())

    def _handle_install(self, msg: Dict[str, Any]) -> None:
        """Decode role: land a router-forwarded slab into a reserved
        slot via the compiled inject program, then tick it like any
        other running request."""
        from ..communicators.base import DcnLaneError

        trace_id, tag = msg["trace_id"], msg["tag"]
        slot = self.engine.pool.reserve()
        if slot is None:
            self._send("install_nack", trace_id=trace_id, tag=tag,
                       reason="no_free_slot")
            return
        try:
            payload = self.plane.lane_get(tag, self.lane_timeout_s)
            stats = self.plane.unpack_into(payload, self.engine.pool, slot)
        except DcnLaneError as e:
            self.engine.pool.cancel_reservation(slot)
            _flight.note("worker", event="install_fault", worker=self.name,
                         trace_id=trace_id, lane=e.lane)
            self._send("install_nack", trace_id=trace_id, tag=tag,
                       reason="lane_fault", lane=e.lane)
            return
        meta = stats["meta"]
        self.engine.pool.commit_reservation(slot)
        req = request_from_wire(meta, on_token=self._on_token(trace_id))
        self._local[trace_id] = _HandleView(req)
        self.engine.install_request(req, slot, meta["tokens"])
        try:
            self.plane.lane_delete(tag)
        except DcnLaneError as e:
            _flight.note("worker", event="gc_failed", tag=tag, lane=e.lane)
        self._send("install_ok", trace_id=trace_id)

    def _handle_cache_pull(self, msg: Dict[str, Any]) -> None:
        """Owner side of a remote prefix pull (ISSUE 12): pack the
        requested prefix's K/V (pinned across the read — a concurrent
        eviction can never free the slot mid-pack) and publish it on
        the lane; the spill tier serves when the device trie already
        scavenged the slot.  A claim that went fully stale since the
        announce nacks ``stale`` — the router counts it and the request
        degrades to re-prefill (the index is a hint, never truth)."""
        from ..communicators.base import DcnLaneError

        trace_id, tag = msg["trace_id"], msg["tag"]
        seq = [int(t) for t in msg["prefix"]][: int(msg["length"])]
        eng = self.engine
        payload = None
        if eng is not None and eng.prefix_cache is not None:
            entry = eng.prefix_cache.pin_covering(seq)
            if entry is not None:
                try:
                    payload = self.plane.pack(
                        eng.pool, entry.slot, len(seq),
                        meta={"seq": seq, "length": len(seq)})
                finally:
                    eng.prefix_cache.release(entry)
            elif eng.spill is not None:
                # demoted to the host tier: the spilled payload is
                # already packed and CRC-stamped — serve it directly
                payload = eng.spill.covering(seq)
        if payload is None:
            self.cache_counters["pull_stale"] += 1
            _flight.note("worker", event="pull_stale", worker=self.name,
                         trace_id=trace_id, prefix_len=len(seq))
            self._send("cache_pull_nack", trace_id=trace_id, tag=tag,
                       reason="stale")
            return
        try:
            self.plane.lane_put(tag, payload)
        except DcnLaneError as e:
            _flight.note("worker", event="pull_publish_fault",
                         worker=self.name, trace_id=trace_id,
                         lane=e.lane)
            self._send("cache_pull_nack", trace_id=trace_id, tag=tag,
                       reason="publish_fault", lane=e.lane)
            return
        self.cache_counters["pull_serves"] += 1
        self._send("cache_slab_ready", trace_id=trace_id, tag=tag,
                   length=len(seq), pull=True)

    def _handle_install_prefix(self, msg: Dict[str, Any]) -> None:
        """Destination side of a remote prefix pull: land the slab into
        a RESERVED slot through the pool-lifetime compiled inject
        program (CRC verified inside ``unpack_into``) and donate it
        straight into the local prefix cache, so the held-back submit
        that follows gets a plain local hit.  The ONE caught
        :class:`DcnLaneError` failure domain: reservation cancelled,
        nack names the lane, the request re-prefills — never a wedge,
        never a leaked slot."""
        from ..communicators.base import DcnLaneError

        trace_id, tag = msg["trace_id"], msg["tag"]
        eng = self.engine
        if eng is None or eng.prefix_cache is None:
            self._send("prefix_nack", trace_id=trace_id, tag=tag,
                       reason="no_cache")
            return
        pool = eng.pool
        slot = pool.reserve()
        if slot is None:
            # scavenge an unpinned prefix slot like admission would —
            # the pull replaces colder cache, it never starves decode
            if eng.prefix_cache.evict_lru() is not None:
                slot = pool.reserve()
        if slot is None:
            self._send("prefix_nack", trace_id=trace_id, tag=tag,
                       reason="no_free_slot")
            return
        try:
            payload = self.plane.lane_get(tag, self.lane_timeout_s)
        except DcnLaneError as e:
            pool.cancel_reservation(slot)
            _flight.note("worker", event="prefix_install_fault",
                         worker=self.name, trace_id=trace_id,
                         lane=e.lane)
            self._send("prefix_nack", trace_id=trace_id, tag=tag,
                       reason="lane_fault", lane=e.lane)
            return
        try:
            stats = self.plane.unpack_into(payload, pool, slot)
        except ValueError as e:
            # corrupt/foreign slab REFUSED (CRC/schema/shape): count,
            # free the reservation, let the router fall back to a
            # clean re-prefill — wrong KV is never served
            pool.cancel_reservation(slot)
            self.cache_counters["crc_refusals"] += 1
            _flight.note("worker", event="prefix_crc_refused",
                         worker=self.name, trace_id=trace_id,
                         error=str(e))
            self._send("prefix_nack", trace_id=trace_id, tag=tag,
                       reason="crc")
            return
        meta = stats["meta"]
        seq = [int(t) for t in meta.get("seq", [])][: stats["length"]]
        pool.commit_reservation(slot)
        entry = eng.prefix_cache.insert(seq, slot, len(seq))
        if entry is not None:
            pool.cache(slot)   # busy -> cached rc=0, announce fired
        else:
            # dedup: something local already covers it — the pull was
            # redundant but the submit that follows still hits
            pool.release(slot)
        try:
            self.plane.lane_delete(tag)
        except DcnLaneError as e:
            _flight.note("worker", event="gc_failed", tag=tag,
                         lane=e.lane)
        self.cache_counters["pull_installs"] += 1
        self._send("prefix_installed", trace_id=trace_id,
                   length=len(seq))

    # ---- role work ----
    def _prefill_round(self) -> int:
        """Prefill-only iteration: admit into staging, run the prefill
        program, publish the slab on the lane, announce it, recycle the
        staging slot.  The router gates downstream capacity (it holds
        ``install`` forwards until a decode worker has a slot), so the
        only local budget is free staging slots."""
        from ..communicators.base import DcnLaneError

        now = time.monotonic()
        for req in self.scheduler.expire_queued(now):
            self._send("result", trace_id=req.trace_id, tokens=[],
                       finish_reason="deadline", ttft_ms=None)
        worked = 0
        for req in self.scheduler.admissions(self.pool.free_count, now):
            slot = self.pool.acquire()
            try:
                first = self.dec_engine.prefill_into_slot(
                    req.prompt, slot, rng=req.rng,
                    temperature=req.temperature)
            except Exception as e:  # noqa: BLE001 — shed THIS request only
                self.pool.release(slot)
                self._send("shed", trace_id=req.trace_id,
                           payload=AdmissionError(
                               "worker_lost",
                               f"prefill failed: {e!r}").to_dict())
                continue
            length = int(self.pool.pos[slot])
            meta = {
                "trace_id": req.trace_id,
                "prompt": [int(t) for t in req.prompt],
                "max_new_tokens": req.max_new_tokens,
                "eos_id": req.eos_id,
                "deadline_rel_s": (None if req.deadline_t is None
                                   else max(req.deadline_t
                                            - time.monotonic(), 0.0)),
                "temperature": req.temperature,
                "rng": (None if req.rng is None
                        else [int(x) for x in np.asarray(req.rng)
                              .reshape(2)]),
                "tokens": [int(first)],
            }
            tag = f"slab/{req.trace_id}"
            try:
                payload = self.plane.pack(self.pool, slot, length,
                                          meta=meta)
                self.plane.lane_put(tag, payload)
            except DcnLaneError as e:
                self.pool.release(slot)
                _flight.note("worker", event="publish_fault",
                             worker=self.name, trace_id=req.trace_id,
                             lane=e.lane)
                self._send("shed", trace_id=req.trace_id,
                           payload=AdmissionError(
                               "worker_lost",
                               f"slab publish failed on lane "
                               f"{e.lane}").to_dict())
                continue
            self.pool.release(slot)
            self._send("slab_ready", trace_id=req.trace_id, tag=tag,
                       length=length, meta=meta)
            worked += 1
        return worked

    def _report_finished(self) -> None:
        """Terminal ``result`` messages for requests that finished this
        step — the AUTHORITATIVE token list (streamed ``token`` messages
        are latency hints; this is what the router reconciles)."""
        done = [tid for tid, h in self._local.items()
                if h.status in ("done", "evicted")]
        for tid in done:
            h = self._local.pop(tid)
            self._send("result", trace_id=tid, tokens=list(h.tokens),
                       finish_reason=h.finish_reason,
                       ttft_ms=h.ttft_ms)

    def _lease_state(self) -> Dict[str, Any]:
        step_age = time.monotonic() - self._t_last_step
        if self.role == "prefill":
            queued = self.scheduler.queued_requests()
            return {
                "queue_depth": len(queued),
                "queue_capacity": self.scheduler.queue_capacity,
                "free_slots": self.pool.free_count,
                "busy_slots": self.pool.busy_count,
                "backlog_tokens": sum(r.prompt_len for r in queued),
                "draining": self.draining,
                "last_step_age_s": round(step_age, 4),
                "model_id": self.model_id,
                "weights_generation": self.weights_generation,
                "cache": {"prefill_calls":
                          int(self.dec_engine.prefill_calls)},
            }
        eng = self.engine
        queued = eng.scheduler.queued_requests()
        backlog = sum(r.prompt_len + r.max_new_tokens for r in queued)
        with eng._lock:
            running = list(eng._running.values())
        backlog += sum(max(r.max_new_tokens - len(r.tokens), 0)
                       for r in running)
        # decode tick-gap p99 rides the lease (ISSUE 11): the
        # autoscaler's decode-side pressure signal, measured where it
        # exists (the engine) and read where the policy runs
        gap_p99 = eng._tick_gap_ms.percentile(99)
        # KV-economy counters ride the lease (ISSUE 12): the router's
        # /metricsz aggregates them fleet-wide without extra messages
        cache = dict(self.cache_counters)
        cache["prefill_calls"] = int(eng.engine.prefill_calls)
        if eng.prefix_cache is not None:
            cache["prefix_entries"] = eng.prefix_cache.n_entries
            cache["prefix_hits"] = int(eng.prefix_cache.hits)
        if eng.spill is not None:
            sp = eng.spill
            cache["spills"] = int(sp.spills)
            cache["restores"] = int(sp.restores)
            cache["crc_refusals"] = (cache.get("crc_refusals", 0)
                                     + int(sp.crc_refusals))
        return {
            "queue_depth": len(queued),
            "queue_capacity": eng.scheduler.queue_capacity,
            "free_slots": eng.pool.free_count,
            "busy_slots": eng.pool.busy_count,
            "reserved_slots": eng.pool.reserved_count,
            "backlog_tokens": int(backlog),
            "tokens_emitted": eng._tokens_emitted,
            "in_flight": len(self._local),
            "draining": self.draining,
            "model_id": self.model_id,
            "weights_generation": self.weights_generation,
            # destination-side slab geometry (ISSUE 18): the router's
            # pull planner refuses geometry-mismatched claims against it
            "geom": self._geom(),
            "last_step_age_s": round(step_age, 4),
            "tick_gap_p99_ms": (None if gap_p99 is None
                                else round(gap_p99, 3)),
            "cache": cache,
        }

    def start_heartbeat(self) -> None:
        """Publish leases from a SIDE thread, so a long device call
        (a first-prefill compile can block the loop for seconds) is not
        misread as death.  A SIGKILL/SIGSTOP takes the whole process —
        thread included — so real death still silences the lease within
        one beat; the lease's ``last_step_age_s`` field carries loop
        progress separately, so a wedged-but-breathing loop is visible
        to the supervisor as degradation rather than invisible."""
        import threading

        if self._beat_thread is not None:
            return

        def loop():
            while not self.finished:
                if not self.killed:
                    try:
                        self.heart.maybe_beat(**self._lease_state())
                    except Exception:  # noqa: BLE001 — a beat must
                        pass           # never kill the worker
                time.sleep(self.heart.beat_interval_s / 2.0)

        self._beat_thread = threading.Thread(
            target=loop, daemon=True, name=f"heartbeat-{self.name}")
        self._beat_thread.start()

    @property
    def idle(self) -> bool:
        busy = (self.scheduler.queue_depth > 0
                or self.pool.busy_count > 0 or bool(self._local))
        if self.role == "decode":
            busy = busy or self.pool.reserved_count > 0
        return not busy

    def step(self) -> int:
        """One worker iteration: drain the control inbox, one round of
        role work, report finished requests, heartbeat.  Returns how
        much work happened (0 == idle)."""
        if self.killed or self.finished:
            return 0
        worked = 0
        for msg in self.inbox.drain():
            self._handle(msg)
            worked += 1
            if self.finished:
                return worked
        if self.role == "prefill":
            worked += self._prefill_round()
        else:
            if (self.scheduler.queue_depth > 0
                    or self.pool.busy_count > 0):
                self.engine.step()
                worked += 1
            self._report_finished()
        if self.draining and self.idle:
            self._send("drained")
            # finished BEFORE the lease release: the heartbeat thread
            # must never re-publish a lease for a drained worker
            self.finished = True
            self.heart.release()
            _flight.note("worker", event="drained", worker=self.name)
            return worked + 1
        if self._beat_thread is None:
            # with the side thread active it owns the lease cadence —
            # beating from here too would interleave two publishers
            self.heart.maybe_beat(**self._lease_state())
        self._steps += 1
        self._t_last_step = time.monotonic()
        return worked

    def run(self, poll_s: float = 0.002) -> int:
        """Drive :meth:`step` until drained/stopped; returns exit code
        0 (the graceful-drain acceptance: a drained worker EXITS 0)."""
        self.start_heartbeat()
        while not self.finished:
            if self.step() == 0:
                time.sleep(poll_s)
        if self._beat_thread is not None:
            # join before interpreter teardown: a daemon thread dying
            # mid-shutdown inside the jax runtime aborts the process
            self._beat_thread.join(timeout=2 * self.heart.beat_interval_s
                                   + 1.0)
            self._beat_thread = None
        if self.engine is not None:
            self.engine.close()
        return 0

    def kill(self) -> None:
        """Chaos face: stop ALL activity including heartbeats — what a
        SIGKILL looks like from the supervisor's side."""
        self.killed = True


class _HandleView:
    """Handle-shaped view of a decode-installed request (the decode
    role has no submit(), so no RequestHandle was minted)."""

    def __init__(self, req: Request):
        self._req = req

    @property
    def status(self):
        return self._req.status

    @property
    def tokens(self):
        return list(self._req.tokens)

    @property
    def finish_reason(self):
        return self._req.finish_reason

    @property
    def ttft_ms(self):
        ts = self._req.timestamps
        if "submitted" in ts and "first_token" in ts:
            return (ts["first_token"] - ts["submitted"]) * 1e3
        return None


def main(argv=None) -> int:
    """Process entry: build the role loop from a pickled params file and
    run it over a :class:`~chainermn_tpu.serving.lanes.FileLaneStore`.
    The fleet spawner (``serving/fleet.py::spawn_worker``) execs this."""
    import argparse
    import pickle

    parser = argparse.ArgumentParser(
        description="chainermn_tpu serving fleet worker process")
    parser.add_argument("--name", required=True)
    parser.add_argument("--role", required=True, choices=ROLES)
    parser.add_argument("--lane-dir", required=True)
    parser.add_argument("--params", required=True,
                        help="pickle file: {'params': pytree, "
                             "'head_dim': int, ...engine kwargs}")
    parser.add_argument("--epoch", type=int, default=1)
    parser.add_argument("--beat-interval-s", type=float, default=0.05)
    parser.add_argument("--bundle-dir", default=None)
    parser.add_argument("--journal-dir", default=None,
                        help="causal HLC journal directory (ISSUE 17); "
                             "this worker tees its state transitions "
                             "into journal.<name>.jsonl there")
    args = parser.parse_args(argv)

    if args.journal_dir:
        from ..observability import journal
        journal.configure(args.journal_dir, args.name)

    import jax  # noqa: F401 — ensure backend init before engine build

    from .lanes import FileLaneStore

    with open(args.params, "rb") as f:
        spec = pickle.load(f)
    params = spec.pop("params")
    if args.bundle_dir:
        from .. import global_except_hook
        from ..observability import flight
        flight.install_signal_handlers(args.bundle_dir)
        global_except_hook.add_hook()
    store = FileLaneStore(args.lane_dir)
    runtime = WorkerRuntime(
        args.name, args.role, params, store, epoch=args.epoch,
        beat_interval_s=args.beat_interval_s,
        bundle_dir=args.bundle_dir, **spec)
    import os as _os
    import sys as _sys
    print(f"[chainermn_tpu worker] {args.name} role={args.role} "
          f"epoch={args.epoch} pid={_os.getpid()} ready",
          file=_sys.stderr, flush=True)
    return runtime.run()


if __name__ == "__main__":
    raise SystemExit(main())
