"""Load-driven elastic autoscaling for the serving fleet (ISSUE 11).

PR 10 built every actuator an autoscaler needs — ``drain()`` exits 0,
``add_worker`` + ``hello`` mints fresh epochs, the ``CircuitBreaker``
governs re-admission, leases carry ``last_step_age_s`` — and PR 5/7/9
export every signal (SLO burn rate, queue depth and backlog-token
estimates, decode tick-gap p99, shed rate).  This module closes the
loop:

* :class:`AutoscalePolicy` — the decision function, deliberately PURE:
  ``decide(signals, now)`` reads a plain signal dict and explicit
  receiver time (no sleeps, no wall-clock reads — the ``health.py``
  discipline), so the hysteresis proof is a unit test over a synthetic
  signal trace.  Scale-up fires on any overload trigger (backlog
  tokens per worker, shed rate, SLO burn, tick-gap p99, queue depth);
  scale-down only after EVERY signal sat below the (strictly lower)
  relax thresholds continuously for ``down_stable_s``.  Both
  directions honor cooldowns and a bounded step size.

  **Why it provably does not flap** (the acceptance invariant: no
  scale-up immediately followed by scale-down inside one cooldown
  window, and vice versa): (1) every up threshold is validated
  strictly above its down counterpart, so no single signal value
  satisfies both directions; (2) after an up decision at ``t``, a down
  decision is refused until ``t + down_cooldown_s`` AND the low-dwell
  clock restarts at the decision (``down_stable_s`` of continuous calm
  must follow it); (3) after a down at ``t``, an up is refused until
  ``t + up_cooldown_s``.  :meth:`flap_count` re-derives the invariant
  from the recorded decision history — the bench gates on it staying 0.

* :class:`FleetAutoscaler` — binds one policy PER ROLE to a live
  :class:`~chainermn_tpu.serving.fleet.FleetRouter`: signals come from
  the leases the workers already publish (queue depth, backlog tokens,
  free/busy slots, ``last_step_age_s``, engine ``tick_gap_p99_ms``)
  plus the router's SLO tracker and shed counters; scale-up spawns a
  fresh worker through the caller's ``spawn(name, role)`` factory and
  registers it via ``add_worker`` (a fresh epoch via ``hello``);
  scale-down ALWAYS goes through ``drain()`` — never a kill — so a
  shrinking fleet sheds nothing (``drain_shed == 0``, the chaos-tier
  acceptance).  Role-split fleets get one policy per role, which IS
  the prefill:decode ratio control: each side scales on its own
  bottleneck signal (prefill: queue/backlog; decode: tick-gap/slots).

  Every decision is recorded as a machine-readable
  ``autoscale_decision`` flight event naming the triggering signal,
  its value and threshold, and the worker count before/after — the
  postmortem answer to "why did the fleet resize"
  (``scripts/explain_bundle.py`` renders them).

* :func:`derive_retry_after_ms` — the drain-aware back-off hint
  (ISSUE 11 satellite): ``retry_after_ms`` = tokens queued / recent
  tokens-per-second, clamped and jittered, so ``submit_with_retry``
  clients back off proportionally to REAL congestion instead of a
  static estimate.  Zero-throughput edges (cold start, wedged fleet)
  fall back to pricing the backlog at ``default_token_latency_ms``.

See docs/ROBUSTNESS.md "Autoscaling & overload" for the knob table and
the hysteresis math.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..observability import flight as _flight

#: Signal names a decision's ``reason`` may carry (the triggering
#: signal), in evaluation order.
UP_SIGNALS = ("below_min", "backlog_tokens_per_worker", "shed_rate",
              "burn_rate_short", "tick_gap_p99_ms",
              "queue_depth_per_worker")


def derive_retry_after_ms(backlog_tokens: float, tokens_per_sec: float, *,
                          default_token_latency_ms: float = 20.0,
                          floor_ms: float = 1.0,
                          cap_ms: float = 30_000.0,
                          jitter_frac: float = 0.1,
                          rng: Optional[random.Random] = None) -> float:
    """Back-off hint from the MEASURED backlog drain rate.

    ``backlog_tokens / tokens_per_sec`` is the wall the queue needs to
    drain at the recent throughput — the honest "come back when
    capacity plausibly exists" signal.  Edge cases, each clamped into
    ``[floor_ms, cap_ms]``:

    * ``backlog_tokens <= 0`` → ``floor_ms`` (no congestion: retry
      immediately-ish; the floor keeps the hint truthy).
    * ``tokens_per_sec <= 0`` with backlog (cold start, or a wedged
      fleet emitting nothing) → price the backlog at
      ``default_token_latency_ms`` per token instead of dividing by
      zero; the cap bounds the hint when the backlog is huge.

    ``jitter_frac`` spreads retries ±uniformly so a shed burst does not
    re-arrive as a synchronized herd (same rationale as
    ``submit_with_retry``); pass ``rng`` (or ``jitter_frac=0``) for
    deterministic tests.  The jittered value is re-clamped, so the
    bounds hold unconditionally.
    """
    backlog = max(float(backlog_tokens), 0.0)
    tps = float(tokens_per_sec)
    if backlog <= 0.0:
        est = float(floor_ms)
    elif tps > 1e-9:
        est = backlog / tps * 1e3
    else:
        est = backlog * float(default_token_latency_ms)
    est = min(max(est, float(floor_ms)), float(cap_ms))
    if jitter_frac > 0.0:
        u = (rng or random).random()
        est *= 1.0 + float(jitter_frac) * (2.0 * u - 1.0)
        est = min(max(est, float(floor_ms)), float(cap_ms))
    return est


class AutoscalePolicy:
    """Hysteretic worker-count policy — pure ``decide(signals, now)``.

    ``signals`` is a plain dict; missing/None entries disable their
    trigger.  Recognized keys: ``live_workers`` (required),
    ``backlog_tokens``, ``queue_depth``, ``shed_rate`` (fraction of
    recently offered), ``burn_rate_short``, ``tick_gap_p99_ms``,
    ``occupancy_frac``.

    Thresholds come in (up, down) pairs validated ``up > down`` —
    see the module docstring for the no-flap argument.
    """

    def __init__(self, *, role: str = "engine",
                 min_workers: int = 1, max_workers: int = 4,
                 up_backlog_tokens_per_worker: float = 64.0,
                 down_backlog_tokens_per_worker: float = 8.0,
                 up_queue_depth_per_worker: float = 4.0,
                 down_queue_depth_per_worker: float = 0.5,
                 up_shed_rate: float = 0.02,
                 up_burn_rate: float = 1.0,
                 up_tick_gap_p99_ms: Optional[float] = None,
                 down_occupancy_frac: float = 0.5,
                 up_cooldown_s: float = 1.0,
                 down_cooldown_s: float = 2.0,
                 down_stable_s: float = 2.0,
                 max_step: int = 1,
                 history: int = 256):
        if not 1 <= int(min_workers) <= int(max_workers):
            raise ValueError(f"need 1 <= min_workers <= max_workers, got "
                             f"{min_workers}..{max_workers}")
        for up, down, what in (
                (up_backlog_tokens_per_worker,
                 down_backlog_tokens_per_worker, "backlog"),
                (up_queue_depth_per_worker,
                 down_queue_depth_per_worker, "queue_depth")):
            if up <= down:
                raise ValueError(
                    f"{what}: up threshold ({up}) must sit strictly "
                    f"above the down threshold ({down}) — equal or "
                    f"inverted bands flap on a noisy signal")
        if down_cooldown_s <= 0 or up_cooldown_s <= 0:
            raise ValueError("cooldowns must be > 0")
        self.role = str(role)
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.up_backlog = float(up_backlog_tokens_per_worker)
        self.down_backlog = float(down_backlog_tokens_per_worker)
        self.up_queue = float(up_queue_depth_per_worker)
        self.down_queue = float(down_queue_depth_per_worker)
        self.up_shed_rate = float(up_shed_rate)
        self.up_burn = float(up_burn_rate)
        self.up_tick_gap_ms = (None if up_tick_gap_p99_ms is None
                               else float(up_tick_gap_p99_ms))
        self.down_occupancy = float(down_occupancy_frac)
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.down_stable_s = float(down_stable_s)
        self.max_step = max(int(max_step), 1)
        # hysteresis state
        self._last_up_t: Optional[float] = None
        self._last_down_t: Optional[float] = None
        self._low_since: Optional[float] = None
        self.ups = 0
        self.downs = 0
        self.decisions: deque = deque(maxlen=int(history))

    # ---- trigger evaluation ----
    def _up_trigger(self, sig: Dict[str, Any],
                    live: int) -> Optional[Dict[str, Any]]:
        def per(v):
            return float(v) / max(live, 1)

        checks = (
            ("backlog_tokens_per_worker",
             per(sig.get("backlog_tokens") or 0), self.up_backlog),
            ("shed_rate", float(sig.get("shed_rate") or 0.0),
             self.up_shed_rate),
            ("burn_rate_short", sig.get("burn_rate_short"), self.up_burn),
            ("tick_gap_p99_ms", sig.get("tick_gap_p99_ms"),
             self.up_tick_gap_ms),
            ("queue_depth_per_worker",
             per(sig.get("queue_depth") or 0), self.up_queue),
        )
        for name, value, thr in checks:
            if value is None or thr is None:
                continue
            if float(value) > thr:
                return {"reason": name, "signal": round(float(value), 4),
                        "threshold": thr}
        return None

    def _is_low(self, sig: Dict[str, Any], live: int) -> bool:
        def per(v):
            return float(v) / max(live, 1)

        if per(sig.get("backlog_tokens") or 0) > self.down_backlog:
            return False
        if per(sig.get("queue_depth") or 0) > self.down_queue:
            return False
        if float(sig.get("shed_rate") or 0.0) > 0.0:
            return False
        burn = sig.get("burn_rate_short")
        if burn is not None and float(burn) > self.up_burn / 2.0:
            return False
        occ = sig.get("occupancy_frac")
        if occ is not None and float(occ) > self.down_occupancy:
            return False
        return True

    # ---- the decision function ----
    def decide(self, signals: Dict[str, Any],
               now: float) -> Optional[Dict[str, Any]]:
        """One policy evaluation; returns a decision dict (also
        appended to :attr:`decisions`) or None.  Deterministic: the
        same (signals, now) trace always yields the same decisions."""
        live = int(signals["live_workers"])
        decision = None
        if live < self.min_workers:
            # both cooldowns apply here too: a permanently failing
            # spawn must retry at the cooldown cadence (not every
            # tick), and an up right after a down — even a legitimate
            # below-min recovery — would read as a flap in the
            # recorded history (invariant 3)
            if self._cooled(self._last_up_t, self.up_cooldown_s, now) \
                    and self._cooled(self._last_down_t,
                                     self.up_cooldown_s, now):
                decision = self._mk(
                    "up", live,
                    min(self.min_workers - live, self.max_step),
                    {"reason": "below_min", "signal": live,
                     "threshold": self.min_workers}, now)
        else:
            trig = self._up_trigger(signals, live)
            if trig is not None:
                self._low_since = None
                if (live < self.max_workers
                        and self._cooled(self._last_up_t,
                                         self.up_cooldown_s, now)
                        and self._cooled(self._last_down_t,
                                         self.up_cooldown_s, now)):
                    decision = self._mk(
                        "up", live, min(self.max_step,
                                        self.max_workers - live),
                        trig, now)
            elif self._is_low(signals, live):
                if self._low_since is None:
                    self._low_since = now
                if (now - self._low_since >= self.down_stable_s
                        and live > self.min_workers
                        and self._cooled(self._last_up_t,
                                         self.down_cooldown_s, now)
                        and self._cooled(self._last_down_t,
                                         self.down_cooldown_s, now)):
                    decision = self._mk(
                        "down", live, min(self.max_step,
                                          live - self.min_workers),
                        {"reason": "sustained_low_load",
                         "signal": round(now - self._low_since, 4),
                         "threshold": self.down_stable_s}, now)
            else:
                self._low_since = None
        return decision

    @staticmethod
    def _cooled(last_t: Optional[float], cooldown_s: float,
                now: float) -> bool:
        return last_t is None or now - last_t >= cooldown_s

    def _mk(self, direction: str, live: int, delta: int,
            trig: Dict[str, Any], now: float) -> Dict[str, Any]:
        if direction == "up":
            self._last_up_t = now
            self._low_since = None   # calm must RE-accumulate after it
            self.ups += 1
            target = live + delta
        else:
            self._last_down_t = now
            self._low_since = None
            self.downs += 1
            target = live - delta
        dec = {"event": "autoscale_decision", "role": self.role,
               "direction": direction, "delta": int(delta),
               "before": int(live), "target": int(target),
               "t": round(now, 4), **trig}
        self.decisions.append(dec)
        return dec

    def flap_count(self) -> int:
        """Opposite-direction decision pairs closer than the relevant
        cooldown, re-derived from the RECORDED history (the bench/test
        acceptance: must be 0 — the refusal logic above makes it so,
        this measures rather than trusts)."""
        flaps = 0
        prev = None
        for dec in self.decisions:
            if prev is not None and dec["direction"] != prev["direction"]:
                window = (self.down_cooldown_s
                          if dec["direction"] == "down"
                          else self.up_cooldown_s)
                if dec["t"] - prev["t"] < window:
                    flaps += 1
            prev = dec
        return flaps

    def state(self) -> Dict[str, Any]:
        return {
            "role": self.role,
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "ups": self.ups,
            "downs": self.downs,
            "flaps": self.flap_count(),
            "last_decision": (self.decisions[-1] if self.decisions
                              else None),
            "cooldowns_s": {"up": self.up_cooldown_s,
                            "down": self.down_cooldown_s,
                            "down_stable": self.down_stable_s},
        }


class FleetAutoscaler:
    """Bind :class:`AutoscalePolicy` instances to a live FleetRouter.

    ``spawn(name, role) -> WorkerClient`` is the caller's worker
    factory (:func:`local_spawn_factory` for in-process runtimes,
    :func:`proc_spawn_factory` for real processes); the autoscaler
    registers the returned client via ``router.add_worker`` — the
    rolling-restart admission path, fresh epoch included.  Scale-down
    picks the live worker of the role with the least in-flight work
    and calls ``router.drain`` — NEVER kill — so every shrink finishes
    its in-flight requests and exits 0.

    Drive: ``router.step()`` calls :meth:`maybe_tick` when an
    autoscaler is attached (throttled to ``interval_s``), so the
    router's supervisor thread IS the control loop; :meth:`tick` is
    the deterministic face tests and the bench drive directly.
    """

    def __init__(self, router, spawn: Callable[[str, str], Any], *,
                 policies: Optional[List[AutoscalePolicy]] = None,
                 interval_s: float = 0.1,
                 signal_window_s: float = 2.0,
                 metrics_writer=None,
                 clock: Callable[[], float] = time.monotonic):
        from ..observability.slo import RateMeter

        self.router = router
        self.spawn = spawn
        roles = sorted({w.role for w in router.workers.values()})
        self.policies: Dict[str, AutoscalePolicy] = {
            p.role: p for p in (policies
                                or [AutoscalePolicy(role=r)
                                    for r in roles])}
        unknown = set(self.policies) - set(roles)
        if unknown:
            raise ValueError(f"policies for roles not in the fleet: "
                             f"{sorted(unknown)} (fleet has {roles})")
        self.interval_s = float(interval_s)
        self.metrics_writer = metrics_writer
        self._clock = clock
        self._t_last_tick: Optional[float] = None
        self._counter = 0
        self._spawn_failures = 0
        self._drains_requested = 0
        self._shed_meter = RateMeter(signal_window_s, clock=clock)
        self._offered_meter = RateMeter(signal_window_s, clock=clock)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        #: latched by stop(): a fleet being deliberately drained to
        #: zero (shutdown, rolling restart) must not fight a control
        #: loop that would re-spawn workers below min_workers
        self._disabled = False
        router.autoscaler = self   # the /statusz fleet_health view

    # ---- signals ----
    def collect(self, role: str) -> Dict[str, Any]:
        """One role's signal snapshot, built from what the fleet
        already exports: worker leases, the router's rejection/dispatch
        counters (windowed into a recent shed RATE), and the shared SLO
        tracker's short-window burn."""
        r = self.router
        now = self._clock()
        live = [w for w in r.workers.values()
                if w.state in ("starting", "live") and w.role == role]
        backlog = queue_depth = busy = free = 0
        gap_p99 = None
        step_age = 0.0
        for w in live:
            lease = w.last_lease or {}
            queue_depth += (int(lease.get("queue_depth", 0))
                            + w.sent_since_lease)
            backlog += int(lease.get("backlog_tokens", 0))
            busy += int(lease.get("busy_slots", 0))
            free += int(lease.get("free_slots", 0))
            step_age = max(step_age,
                           float(lease.get("last_step_age_s", 0.0)))
            g = lease.get("tick_gap_p99_ms")
            if g is not None:
                gap_p99 = max(gap_p99 or 0.0, float(g))
        with r._lock:
            # CAPACITY sheds only: queue_full/shed_slo are fixed by
            # more workers; shed_tenant_budget and too_long are not —
            # a budget-capped tenant hammering submit_with_retry must
            # neither drive a spurious scale-up nor (via the is-low
            # check) pin the fleet at max forever
            rejected = sum(n for reason, n in r._rejected.items()
                           if reason in ("queue_full", "shed_slo"))
            dispatched = r._dispatched
        self._shed_meter.observe(rejected, now=now)
        self._offered_meter.observe(rejected + dispatched, now=now)
        offered_rate = self._offered_meter.rate(now=now)
        shed_rate = (self._shed_meter.rate(now=now) / offered_rate
                     if offered_rate > 0 else 0.0)
        burn = (r.slo.short_window_burn() if r.slo is not None
                else None)
        return {
            "live_workers": len(live),
            "queue_depth": queue_depth,
            "backlog_tokens": backlog,
            "shed_rate": round(shed_rate, 4),
            "burn_rate_short": burn,
            "tick_gap_p99_ms": gap_p99,
            "occupancy_frac": busy / max(busy + free, 1),
            "last_step_age_s": round(step_age, 4),
        }

    # ---- drive ----
    def maybe_tick(self) -> List[Dict[str, Any]]:
        now = self._clock()
        if self._disabled or (
                self._t_last_tick is not None
                and now - self._t_last_tick < self.interval_s):
            return []
        return self.tick(now=now)

    def tick(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One control-loop round: collect → decide → actuate, per
        role.  Returns the decisions applied (possibly empty)."""
        if self._disabled:
            return []
        now = self._clock() if now is None else float(now)
        with self._lock:
            self._t_last_tick = now
            applied = []
            for role, policy in self.policies.items():
                signals = self.collect(role)
                dec = policy.decide(signals, now)
                if dec is None:
                    continue
                dec["signals"] = signals
                self._apply(dec)
                applied.append(dec)
            return applied

    def _apply(self, dec: Dict[str, Any]) -> None:
        role, delta = dec["role"], dec["delta"]
        if dec["direction"] == "up":
            spawned = []
            for _ in range(delta):
                self._counter += 1
                name = f"{role}-as{self._counter}"
                try:
                    wc = self.spawn(name, role)
                    self.router.add_worker(wc)
                except Exception as e:  # noqa: BLE001 — a failed spawn
                    # must not kill the control loop; the gap re-fires
                    # the trigger next tick
                    self._spawn_failures += 1
                    _flight.note("autoscale", event="spawn_failed",
                                 worker=name, role=role, error=repr(e))
                    continue
                spawned.append(name)
            dec["spawned"] = spawned
        else:
            # scale-down is ALWAYS a drain (never kill): pick the live
            # workers with the least in-flight work, let them finish,
            # collect exit 0 — drain_shed stays 0 by construction
            with self.router._lock:
                inflight: Dict[str, int] = {}
                for e in self.router._inflight.values():
                    inflight[e["worker"]] = \
                        inflight.get(e["worker"], 0) + 1
            live = [w for w in self.router.workers.values()
                    if w.state in ("starting", "live")
                    and w.role == role]
            victims = sorted(
                live, key=lambda w: (
                    inflight.get(w.name, 0),
                    int((w.last_lease or {}).get("queue_depth", 0))
                    + w.sent_since_lease))[:delta]
            for w in victims:
                self.router.drain(w.name)
                self._drains_requested += 1
            dec["drained"] = [w.name for w in victims]
        # "t" is the POLICY clock (monotonic decision time, used by
        # flap_count); the ring stamps its own wall-clock "t" — don't
        # shadow it
        _flight.note("autoscale_decision",
                     **{k: v for k, v in dec.items()
                        if k not in ("event", "t")})
        if self.metrics_writer is not None:
            self.metrics_writer.write(
                {k: v for k, v in dec.items()
                 if isinstance(v, (int, float)) and k != "t"},
                kind="autoscale_decision")

    def start(self) -> None:
        """Standalone supervisor thread (when the router is driven by
        something that never calls ``step()``)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.maybe_tick()
                except Exception as e:  # noqa: BLE001 — the control
                    # loop must outlive one bad tick; note and continue
                    _flight.note("autoscale", event="tick_failed",
                                 error=repr(e))
                self._stop.wait(self.interval_s / 2.0)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="fleet-autoscaler")
        self._thread.start()

    def stop(self) -> None:
        """Latch the control loop OFF (both the standalone thread and
        the router-step drive): call before deliberately draining the
        fleet, or the below-min rule would re-spawn what shutdown just
        drained."""
        self._disabled = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # ---- read-out ----
    # every reader takes the same lock tick() holds while appending
    # decisions / registering workers: a /statusz scrape or a bench
    # metrics() call iterating the decision deque mid-append would
    # otherwise raise RuntimeError (the dict-mutation race this PR
    # fixed in FleetRouter._live, on the autoscaler's own state)
    def target_sizes(self) -> Dict[str, int]:
        with self._lock:
            return self._target_sizes_locked()

    def _target_sizes_locked(self) -> Dict[str, int]:
        out = {}
        for role, p in self.policies.items():
            last = p.decisions[-1] if p.decisions else None
            out[role] = (int(last["target"]) if last is not None
                         else sum(1 for w in
                                  list(self.router.workers.values())
                                  if w.role == role
                                  and w.state in ("starting", "live")))
        return out

    def state(self) -> Dict[str, Any]:
        """The fleet_health provider's autoscaler view (ISSUE 11
        satellite: /statusz and the flight bundle agree on why the
        fleet is its current size)."""
        with self._lock:
            return {
                "target_sizes": self._target_sizes_locked(),
                "policies": {role: p.state()
                             for role, p in self.policies.items()},
                "spawn_failures": self._spawn_failures,
                "drains_requested": self._drains_requested,
                "interval_s": self.interval_s,
            }

    def metrics(self) -> Dict[str, float]:
        with self._lock:
            out: Dict[str, float] = {
                "autoscale/spawn_failures": float(self._spawn_failures),
                "autoscale/drains_requested": float(
                    self._drains_requested),
            }
            for role, p in self.policies.items():
                out[f"autoscale/{role}/ups"] = float(p.ups)
                out[f"autoscale/{role}/downs"] = float(p.downs)
                out[f"autoscale/{role}/flap"] = float(p.flap_count())
            return out


# ---------------------------------------------------------------------------
# spawn factories (the actuator's supply side)
# ---------------------------------------------------------------------------

def local_spawn_factory(params, router, *, head_dim: int,
                        beat_interval_s: float = 0.02,
                        worker_kwargs: Optional[Dict[str, Any]] = None,
                        runtimes: Optional[List[Any]] = None):
    """``spawn(name, role)`` for in-process fleets: builds a
    :class:`~chainermn_tpu.serving.worker.WorkerRuntime` on the
    router's store, drives it on a daemon thread (``rt.run`` — the
    same loop a process runs, exit 0 on drain), and returns the
    :class:`~chainermn_tpu.serving.fleet.WorkerClient` to register.
    Appends each runtime to ``runtimes`` so the caller can tear them
    down."""
    from .fleet import WorkerClient
    from .worker import WorkerRuntime

    def spawn(name: str, role: str):
        rt = WorkerRuntime(name, role, params, router.store,
                           head_dim=head_dim, epoch=1,
                           beat_interval_s=beat_interval_s,
                           **(worker_kwargs or {}))
        if runtimes is not None:
            runtimes.append(rt)
        threading.Thread(target=rt.run, daemon=True,
                         name=f"worker-{name}").start()
        return WorkerClient(name, role, router.store, epoch=1)

    return spawn


def proc_spawn_factory(lane_dir: str, params_file: str, *,
                       beat_interval_s: float = 0.05,
                       bundle_dir: Optional[str] = None,
                       journal_dir: Optional[str] = None,
                       env: Optional[Dict[str, str]] = None):
    """``spawn(name, role)`` for cross-process fleets: execs a real
    worker process over the file lanes (the ``build_proc_fleet``
    spawner) and returns its :class:`WorkerClient`."""
    from .fleet import WorkerClient, spawn_worker
    from .lanes import FileLaneStore

    store = FileLaneStore(lane_dir)

    def spawn(name: str, role: str):
        proc = spawn_worker(lane_dir, params_file, name, role, epoch=1,
                            beat_interval_s=beat_interval_s,
                            bundle_dir=bundle_dir,
                            journal_dir=journal_dir, env=env)
        return WorkerClient(name, role, store, epoch=1, proc=proc)

    return spawn
