"""KV-transfer plane: ship a finished prefill's KV slab between pools.

The disaggregation primitive (ISSUE 9, ROADMAP item 4): a PREFILL
worker computes a prompt's K/V slab into a staging slot of its own
:class:`~chainermn_tpu.serving.cache_pool.CachePool`; this plane moves
that slab — plus the request metadata riding with it — into a DECODE
worker's reserved slot.  Two transports, one contract:

* **Same-process** (:meth:`KvTransferPlane.transfer_local`): ONE
  compiled program per (src-pool, dst-pool) shape pair — slot row out
  of the source caches, through the PR 8 redistribution primitive
  (``parallel/reshard.py::reshard``: each (src, dst) cache-spec pair
  lowers to its MINIMAL collective — identity when both pools shard
  the KV columns the same way, one accounted all_to_all if they ever
  differ), ``dynamic_update_slice`` into the destination slot.  Slot
  indices are traced operands, so every transfer after the first hits
  the jit cache (the ``serving.kv_transfer`` analysis entry point
  asserts one program across src/dst variants and reconciles its
  collective bytes against the comm ledger).
* **Cross-process** (:meth:`pack` → a DCN object lane →
  :meth:`unpack_into`): the slab's written rows ``[0, pos)`` are
  serialized with the request wire dict and shipped over the hardened
  KV-store lanes (``communicators/base.py::lane_call`` — retry/backoff
  on transients, loud :class:`~chainermn_tpu.communicators.base
  .DcnLaneError` NAMING the lane otherwise), then injected through a
  pool-lifetime compiled slab write on the receiving side.  Every lane
  transfer books its RAW slab bytes in the comm ledger as a noted
  ``kv_transfer_lane@dcn`` row — the same number
  :func:`transfer_cost` predicts statically, held byte-exact by
  tests/test_serving_disagg.py.

Correctness of the full-row copy without a length operand: rows beyond
the prompt's ``pos`` carry the source slot's stale K/V, but they land
ABOVE the destination occupant's position and are unreachable by the
standard per-slot masking argument (cache_pool.py module docstring) —
the same reasoning that makes slot recycling and the prefix-cache copy
exact, asserted token-exactly by the disagg fuzz tests.
"""

from __future__ import annotations

import pickle
import threading
import time
import zlib
from typing import Any, Dict, Optional

import numpy as np

#: Wire schema of one packed transfer (bump on layout change — a
#: receiver must refuse a slab it cannot interpret, never guess).
WIRE_SCHEMA = "chainermn_tpu.kv_transfer.v1"

#: The ledger key every lane-mode transfer books under (op@axis) — the
#: shard-flow/bench reconciliation joins on it.
LANE_OP = "kv_transfer_lane"
LANE_AXIS = "dcn"

#: The ledger key a host-RAM spill RESTORE books under (ISSUE 12): the
#: same payload format and inject program as a lane transfer, but the
#: slab never crossed DCN — it round-tripped through the local spill
#: tier, so pricing it as DCN traffic would corrupt the wire-byte gate.
SPILL_OP = "kv_spill_restore"
SPILL_AXIS = "host"


def slab_crc32(rows) -> int:
    """CRC32 over the packed slab's raw K/V bytes, in layer order (K
    then V per layer) — the end-to-end integrity stamp every
    ``chainermn_tpu.kv_transfer.v1`` payload carries (ISSUE 12).  The
    checksum covers the KV numbers themselves, so a slab corrupted
    anywhere between :meth:`KvTransferPlane.pack` and
    :meth:`KvTransferPlane.unpack_into` (lane store, host spill tier,
    a bad DIMM) is REFUSED at landing rather than silently decoded
    into wrong-but-plausible tokens."""
    crc = 0
    for k, v in rows:
        crc = zlib.crc32(np.ascontiguousarray(k).tobytes(), crc)
        crc = zlib.crc32(np.ascontiguousarray(v).tobytes(), crc)
    return crc & 0xFFFFFFFF


def _shard_axis_of(spec, axis_name: str) -> Optional[int]:
    """The logical axis a pool's cache PartitionSpec shards over
    ``axis_name`` — the glue into ``reshard``'s spec language (None =
    replicated)."""
    for i, s in enumerate(tuple(spec)):
        names = s if isinstance(s, tuple) else (s,)
        if axis_name in [n for n in names if n is not None]:
            return i
    return None


def slab_nbytes(n_layers: int, length: int, kv_dim: int, dtype) -> int:
    """RAW K/V payload bytes of one transferred slab: 2 (K and V) ×
    layers × written rows × kv_dim — the ledger-convention number
    (pickle framing excluded; the wire adds a few % on top)."""
    item = np.dtype(dtype).itemsize
    return 2 * int(n_layers) * int(length) * int(kv_dim) * item


def transfer_cost(n_layers: int, length: int, kv_dim: int, dtype, *,
                  mode: str, axis_size: int = 1,
                  src_spec: Optional[int] = 2,
                  dst_spec: Optional[int] = 2,
                  copy_rows: Optional[int] = None) -> Dict[str, Any]:
    """Static prediction of one transfer's comm-ledger booking — the
    number the runtime must reproduce byte-exactly (the shard-flow
    discipline applied to the transfer plane).

    ``mode="local"``: the compiled same-process path — per-(K|V)-row
    :func:`~chainermn_tpu.parallel.reshard.reshard_cost` of the
    (1, copy_rows, kv_dim) block between the two pools' cache specs
    (zero when they match, one all_to_all per row otherwise).
    ``mode="lanes"``: the DCN object-lane path — :func:`slab_nbytes`
    of the written rows, booked as one noted ``kv_transfer_lane@dcn``
    row per transfer.
    """
    if mode == "lanes":
        nbytes = slab_nbytes(n_layers, length, kv_dim, dtype)
        return {"mode": mode, "primitive": LANE_OP,
                "ledger_bytes": nbytes, "wire_bytes": nbytes,
                "messages": 1}
    if mode != "local":
        raise ValueError(f"mode must be 'local' or 'lanes', got {mode!r}")
    from ..parallel.reshard import reshard_cost

    rows = int(copy_rows if copy_rows is not None else length)
    total = {"mode": mode, "primitive": None, "ledger_bytes": 0,
             "wire_bytes": 0, "messages": 0}
    for _ in range(2 * int(n_layers)):
        c = reshard_cost((1, rows, int(kv_dim)), dtype, src_spec,
                         dst_spec, axis_size)
        total["ledger_bytes"] += c["ledger_bytes"]
        total["wire_bytes"] += c["wire_bytes"]
        total["messages"] += c["messages"]
        if c["primitive"]:
            total["primitive"] = c["primitive"]
    return total


class InProcessLaneStore:
    """Loopback object-lane transport: the single-process stand-in for
    the jax.distributed KV store (``XlaCommunicator``'s client), with
    the same put/get/delete face the cross-process deployment wires in.
    Faults are injected through ``lane_call``'s injector, NOT here —
    the chaos tests exercise the real retry/classification path."""

    def __init__(self):
        self._store: Dict[str, bytes] = {}
        self._cv = threading.Condition()

    def put(self, tag: str, payload: bytes) -> None:
        with self._cv:
            self._store[str(tag)] = bytes(payload)
            self._cv.notify_all()

    def get(self, tag: str, timeout_s: float = 10.0) -> bytes:
        deadline = time.monotonic() + float(timeout_s)
        with self._cv:
            while str(tag) not in self._store:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"kv transfer tag {tag!r} not published within "
                        f"{timeout_s}s (deadline exceeded)")
                self._cv.wait(left)
            return self._store[str(tag)]

    def delete(self, tag: str) -> None:
        with self._cv:
            self._store.pop(str(tag), None)

    def tags(self):
        """Snapshot of every published tag — the supervisor's orphan
        sweep face (ISSUE 12): a slab tag left by a worker that died
        between pack-publish and install-ack is visible here, owned by
        nobody, and must eventually be GC'd."""
        with self._cv:
            return list(self._store)


class KvTransferPlane:
    """The transfer-plane object a disaggregated fleet shares.

    ``transport``: an object-lane (put/get/delete) for the cross-
    process path — :class:`InProcessLaneStore` by default; a
    multi-controller deployment passes the communicator-backed lanes
    (``CommunicatorBase.kv_lane_transport()``).  The local compiled
    path needs no transport and is used whenever source and
    destination pools share a mesh.
    """

    def __init__(self, transport=None, lane_config=None):
        self.transport = transport or InProcessLaneStore()
        self.lane_config = lane_config
        self._programs: Dict[Any, Any] = {}   # local-path program cache
        self._inject_programs: Dict[Any, Any] = {}
        # host-side counters (the fleet's /statusz + bench read these)
        self.transfers = 0
        self.lane_transfers = 0
        self.bytes_moved = 0            # ledger-convention slab bytes
        self.last_transfer_ms = 0.0

    # ------------------------------------------------------------------
    # same-process: one compiled program per pool-shape pair
    # ------------------------------------------------------------------
    def _local_key(self, src_pool, dst_pool):
        def sig(pool):
            return (pool.n_layers, pool.n_slots, pool.max_total,
                    pool.kv_dim, str(pool.caches[0][0].dtype))
        return (sig(src_pool), sig(dst_pool), id(src_pool.mesh),
                id(dst_pool.mesh), src_pool.axis_name)

    def _build_local(self, src_pool, dst_pool):
        import jax
        from jax.sharding import PartitionSpec as P

        from .._compat import shard_map
        from ..parallel.reshard import reshard

        if src_pool.mesh is not dst_pool.mesh \
                or src_pool.axis_name != dst_pool.axis_name:
            raise ValueError(
                "local transfer needs src and dst pools on ONE mesh/"
                "axis; cross-mesh transfers go over the object lanes "
                "(pack/unpack_into)")
        if src_pool.kv_dim != dst_pool.kv_dim \
                or src_pool.n_layers != dst_pool.n_layers:
            raise ValueError(
                f"pool shape mismatch: src (layers={src_pool.n_layers}, "
                f"kv_dim={src_pool.kv_dim}) vs dst "
                f"(layers={dst_pool.n_layers}, kv_dim={dst_pool.kv_dim})")
        axis = src_pool.axis_name
        copy_rows = min(src_pool.max_total, dst_pool.max_total)
        s_spec = _shard_axis_of(src_pool.cache_spec, axis)
        d_spec = _shard_axis_of(dst_pool.cache_spec, axis)
        src_specs = [(src_pool.cache_spec, src_pool.cache_spec)
                     for _ in range(src_pool.n_layers)]
        dst_specs = [(dst_pool.cache_spec, dst_pool.cache_spec)
                     for _ in range(dst_pool.n_layers)]

        def body(src_caches, dst_caches, src_slot, dst_slot):
            out = []
            for (ks, vs), (kd, vd) in zip(src_caches, dst_caches):
                k_row = jax.lax.dynamic_index_in_dim(ks, src_slot, axis=0,
                                                     keepdims=True)
                v_row = jax.lax.dynamic_index_in_dim(vs, src_slot, axis=0,
                                                     keepdims=True)
                k_row = k_row[:, :copy_rows]
                v_row = v_row[:, :copy_rows]
                # the portable redistribution primitive: identity while
                # both pools shard the KV columns identically, the
                # minimal accounted collective the moment they differ
                k_row = reshard(k_row, s_spec, d_spec, axis)
                v_row = reshard(v_row, s_spec, d_spec, axis)
                start = (dst_slot, 0, 0)
                out.append(
                    (jax.lax.dynamic_update_slice(
                        kd, k_row.astype(kd.dtype), start),
                     jax.lax.dynamic_update_slice(
                        vd, v_row.astype(vd.dtype), start)))
            return out

        return jax.jit(shard_map(
            body, mesh=src_pool.mesh,
            in_specs=(src_specs, dst_specs, P(), P()),
            out_specs=dst_specs))

    def local_program(self, src_pool, dst_pool):
        """The compiled (src-pool, dst-pool) transfer program — cached;
        the analysis entry point probes it for recompiles."""
        key = self._local_key(src_pool, dst_pool)
        prog = self._programs.get(key)
        if prog is None:
            prog = self._programs[key] = self._build_local(src_pool,
                                                           dst_pool)
            from ..observability import flight as _flight
            _flight.note("compile", program="serving_kv_transfer",
                         family_size=len(self._programs))
        return prog

    def transfer_local(self, src_pool, src_slot: int, dst_pool,
                       dst_slot: int, length: int) -> Dict[str, Any]:
        """Move slot ``src_slot``'s slab into ``dst_slot`` on the same
        mesh and set ``dst_pool.pos[dst_slot] = length``.  Returns the
        transfer stats row (mode, ms, ledger bytes)."""
        import jax.numpy as jnp

        copy_rows = min(src_pool.max_total, dst_pool.max_total)
        if not (0 < int(length) <= copy_rows):
            raise ValueError(
                f"transfer length {length} out of range (0, {copy_rows}] "
                f"(src max_total {src_pool.max_total}, dst "
                f"{dst_pool.max_total})")
        prog = self.local_program(src_pool, dst_pool)
        t0 = time.monotonic()
        dst_pool.caches = prog(src_pool.caches, dst_pool.caches,
                               jnp.int32(src_slot), jnp.int32(dst_slot))
        dst_pool.pos[dst_slot] = int(length)
        ms = (time.monotonic() - t0) * 1e3
        self.transfers += 1
        self.last_transfer_ms = ms
        axis = src_pool.axis_name
        cost = transfer_cost(
            src_pool.n_layers, length, src_pool.kv_dim,
            src_pool.caches[0][0].dtype, mode="local",
            axis_size=src_pool.mesh.shape[axis],
            src_spec=_shard_axis_of(src_pool.cache_spec, axis),
            dst_spec=_shard_axis_of(dst_pool.cache_spec, axis),
            copy_rows=copy_rows)
        return {"mode": "local", "ms": ms,
                "ledger_bytes": cost["ledger_bytes"],
                "length": int(length)}

    # ------------------------------------------------------------------
    # cross-process: pack -> object lane -> unpack_into
    # ------------------------------------------------------------------
    def pack(self, src_pool, src_slot: int, length: int,
             meta: Dict[str, Any]) -> bytes:
        """Serialize slot ``src_slot``'s written rows ``[0, length)``
        plus the request wire dict.  Host-side numpy throughout — the
        payload is transport-agnostic bytes."""
        import jax

        if not (0 < int(length) <= src_pool.max_total):
            raise ValueError(f"pack length {length} out of range "
                             f"(0, {src_pool.max_total}]")
        rows = []
        for kc, vc in src_pool.caches:
            rows.append((np.asarray(jax.device_get(kc[src_slot, :length])),
                         np.asarray(jax.device_get(vc[src_slot, :length]))))
        return pickle.dumps({
            "schema": WIRE_SCHEMA,
            "meta": dict(meta),
            "pos": int(length),
            "n_layers": src_pool.n_layers,
            "kv_dim": src_pool.kv_dim,
            "dtype": str(rows[0][0].dtype),
            # end-to-end integrity stamp (ISSUE 12): the receiver
            # recomputes this over the decoded rows and REFUSES a
            # mismatch — a corrupt slab degrades to re-prefill, it is
            # never served
            "crc32": slab_crc32(rows),
            "rows": rows,
        }, protocol=pickle.HIGHEST_PROTOCOL)

    def lane_put(self, tag: str, payload: bytes) -> None:
        """Publish a packed slab on the object lane, under the hardened
        retry discipline — the flight ring records every retry and the
        terminal fault NAMES the lane (``kv_transfer/put/<tag>``)."""
        from ..communicators.base import lane_call

        lane_call(f"kv_transfer/put/{tag}",
                  lambda: self.transport.put(tag, payload),
                  self.lane_config)

    def lane_get(self, tag: str, timeout_s: float = 10.0) -> bytes:
        from ..communicators.base import lane_call

        return lane_call(
            f"kv_transfer/get/{tag}",
            lambda: self.transport.get(tag, timeout_s),
            self.lane_config)

    def lane_delete(self, tag: str) -> None:
        from ..communicators.base import lane_call

        lane_call(f"kv_transfer/gc/{tag}",
                  lambda: self.transport.delete(tag), self.lane_config)

    def inject_program(self, dst_pool):
        """The pool-lifetime compiled slab WRITE — the landing half of
        every lane-mode transfer (and the ``serving.worker_lane``
        analysis entry point's program): host-padded slab rows
        ``dynamic_update_slice``\\ d into the destination slot, slot
        index a traced operand so every landing after the first hits
        the jit cache.  Zero collectives: each TP rank writes its local
        KV columns."""
        import jax
        from jax.sharding import PartitionSpec as P

        from .._compat import shard_map

        key = (dst_pool.n_layers, dst_pool.n_slots, dst_pool.max_total,
               dst_pool.kv_dim, str(dst_pool.caches[0][0].dtype),
               id(dst_pool.mesh))
        prog = self._inject_programs.get(key)
        if prog is None:
            dst_specs = [(dst_pool.cache_spec, dst_pool.cache_spec)
                         for _ in range(dst_pool.n_layers)]
            # a slab row is the cache row minus the slot dim: same
            # column sharding, one rank lower
            row_spec = P(*tuple(dst_pool.cache_spec)[1:])
            slab_specs = [(row_spec, row_spec)
                          for _ in range(dst_pool.n_layers)]

            def body(dst_caches, slabs, dst_slot):
                out = []
                for (kd, vd), (ks, vs) in zip(dst_caches, slabs):
                    start = (dst_slot, 0, 0)
                    out.append(
                        (jax.lax.dynamic_update_slice(
                            kd, ks[None].astype(kd.dtype), start),
                         jax.lax.dynamic_update_slice(
                            vd, vs[None].astype(vd.dtype), start)))
                return out

            prog = self._inject_programs[key] = jax.jit(shard_map(
                body, mesh=dst_pool.mesh,
                in_specs=(dst_specs, slab_specs, P()),
                out_specs=dst_specs))
            from ..observability import flight as _flight
            _flight.note("compile", program="serving_kv_inject")
        return prog

    def unpack_into(self, payload: bytes, dst_pool, dst_slot: int, *,
                    ledger_op: str = LANE_OP,
                    ledger_axis: str = LANE_AXIS) -> Dict[str, Any]:
        """Inject a packed slab into ``dst_slot`` (compiled pool-
        lifetime slab write; the host pads the slab to the pool row so
        the program needs no length operand) and book the RAW slab
        bytes as a noted ``ledger_op@ledger_axis`` row — by default the
        ``kv_transfer_lane@dcn`` key, the exact
        :func:`transfer_cost(mode="lanes")` prediction; the host spill
        tier restores under ``kv_spill_restore@host`` so its traffic
        never pollutes the DCN wire-byte gate (ISSUE 12).  The payload's
        CRC32 stamp is verified BEFORE anything touches the pool: a
        corrupt or foreign slab is refused with :class:`ValueError`,
        never decoded.  Returns the wire dict's ``meta`` + transfer
        stats."""
        import jax.numpy as jnp

        t0 = time.monotonic()
        data = pickle.loads(payload)
        if data.get("schema") != WIRE_SCHEMA:
            raise ValueError(
                f"refusing KV transfer with schema "
                f"{data.get('schema')!r} (this receiver speaks "
                f"{WIRE_SCHEMA})")
        if data["n_layers"] != dst_pool.n_layers \
                or data["kv_dim"] != dst_pool.kv_dim:
            raise ValueError(
                f"slab shape mismatch: wire (layers={data['n_layers']}, "
                f"kv_dim={data['kv_dim']}) vs pool "
                f"(layers={dst_pool.n_layers}, kv_dim={dst_pool.kv_dim})")
        length = int(data["pos"])
        if length > dst_pool.max_total:
            raise ValueError(
                f"slab length {length} exceeds destination per-slot "
                f"capacity {dst_pool.max_total}")
        want_crc = data.get("crc32")
        if want_crc is not None:
            got_crc = slab_crc32(data["rows"])
            if got_crc != int(want_crc):
                raise ValueError(
                    f"refusing KV transfer: CRC mismatch (payload says "
                    f"{int(want_crc):#010x}, rows hash {got_crc:#010x}) "
                    f"— the slab was corrupted in transit/storage and "
                    f"must re-prefill, never serve")

        prog = self.inject_program(dst_pool)
        # pad each layer's rows to the pool row (rows above ``length``
        # are stale-but-unreachable, the standard masking argument)
        slabs = []
        dt = dst_pool.caches[0][0].dtype
        for k, v in data["rows"]:
            kp = np.zeros((dst_pool.max_total, dst_pool.kv_dim),
                          np.asarray(k).dtype)
            vp = np.zeros_like(kp)
            kp[:length] = k
            vp[:length] = v
            slabs.append((jnp.asarray(kp.astype(dt)),
                          jnp.asarray(vp.astype(dt))))
        dst_pool.caches = prog(dst_pool.caches, slabs,
                               jnp.int32(dst_slot))
        dst_pool.pos[dst_slot] = length

        nbytes = slab_nbytes(data["n_layers"], length, data["kv_dim"],
                             data["dtype"])
        ms = (time.monotonic() - t0) * 1e3
        self.transfers += 1
        self.lane_transfers += 1
        self.bytes_moved += nbytes
        self.last_transfer_ms = ms
        # comm-ledger booking (the acceptance contract: every transfer
        # priced, byte-exact vs transfer_cost) — noted, like the
        # AD-inserted gradient psum: traffic no collective wrapper sees
        from ..observability import comm as _comm
        from ..observability import trace as _trace
        if _trace.get_tracer().enabled:
            _comm.get_accountant().record(
                ledger_op, ledger_axis, nbytes, data["dtype"],
                in_jit=False, latency_s=ms / 1e3, noted=True)
        return {"mode": "lanes", "ms": ms, "ledger_bytes": nbytes,
                "wire_payload_bytes": len(payload), "length": length,
                "meta": data["meta"]}

    def stats(self) -> Dict[str, float]:
        return {
            "transfers": float(self.transfers),
            "lane_transfers": float(self.lane_transfers),
            "bytes_moved": float(self.bytes_moved),
            "last_transfer_ms": float(self.last_transfer_ms),
            "programs": float(len(self._programs)
                              + len(self._inject_programs)),
        }
