"""Continuous-batching inference serving (iteration-level scheduling).

The ROADMAP north star is a system that "serves heavy traffic from
millions of users"; the decode stack (``parallel/decode.py``,
``ops/kv_cache.py``) is the fast half of that story, and this package is
the serving half: instead of the closed batch-synchronous ``lax.scan``
of ``lm_generate`` — which admits requests only at t=0 and holds the
whole batch until the longest sequence finishes — the engine here runs
ONE compiled decode tick per iteration over a fixed pool of KV-cache
slots and inserts/evicts sequences BETWEEN ticks (Orca-style
iteration-level scheduling, the batching model vLLM popularized).

Layers (host → device):

* :mod:`~chainermn_tpu.serving.scheduler` — bounded admission queue with
  reject-with-reason backpressure, FIFO admission into free slots,
  EOS/length/deadline eviction.  Pure host Python, jax-free.
* :mod:`~chainermn_tpu.serving.cache_pool` — the slot-managed KV-cache
  pool: per-layer ``(n_slots, max_total, H_kv·head_dim)`` device
  buffers + a per-slot write-position vector; freed slots are recycled
  without reallocation or re-jit (the tick program's shapes never
  change).
* :mod:`~chainermn_tpu.serving.engine` — the compiled per-tick step
  (``prefill(prompt) → slot``, ``tick(slots) → one token per active
  slot``) built from ``parallel/decode.py``'s ``lm_prefill`` /
  ``lm_decode_tick``.
* :mod:`~chainermn_tpu.serving.frontend` — the threaded Python API:
  ``ServingEngine.submit() -> RequestHandle`` with streaming token
  callbacks, plus the observability wiring (per-request phase
  timestamps/spans, serving gauges through the tracer and the
  Prometheus/JSONL exporters).

Fleet layer (ISSUE 7; docs/SERVING.md "Router, prefix cache &
admission"):

* :mod:`~chainermn_tpu.serving.prefix_cache` — radix-trie prefix cache
  over donated read-only KV slots (refcounted; LRU-scavenged), so
  shared system prompts skip re-prefill via the engine's compiled
  copy-on-extend path.
* :mod:`~chainermn_tpu.serving.replica` / :mod:`~chainermn_tpu.serving
  .router` — N engines behind one :class:`ServingRouter`: least-loaded
  deadline-aware prefix-affine dispatch, SLO-burn-driven shedding with
  machine-readable rejections, fleet-wide metrics//statusz roll-up.

Disaggregated layer (ISSUE 9; docs/SERVING.md "Disaggregated
prefill/decode"):

* :mod:`~chainermn_tpu.serving.transfer` — the KV-transfer plane:
  finished prefill slabs move between pools over the PR 8 reshard
  primitive same-process or the hardened DCN object lanes across
  processes, every transfer ledger-booked and statically priced.
* :mod:`~chainermn_tpu.serving.disagg` — role-split workers
  (:class:`PrefillWorker` runs only the prefill programs,
  :class:`DecodeWorker` only the compiled tick) behind a role-aware
  :class:`DisaggRouter` (prompts → least-loaded prefill worker, slabs
  → decode worker by free slots + deadline feasibility).

Cross-process layer (ISSUE 10; docs/ROBUSTNESS.md "Serving failure
domains"):

* :mod:`~chainermn_tpu.serving.lanes` — the elastic object-lane
  transport (:class:`FileLaneStore` over a shared directory for
  unrelated processes) plus single-writer mailboxes, every operation
  under the hardened ``lane_call`` discipline.
* :mod:`~chainermn_tpu.serving.health` — heartbeat leases, epoch
  fencing (a zombie's stale writes are refused and counted), and the
  per-worker circuit breaker governing re-admission.
* :mod:`~chainermn_tpu.serving.worker` — the per-PROCESS role loops
  (``engine`` / ``prefill`` / ``decode``) the fleet spawner execs;
  drain finishes in-flight work and exits 0.
* :mod:`~chainermn_tpu.serving.fleet` — :class:`FleetRouter`:
  lease-driven dispatch, death detection within the configured window,
  in-flight request failover (re-dispatch or machine-readable
  ``worker_lost`` shed), ``drain(worker)`` rolling restart, and
  :func:`submit_with_retry` (the client-side honor of
  ``retry_after_ms``).

Fleet KV economy (ISSUE 12; docs/SERVING.md "Fleet KV economy"):

* :mod:`~chainermn_tpu.serving.fleet_cache` —
  :class:`FleetCacheIndex`: the router's soft-state radix trie over
  every worker's ANNOUNCED prefix-cache entries (epoch-fenced, rebuilt
  on re-admission); a local miss with a remote hit becomes a priced
  REMOTE PULL over the KV-transfer plane instead of a re-prefill.
* :mod:`~chainermn_tpu.serving.spill` — :class:`HostSpillStore`: the
  bounded host-RAM spill tier evicted prefix slabs fall into
  (CRC-verified ``kv_transfer.v1`` payloads); a later hit restores
  through the compiled inject path instead of re-prefilling.

Scenario plane & heterogeneous fleet (ISSUE 18; docs/SERVING.md
"Scenario engine & heterogeneous fleet"):

* :mod:`~chainermn_tpu.serving.scenarios` — the seeded, replayable
  workload engine: jax-free generators (diurnal, flash crowd,
  adversarial tenants, mixed deadlines, composed chaos) emitting the
  deterministic ``chainermn_tpu.scenario.v1`` event stream, plus
  :func:`~chainermn_tpu.serving.scenarios.run_scenario` replaying it
  in scaled wall-clock against a real fleet.
* :mod:`~chainermn_tpu.serving.models` — :class:`ModelRegistry`:
  multiple model variants (and weight GENERATIONS) behind one
  :class:`FleetRouter`; ``model_id`` rides the hello/lease wire, and
  :func:`~chainermn_tpu.serving.fleet.rolling_upgrade` installs a new
  checkpoint generation worker-by-worker with zero restart and zero
  shed (docs/ROBUSTNESS.md "Rolling weight upgrade").

``python -m chainermn_tpu.serve`` is the CLI demo over the toy-corpus
LM from ``examples/generate`` (``--replicas N`` stands up the fleet,
``--disagg P:D`` the disaggregated topology, ``--fleet-procs N`` the
cross-process gang).  See docs/SERVING.md.
"""

from .scheduler import (  # noqa: F401
    AdmissionError,
    Request,
    Scheduler,
)
from .cache_pool import SlotAllocator  # noqa: F401
from .fleet_cache import FleetCacheIndex, IndexRecord  # noqa: F401
from .prefix_cache import PrefixCache, PrefixEntry  # noqa: F401
from .spill import HostSpillStore  # noqa: F401 — jax-free spill tier
from .tenancy import (  # noqa: F401 — jax-free, like the scheduler
    DegradationLadder,
    Tenant,
    TenantTable,
)

__all__ = ["AdmissionError", "Request", "Scheduler", "SlotAllocator",
           "PrefixCache", "PrefixEntry",
           "FleetCacheIndex", "IndexRecord", "HostSpillStore",
           "TenantTable", "Tenant", "DegradationLadder",
           "AutoscalePolicy", "FleetAutoscaler", "derive_retry_after_ms",
           "ServingEngine", "RequestHandle", "CachePool", "DecodeEngine",
           "Replica", "ServingRouter", "build_fleet",
           "KvTransferPlane", "DisaggRouter", "PrefillWorker",
           "DecodeWorker", "build_disagg_fleet",
           "FileLaneStore", "WorkerRuntime", "FleetRouter",
           "WorkerClient", "build_proc_fleet", "build_local_fleet",
           "submit_with_retry", "rolling_upgrade",
           "ModelRegistry", "ModelVariant",
           "SCENARIO_SCHEMA", "build_scenario", "run_scenario",
           "stream_digest", "materialize_prompt"]


def __getattr__(name):
    # The device-side halves import jax; keep `import chainermn_tpu.serving`
    # cheap for host-only consumers (the scheduler + prefix-trie fuzz
    # tests).
    if name in ("ServingEngine", "RequestHandle"):
        from . import frontend
        return getattr(frontend, name)
    if name == "CachePool":
        from .cache_pool import CachePool
        return CachePool
    if name == "DecodeEngine":
        from .engine import DecodeEngine
        return DecodeEngine
    if name == "Replica":
        from .replica import Replica
        return Replica
    if name in ("ServingRouter", "build_fleet"):
        from . import router
        return getattr(router, name)
    if name == "KvTransferPlane":
        from .transfer import KvTransferPlane
        return KvTransferPlane
    if name in ("DisaggRouter", "PrefillWorker", "DecodeWorker",
                "build_disagg_fleet"):
        from . import disagg
        return getattr(disagg, name)
    if name == "FileLaneStore":
        from .lanes import FileLaneStore
        return FileLaneStore
    if name == "WorkerRuntime":
        from .worker import WorkerRuntime
        return WorkerRuntime
    if name in ("FleetRouter", "WorkerClient", "build_proc_fleet",
                "build_local_fleet", "submit_with_retry",
                "rolling_upgrade"):
        from . import fleet
        return getattr(fleet, name)
    if name in ("ModelRegistry", "ModelVariant"):
        from . import models
        return getattr(models, name)
    if name in ("SCENARIO_SCHEMA", "build_scenario", "run_scenario",
                "stream_digest", "materialize_prompt"):
        from . import scenarios
        return getattr(scenarios, name)
    if name in ("AutoscalePolicy", "FleetAutoscaler",
                "derive_retry_after_ms"):
        from . import autoscale
        return getattr(autoscale, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
