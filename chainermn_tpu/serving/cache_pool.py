"""Slot-managed KV-cache pool for continuous-batching decode.

The pool is the device half of iteration-level scheduling: ONE set of
per-layer flat K/V buffers shaped ``(n_slots, max_total, H_kv·head_dim)``
(the same flat layout ``parallel/decode.py`` streams at full lane
density), allocated once, plus a per-slot int32 write-position vector.
Admitting a request means writing its prefill slab into a free slot's
rows ``[0, s_p)`` and setting ``pos[slot] = s_p``; every decode tick
appends one row per slot at its own ``pos`` (the per-row vector
``ops.kv_cache.cache_append`` path) and advances it; eviction just
returns the slot index to the free list.  Nothing is reallocated and
nothing re-jits: the tick program's operand shapes are fixed for the
pool's lifetime, which is the whole point — a freed slot is recycled by
the NEXT prefill while the other slots keep decoding.

Correctness of recycling without zeroing: a slot's rows ``> pos`` may
hold a previous occupant's K/V, but every attention read is masked to
the occupant's own prefix ``[0, pos]``, and row ``p`` is written by the
current occupant strictly before ``pos`` reaches ``p`` (prefill writes
``[0, s_p)``; each tick writes row ``pos`` before attending it).  Stale
rows are therefore unreachable — asserted token-exactly by the
cross-talk fuzz in tests/test_serving.py.

:class:`SlotAllocator` is the jax-free bookkeeping half (fuzzable
standalone); :class:`CachePool` adds the device buffers.

Transfer-destination reservations (ISSUE 9): the disaggregated fleet
lands finished prefill KV slabs into a DECODE worker's slot, and the
destination must be held from the moment the transfer is chosen until
the slab arrives — otherwise the worker's own admission path (which
admits up to ``min(free_slots, max_prefills_per_tick)``) can take the
slot out from under an in-flight transfer, and a burst of arriving
slabs deadlocks against admission.  Reservations are therefore
FIRST-CLASS allocator state: ``reserve()`` moves a slot free →
reserved (it no longer counts in ``free_count``, so admission can never
see it), ``commit_reservation()`` promotes it to busy when the slab
lands, and ``cancel_reservation()`` returns it to the free list when
the transfer fails (lane fault, dead source worker).  The invariants
are hard errors for the same reason double-release is: a leaked
reservation silently shrinks the pool forever.

Spill-tier extension (ISSUE 12): evicting a cached rc==0 slot no longer
simply frees its K/V — the frontend packs the slab (CRC-stamped
``chainermn_tpu.kv_transfer.v1`` payload) into the bounded host-RAM
spill store (``spill.py``) BEFORE ``uncache`` resets the position, and
a later matching prompt re-lands it through the compiled inject path.
The allocator is untouched by the tier: spill rides the existing
``cached → free`` transition via the prefix cache's pre-evict hook, so
every slot-state invariant below holds unchanged.

Prefix-cache extension (ISSUE 7): a slot now has THREE states, not two
— ``free`` (on the free list), ``busy`` (a live request's K/V), and
``cached`` (a finished request's prompt K/V donated to the radix-trie
prefix cache as a READ-ONLY shared prefix, with a refcount of the
in-flight requests currently built on it).  Cached slots are
*scavengeable* capacity: admission treats an rc==0 cached slot as
free-after-eviction, so the prefix cache can never starve decoding —
it only borrows slots nobody needs yet.  Refcounts are the allocator's
(hard-error) invariants for the same reason double-release is: a leaked
ref pins a slot forever, silently shrinking the pool.
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional

from ..observability import journal as _journal

#: Distinguishes interleaved allocators in ONE process's journal (an
#: in-process fleet runs several engines, each with its own pool).
_ALLOC_IDS = itertools.count()


class SlotAllocator:
    """Free/busy/cached slot bookkeeping: acquire → busy, release →
    recycled, cache → read-only prefix slot (refcounted) until evicted.

    Slots are handed out lowest-index-first (deterministic for tests);
    double-release, foreign releases, and refcount underflow raise — a
    slot leak in a serving loop is silent capacity loss, so the
    invariants are hard errors.
    """

    def __init__(self, n_slots: int):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.n_slots = int(n_slots)
        self._free: List[int] = list(range(self.n_slots))
        self._busy: set = set()
        self._cached: Dict[int, int] = {}   # slot -> refcount
        self._reserved: set = set()         # in-flight transfer dests
        # the disagg fleet's role-parallel drive reserves from the
        # prefill thread while commit/cancel/release run on the decode
        # thread — every state transition is a compound read-then-write,
        # so the lock is load-bearing, not defensive
        self._lock = threading.Lock()
        # the conformance monitor replays these against the ISSUE 15
        # slot_lifecycle model — op=init carries the universe size
        self._aid = next(_ALLOC_IDS)
        self._jemit("init", n_slots=self.n_slots)

    def _jemit(self, op: str, **fields) -> None:
        _journal.emit("slot", op=op, alloc=self._aid, **fields)

    def acquire(self) -> Optional[int]:
        """Lowest free slot index, or None when the pool is saturated."""
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop(0)
            self._busy.add(slot)
        self._jemit("acquire", slot=slot)
        return slot

    def release(self, slot: int) -> None:
        with self._lock:
            if slot not in self._busy:
                raise ValueError(
                    f"slot {slot} is not busy (double release or "
                    f"foreign slot); busy={sorted(self._busy)}")
            self._busy.remove(slot)
            # keep the free list sorted so acquisition order is
            # deterministic
            self._free.append(slot)
            self._free.sort()
        self._jemit("release", slot=slot)

    # ---- transfer-destination reservations: free -> reserved -> busy ----
    def reserve(self) -> Optional[int]:
        """Hold the lowest free slot for an in-flight KV transfer, or
        None when the pool is saturated.  A reserved slot is invisible
        to ``acquire``/``free_count`` — admission can never race the
        arriving slab for it."""
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop(0)
            self._reserved.add(slot)
        self._jemit("reserve", slot=slot)
        return slot

    def commit_reservation(self, slot: int) -> None:
        """The slab landed: promote the reservation to a busy slot."""
        with self._lock:
            if slot not in self._reserved:
                raise ValueError(
                    f"slot {slot} is not reserved (commit "
                    f"without reserve, or double commit); "
                    f"reserved={sorted(self._reserved)}")
            self._reserved.remove(slot)
            self._busy.add(slot)
        self._jemit("commit_reservation", slot=slot)

    def cancel_reservation(self, slot: int) -> None:
        """The transfer failed: return the held slot to the free list."""
        with self._lock:
            if slot not in self._reserved:
                raise ValueError(
                    f"slot {slot} is not reserved (cancel "
                    f"without reserve, or double cancel); "
                    f"reserved={sorted(self._reserved)}")
            self._reserved.remove(slot)
            self._free.append(slot)
            self._free.sort()
        self._jemit("cancel_reservation", slot=slot)

    # ---- prefix-cache faces: busy -> cached(rc) -> free ----
    def cache(self, slot: int) -> None:
        """Donate a busy slot to the prefix cache (read-only, rc=0)."""
        with self._lock:
            if slot not in self._busy:
                raise ValueError(f"slot {slot} is not busy (only a live "
                                 f"request's slot can be donated); "
                                 f"busy={sorted(self._busy)}")
            self._busy.remove(slot)
            self._cached[slot] = 0
        self._jemit("cache", slot=slot)

    def retain(self, slot: int) -> int:
        """Pin a cached slot for one more in-flight reader."""
        with self._lock:
            if slot not in self._cached:
                raise ValueError(f"slot {slot} is not cached; "
                                 f"cached={sorted(self._cached)}")
            self._cached[slot] += 1
            rc = self._cached[slot]
        self._jemit("retain", slot=slot)
        return rc

    def unretain(self, slot: int) -> int:
        with self._lock:
            if slot not in self._cached:
                raise ValueError(f"slot {slot} is not cached; "
                                 f"cached={sorted(self._cached)}")
            if self._cached[slot] <= 0:
                raise ValueError(f"slot {slot} refcount underflow "
                                 f"(double unretain)")
            self._cached[slot] -= 1
            rc = self._cached[slot]
        self._jemit("unretain", slot=slot)
        return rc

    def uncache(self, slot: int) -> None:
        """Evict a cached slot back to the free list (rc must be 0: an
        entry someone is still built on must never be recycled)."""
        with self._lock:
            rc = self._cached.get(slot)
            if rc is None:
                raise ValueError(f"slot {slot} is not cached; "
                                 f"cached={sorted(self._cached)}")
            if rc != 0:
                raise ValueError(f"slot {slot} still has {rc} reader(s); "
                                 f"refusing to evict a pinned prefix")
            del self._cached[slot]
            self._free.append(slot)
            self._free.sort()
        self._jemit("uncache", slot=slot)

    def refcount(self, slot: int) -> Optional[int]:
        return self._cached.get(slot)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def busy_count(self) -> int:
        return len(self._busy)

    @property
    def cached_count(self) -> int:
        return len(self._cached)

    @property
    def reserved_count(self) -> int:
        return len(self._reserved)

    def check_invariants(self) -> None:
        """No leak, no alias: free ∪ busy ∪ cached ∪ reserved is exactly
        {0..n_slots-1}, pairwise disjoint, and every refcount >= 0."""
        free, busy = set(self._free), set(self._busy)
        cached, reserved = set(self._cached), set(self._reserved)
        assert not (free & busy), (free, busy)
        assert not (free & cached), (free, cached)
        assert not (busy & cached), (busy, cached)
        assert not (reserved & (free | busy | cached)), \
            (reserved, free, busy, cached)
        assert free | busy | cached | reserved \
            == set(range(self.n_slots)), (free, busy, cached, reserved)
        assert all(rc >= 0 for rc in self._cached.values()), self._cached


class CachePool:
    """Device-buffer half: per-layer flat K/V pools + per-slot positions.

    ``caches`` is the pytree the engine's compiled programs thread
    through (list of ``(k, v)`` per layer, each ``(n_slots, max_total,
    kv_dim)`` sharded ``P(None, None, axis)`` over the model axis — each
    chip holds only its local heads' columns, exactly the closed-batch
    decoder's TP layout).  ``pos`` lives HOST-side as numpy (the
    scheduler reads/writes it every tick; shipping it to device happens
    once per tick as a tiny operand).
    """

    def __init__(self, n_slots: int, max_total: int, n_layers: int,
                 kv_dim: int, dtype, mesh, axis_name: str = "model"):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        if max_total < 2:
            raise ValueError(f"max_total must be >= 2, got {max_total}")
        self.allocator = SlotAllocator(n_slots)
        self.n_slots = int(n_slots)
        self.max_total = int(max_total)
        self.n_layers = int(n_layers)
        self.kv_dim = int(kv_dim)
        self.axis_name = axis_name
        self.mesh = mesh
        self.cache_spec = P(None, None, axis_name)
        sharding = NamedSharding(mesh, self.cache_spec)
        shape = (self.n_slots, self.max_total, self.kv_dim)
        self.caches = [
            (jax.device_put(jnp.zeros(shape, dtype), sharding),
             jax.device_put(jnp.zeros(shape, dtype), sharding))
            for _ in range(self.n_layers)]
        # host-side per-slot NEXT-WRITE position (== sequence length so
        # far).  The tick advances EVERY slot's pos (one fixed program),
        # so a free slot's position drifts upward until the next prefill
        # resets it; its garbage writes land at the drifting row (clamped
        # to max_total-1 by dynamic_update_slice) INSIDE ITS OWN SLOT
        # ROW, which stays safe by the module-docstring argument: the
        # next occupant rewrites row p before its own pos reaches p.
        self.pos = np.zeros(self.n_slots, np.int32)

    # thin faces over the allocator (the frontend talks to the pool)
    def acquire(self) -> Optional[int]:
        return self.allocator.acquire()

    def release(self, slot: int) -> None:
        self.pos[slot] = 0
        self.allocator.release(slot)

    # transfer-destination reservations (ISSUE 9).  The committing
    # caller (the KV-transfer plane) sets ``pos[slot]`` itself — the
    # landed slab's length is transfer metadata the pool cannot know.
    def reserve(self) -> Optional[int]:
        return self.allocator.reserve()

    def commit_reservation(self, slot: int) -> None:
        self.allocator.commit_reservation(slot)

    def cancel_reservation(self, slot: int) -> None:
        self.pos[slot] = 0
        self.allocator.cancel_reservation(slot)

    # prefix-cache faces.  A cached slot's ``pos`` is deliberately NOT
    # reset: the tick still advances every slot's position, so the
    # cached slot's garbage writes keep landing at its drifting pos —
    # strictly ABOVE the donated prefix length — leaving the read-only
    # rows [0, length) intact for the copy-on-extend path (the same
    # above-``pos`` unreachability argument as free-slot recycling).
    def cache(self, slot: int) -> None:
        self.allocator.cache(slot)

    def uncache(self, slot: int) -> None:
        self.pos[slot] = 0
        self.allocator.uncache(slot)

    def retain(self, slot: int) -> int:
        return self.allocator.retain(slot)

    def unretain(self, slot: int) -> int:
        return self.allocator.unretain(slot)

    @property
    def free_count(self) -> int:
        return self.allocator.free_count

    @property
    def busy_count(self) -> int:
        return self.allocator.busy_count

    @property
    def cached_count(self) -> int:
        return self.allocator.cached_count

    @property
    def reserved_count(self) -> int:
        return self.allocator.reserved_count
