"""Slot-managed KV-cache pool for continuous-batching decode.

The pool is the device half of iteration-level scheduling: ONE set of
per-layer flat K/V buffers shaped ``(n_slots, max_total, H_kv·head_dim)``
(the same flat layout ``parallel/decode.py`` streams at full lane
density), allocated once, plus a per-slot int32 write-position vector.
Admitting a request means writing its prefill slab into a free slot's
rows ``[0, s_p)`` and setting ``pos[slot] = s_p``; every decode tick
appends one row per slot at its own ``pos`` (the per-row vector
``ops.kv_cache.cache_append`` path) and advances it; eviction just
returns the slot index to the free list.  Nothing is reallocated and
nothing re-jits: the tick program's operand shapes are fixed for the
pool's lifetime, which is the whole point — a freed slot is recycled by
the NEXT prefill while the other slots keep decoding.

Correctness of recycling without zeroing: a slot's rows ``> pos`` may
hold a previous occupant's K/V, but every attention read is masked to
the occupant's own prefix ``[0, pos]``, and row ``p`` is written by the
current occupant strictly before ``pos`` reaches ``p`` (prefill writes
``[0, s_p)``; each tick writes row ``pos`` before attending it).  Stale
rows are therefore unreachable — asserted token-exactly by the
cross-talk fuzz in tests/test_serving.py.

:class:`SlotAllocator` is the jax-free bookkeeping half (fuzzable
standalone); :class:`CachePool` adds the device buffers.
"""

from __future__ import annotations

from typing import List, Optional


class SlotAllocator:
    """Free-list slot bookkeeping: acquire → occupied, release → recycled.

    Slots are handed out lowest-index-first (deterministic for tests);
    double-release and foreign releases raise — a slot leak in a serving
    loop is silent capacity loss, so the invariants are hard errors.
    """

    def __init__(self, n_slots: int):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.n_slots = int(n_slots)
        self._free: List[int] = list(range(self.n_slots))
        self._busy: set = set()

    def acquire(self) -> Optional[int]:
        """Lowest free slot index, or None when the pool is saturated."""
        if not self._free:
            return None
        slot = self._free.pop(0)
        self._busy.add(slot)
        return slot

    def release(self, slot: int) -> None:
        if slot not in self._busy:
            raise ValueError(f"slot {slot} is not busy (double release or "
                             f"foreign slot); busy={sorted(self._busy)}")
        self._busy.remove(slot)
        # keep the free list sorted so acquisition order is deterministic
        self._free.append(slot)
        self._free.sort()

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def busy_count(self) -> int:
        return len(self._busy)

    def check_invariants(self) -> None:
        """No leak, no alias: free ∪ busy is exactly {0..n_slots-1}."""
        free, busy = set(self._free), set(self._busy)
        assert not (free & busy), (free, busy)
        assert free | busy == set(range(self.n_slots)), (free, busy)


class CachePool:
    """Device-buffer half: per-layer flat K/V pools + per-slot positions.

    ``caches`` is the pytree the engine's compiled programs thread
    through (list of ``(k, v)`` per layer, each ``(n_slots, max_total,
    kv_dim)`` sharded ``P(None, None, axis)`` over the model axis — each
    chip holds only its local heads' columns, exactly the closed-batch
    decoder's TP layout).  ``pos`` lives HOST-side as numpy (the
    scheduler reads/writes it every tick; shipping it to device happens
    once per tick as a tiny operand).
    """

    def __init__(self, n_slots: int, max_total: int, n_layers: int,
                 kv_dim: int, dtype, mesh, axis_name: str = "model"):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        if max_total < 2:
            raise ValueError(f"max_total must be >= 2, got {max_total}")
        self.allocator = SlotAllocator(n_slots)
        self.n_slots = int(n_slots)
        self.max_total = int(max_total)
        self.n_layers = int(n_layers)
        self.kv_dim = int(kv_dim)
        self.axis_name = axis_name
        self.mesh = mesh
        self.cache_spec = P(None, None, axis_name)
        sharding = NamedSharding(mesh, self.cache_spec)
        shape = (self.n_slots, self.max_total, self.kv_dim)
        self.caches = [
            (jax.device_put(jnp.zeros(shape, dtype), sharding),
             jax.device_put(jnp.zeros(shape, dtype), sharding))
            for _ in range(self.n_layers)]
        # host-side per-slot NEXT-WRITE position (== sequence length so
        # far).  The tick advances EVERY slot's pos (one fixed program),
        # so a free slot's position drifts upward until the next prefill
        # resets it; its garbage writes land at the drifting row (clamped
        # to max_total-1 by dynamic_update_slice) INSIDE ITS OWN SLOT
        # ROW, which stays safe by the module-docstring argument: the
        # next occupant rewrites row p before its own pos reaches p.
        self.pos = np.zeros(self.n_slots, np.int32)

    # thin faces over the allocator (the frontend talks to the pool)
    def acquire(self) -> Optional[int]:
        return self.allocator.acquire()

    def release(self, slot: int) -> None:
        self.pos[slot] = 0
        self.allocator.release(slot)

    @property
    def free_count(self) -> int:
        return self.allocator.free_count

    @property
    def busy_count(self) -> int:
        return self.allocator.busy_count
