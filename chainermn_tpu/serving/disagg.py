"""Disaggregated prefill/decode serving: role-split workers (ISSUE 9).

Prefill is compute-bound (one whole-prompt forward), decode is
memory-bound (one cache-streaming tick); PR 7's fleet runs both in one
tick budget per replica, so a burst of arrivals steals decode ticks and
inflates every running request's inter-token latency (the
``max_prefills_per_tick`` bound caps, but cannot remove, the
interference).  This module splits the roles:

* :class:`PrefillWorker` — owns a small STAGING pool and runs ONLY the
  per-prompt-length prefill programs (its decode tick is never
  compiled).  A finished prefill's KV slab + request metadata leave
  immediately over the transfer plane and the staging slot is recycled.
* :class:`DecodeWorker` — a :class:`~chainermn_tpu.serving.frontend
  .ServingEngine` that never prefills: requests arrive ONLY as
  transferred slabs landed in reserved slots
  (``ServingEngine.install_request``), so its compiled tick runs
  back-to-back and the decode tick-gap p99 collapses to the tick cost
  (the bench ``serving_disagg`` section measures exactly this).
* :class:`DisaggRouter` — the role-aware composition: prompts dispatch
  to the least-loaded LIVE prefill worker; finished slabs to the decode
  worker chosen by free (reservation-aware) slots + deadline
  feasibility.  Transfers ride
  :class:`~chainermn_tpu.serving.transfer.KvTransferPlane` — the
  compiled reshard path same-process, the hardened DCN object lanes
  across processes — with the transfer wall booked into the prefill
  worker's goodput ledger under its own ``transfer`` bucket.

Drive model: a transfer is SPLIT at the role boundary.  The prefill
side chooses the destination, RESERVES its slot, and (lanes mode)
publishes the packed slab; the landing — lane get/unpack or the
compiled local copy, reservation commit, ``install_request`` — happens
on the DECODE worker's own step, through a per-worker inbox.  That is
the real disaggregated shape (a decode worker's loop is the only thing
that touches its pool) and what makes role-PARALLEL drive race-free:
``start()`` runs one driver thread per role, so a prefill never sits
between two decode ticks and the decode tick-gap p99 collapses to the
tick cost — the ISSUE 9 acceptance metric, measured by the bench
``serving_disagg`` section against the fused engine at the same
offered load.  ``step()``/``run()`` keep the deterministic
single-thread interleave (prefill round, then decode round) for tests.

Failure domain (the one place a :class:`~chainermn_tpu.communicators
.base.DcnLaneError` is CAUGHT in this package): a lane fault during a
transfer kills ONE worker's usefulness, not the gang — the router
cancels the destination reservation (decode workers are never wedged;
the slot returns to the free list), marks the victim dead, dumps a
flight bundle whose ring names the lane, and the request is re-queued
on a surviving prefill worker (a re-prefill — the slab died with the
lane) or, when none survives / the retry budget is spent, shed
machine-readably in the ``AdmissionError.to_dict()`` wire shape
(reason ``worker_lost``).  Everywhere else the lane error still
propagates and the gang dies loudly, as PR 8 specified.

Deadlock freedom (the ISSUE 9 small fix): transfer destinations are
FIRST-CLASS reservations in :class:`~chainermn_tpu.serving.cache_pool
.SlotAllocator` — a reserved slot is invisible to ``free_count``, so a
decode worker's own admission arithmetic can never hand an in-flight
transfer's slot to someone else, and a burst of arriving slabs cannot
deadlock against admission.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .. import observability as obs
from ..communicators.base import DcnLaneError
from ..observability import flight as _flight
from ..observability.slo import (GoodputLedger, ReservoirSample,
                                 SLOTracker, percentile_of)
from .cache_pool import CachePool
from .engine import DecodeEngine
from .frontend import RequestHandle, ServingEngine, _request_row
from .router import RouterBase
from .scheduler import AdmissionError, Request, Scheduler
from .transfer import KvTransferPlane


def request_wire(req: Request, first_tokens) -> Dict[str, Any]:
    """The request metadata that rides the transfer plane with a slab —
    everything a decode worker needs to continue the generation exactly
    (deadline shipped RELATIVE: monotonic clocks do not cross
    processes)."""
    now = time.monotonic()
    return {
        "trace_id": req.trace_id,
        "prompt": [int(t) for t in req.prompt],
        "max_new_tokens": int(req.max_new_tokens),
        "eos_id": req.eos_id,
        "deadline_rel_s": (None if req.deadline_t is None
                           else max(req.deadline_t - now, 0.0)),
        "temperature": float(req.temperature),
        "rng": (None if req.rng is None
                else [int(x) for x in np.asarray(req.rng).reshape(2)]),
        "tokens": [int(t) for t in first_tokens],
    }


class PrefillWorker:
    """Role-split worker running ONLY the prefill programs.

    Owns a bounded admission queue (the same FIFO/backpressure policy
    as the fused engine) and a small staging pool whose slots live only
    from prefill to transfer.  ``step(fleet)`` is one iteration: expire
    overdue queued work, admit up to ``min(free staging slots, decode
    capacity, max_prefills_per_tick)``, prefill each admission, hand
    the slab to ``fleet.transfer_out`` and recycle the staging slot.
    """

    role = "prefill"

    def __init__(self, name: str, params, *, head_dim: int,
                 n_slots: int = 2, max_total: int = 128, mesh=None,
                 axis_name: str = "model", queue_capacity: int = 16,
                 max_prefills_per_tick: int = 1,
                 prefill_bucket: int = 1):
        from ..parallel.decode import _kv_heads

        self.name = str(name)
        n_kv = _kv_heads(params, head_dim)
        dtype = params["embed"].dtype
        if mesh is None:
            from ..topology import make_mesh
            mesh = make_mesh(axis_name=axis_name)
        self.pool = CachePool(n_slots, max_total, len(params["blocks"]),
                              n_kv * head_dim, dtype, mesh, axis_name)
        self.engine = DecodeEngine(params, self.pool, mesh, axis_name,
                                   head_dim=head_dim,
                                   prefill_bucket=prefill_bucket)
        self.scheduler = Scheduler(
            queue_capacity, max_total,
            max_prefills_per_tick=max_prefills_per_tick,
            max_positions=self.engine.max_positions)
        self.goodput = GoodputLedger()
        self.dead = False
        self.prefills = 0
        self.transfer_failures = 0
        self._t0 = time.monotonic()
        self._last_step_end: Optional[float] = None

    # ---- dispatch inputs ----
    def load(self) -> Dict[str, Any]:
        queued = self.scheduler.queued_requests()
        return {
            "name": self.name,
            "dead": self.dead,
            "queue_depth": len(queued),
            "queue_capacity": self.scheduler.queue_capacity,
            "free_slots": self.pool.free_count,
            # prefill cost only: the decode remainder is the DECODE
            # worker's backlog, not this one's
            "backlog_tokens": sum(r.prompt_len for r in queued),
        }

    def submit_request(self, req: Request, now: float) -> None:
        """Scheduler admission with the engine's padded-length check
        (the same bucket-aware bound the fused frontend applies)."""
        s_pad = self.engine.padded_len(req.prompt_len)
        cap = self.pool.max_total
        if self.engine.max_positions is not None:
            cap = min(cap, self.engine.max_positions)
        if s_pad > cap:
            raise AdmissionError(
                "too_long",
                f"prompt {req.prompt_len} pads to {s_pad} "
                f"(prefill_bucket {self.engine.prefill_bucket}), "
                f"exceeding staging capacity {cap}")
        self.scheduler.submit(req, now)

    # ---- the worker iteration ----
    def step(self, fleet: "DisaggRouter") -> int:
        """One prefill-worker iteration; returns prefills completed."""
        if self.dead:
            return 0
        t0 = time.monotonic()
        last = (self._last_step_end if self._last_step_end is not None
                else self._t0)
        gap = t0 - last
        if gap > 0:
            self.goodput.add("queue_wait" if self.scheduler.queue_depth
                             else "stall", gap)
        t_host = t0
        now = time.monotonic()
        for req in self.scheduler.expire_queued(now):
            obs.instant("serving/request/expired", cat="serving",
                        request=req.id, trace_id=req.trace_id)
            fleet._finish_tracing(req, "deadline")
        # admit no more prefills than the decode side can take THIS
        # round: a slab with no destination is a wasted whole-prompt
        # forward (the requeue fallback still catches races)
        budget = min(self.pool.free_count, fleet.decode_free_slots())
        worked = 0
        for req in self.scheduler.admissions(budget, now):
            slot = self.pool.acquire()
            t_admit = time.monotonic()
            req.timestamps["prefill_start"] = t_admit
            t_us = getattr(req, "trace_us", None)
            if t_us is not None:
                now_us = obs.now_us()
                obs.complete_event(
                    "request/queue_wait", t_us["submitted"],
                    now_us - t_us["submitted"], cat="serving_request",
                    trace_id=req.trace_id, request=req.id)
            self.goodput.add("host", t_admit - t_host)
            compiles_before = self.engine.prefill_compiles
            t_pf = time.monotonic()
            try:
                with obs.span("serving/prefill", cat="serving_request",
                              request=req.id, trace_id=req.trace_id,
                              slot=slot, worker=self.name):
                    first = self.engine.prefill_into_slot(
                        req.prompt, slot, rng=req.rng,
                        temperature=req.temperature)
            except Exception as e:
                t_host = time.monotonic()
                self.goodput.add("compute", t_host - t_pf)
                self.pool.release(slot)
                req.finish("error", time.monotonic())
                _flight.note("disagg", event="prefill_error",
                             worker=self.name, request=req.id,
                             trace_id=req.trace_id, error=repr(e))
                fleet._finish_tracing(req, "error")
                continue
            t_host = time.monotonic()
            self.goodput.add(
                "compile" if self.engine.prefill_compiles
                > compiles_before else "compute", t_host - t_pf)
            self.prefills += 1
            # the slab leaves over the plane, which takes ownership of
            # the staging slot: lanes mode packs and releases it here,
            # local mode holds it busy until the decode side's landing
            # copies the rows out.  The publish wall (choose/reserve/
            # pack/put) is THIS thread's transfer cost — the landing
            # wall is the decode worker's, booked by its own ledger's
            # gap attribution (each ledger partitions only its own
            # thread's wall)
            t_xfer = time.monotonic()
            fleet.transfer_out(self, req, slot, first)
            t_host = time.monotonic()
            self.goodput.add("transfer", t_host - t_xfer)
            worked += 1
        t_end = time.monotonic()
        self.goodput.add("host", t_end - t_host)
        self._last_step_end = t_end
        if worked:
            _flight.note("phase", name="disagg/prefill_step",
                         worker=self.name, prefills=worked)
        return worked

    def kill(self) -> None:
        """Chaos face: the worker stops doing work (its queue is
        re-dispatched by the router's health sweep)."""
        self.dead = True

    @property
    def idle(self) -> bool:
        # a busy staging slot means a prefill/transfer is mid-flight on
        # the driver thread even when the queue just drained — without
        # it, a drain poll between queue pop and inbox handoff could
        # declare the fleet done and stop() under an in-flight request
        return self.dead or (self.scheduler.queue_depth == 0
                             and self.pool.busy_count == 0)

    def introspect_state(self) -> Dict[str, Any]:
        return {
            "role": self.role,
            "dead": self.dead,
            "queue_depth": self.scheduler.queue_depth,
            "free_slots": self.pool.free_count,
            "prefills": self.prefills,
            "prefill_compiles": self.engine.prefill_compiles,
            "transfer_failures": self.transfer_failures,
            "goodput": self.goodput.report(),
            "queued": [_request_row(r)
                       for r in self.scheduler.queued_requests()],
        }


class DecodeWorker:
    """Role-split worker running ONLY the compiled decode tick.

    A thin wrapper over :class:`ServingEngine` whose admission path is
    never used: requests arrive as transferred slabs via
    ``engine.install_request`` into slots the router reserved.  Its
    prefill-program family stays empty and its prefix cache is off (a
    decode worker never sees a prompt before its K/V already exists).

    ``inbox`` holds in-flight transfers addressed to this worker
    (appended by the router from the prefill side, drained at the start
    of this worker's step) — the one-way handoff that keeps every
    touch of this worker's pool on its own driver thread.
    """

    role = "decode"

    def __init__(self, name: str, params, *, head_dim: int,
                 n_slots: int = 4, max_total: int = 128, mesh=None,
                 axis_name: str = "model",
                 slo: Optional[SLOTracker] = None,
                 stats_capacity: int = 1024):
        self.name = str(name)
        self.inbox: deque = deque()   # append/popleft are GIL-atomic
        self.engine = ServingEngine(
            params, head_dim=head_dim, n_slots=n_slots,
            max_total=max_total, mesh=mesh, axis_name=axis_name,
            queue_capacity=1, max_prefills_per_tick=1,
            prefix_cache=False, slo=slo, stats_capacity=stats_capacity)

    def load(self) -> Dict[str, Any]:
        eng = self.engine
        with eng._lock:
            running = list(eng._running.values())
        backlog = sum(max(r.max_new_tokens - len(r.tokens), 0)
                      for r in running)
        return {
            "name": self.name,
            "free_slots": eng.pool.free_count,       # excludes reserved
            "reserved_slots": eng.pool.reserved_count,
            "busy_slots": eng.pool.busy_count,
            "backlog_tokens": int(backlog),
        }

    def token_latency_ms(self, default: float = 20.0) -> float:
        p50 = self.engine._tok_lat_ms.percentile(50)
        return float(p50) if p50 else float(default)

    def step(self):
        return self.engine.step()

    @property
    def idle(self) -> bool:
        # reserved slots are in-flight transfers addressed here whose
        # inbox entry may not have landed yet — they count as work
        return (self.engine.pool.busy_count == 0
                and self.engine.pool.reserved_count == 0
                and not self.inbox)

    def introspect_state(self) -> Dict[str, Any]:
        state = self.engine.introspect_state()
        state["role"] = self.role
        return state


class DisaggRouter(RouterBase):
    """Role-aware dispatch over prefill + decode worker sets.

    * **Prompts** → the least-loaded LIVE prefill worker (fewest
      backlog prompt-tokens, ties to the emptier queue, then
      round-robin) — after the same SLO-burn shedding gate as the
      replica router (``shed_slo`` before the pager fires).
    * **Slabs** (called back from a prefill worker's step) → the decode
      worker chosen by FREE (reservation-aware) slots + deadline
      feasibility (remaining tokens × measured token latency must fit
      the request's remaining budget); the destination slot is reserved
      before the transfer starts and committed when the slab lands.
    * **Transport**: ``transport_mode="local"`` runs the compiled
      reshard path (one program per pool pair); ``"lanes"`` runs
      pack → hardened object lane → unpack, booking slab bytes in the
      comm ledger — the cross-process wire, exercised in-process so the
      chaos/exactness tests cover the real lane discipline.
    """

    ROLE = "disagg"

    def __init__(self, prefill_workers: Sequence[PrefillWorker],
                 decode_workers: Sequence[DecodeWorker], *,
                 plane: Optional[KvTransferPlane] = None,
                 transport_mode: str = "local",
                 slo: Optional[SLOTracker] = None,
                 shed_burn_threshold: float = 1.0,
                 tenancy=None,
                 paid_burn_headroom: float = 2.0,
                 default_token_latency_ms: float = 20.0,
                 metrics_writer=None,
                 max_transfer_attempts: int = 2,
                 bundle_dir: Optional[str] = None,
                 lane_timeout_s: float = 10.0):
        if not prefill_workers or not decode_workers:
            raise ValueError("need at least one worker per role")
        if transport_mode not in ("local", "lanes"):
            raise ValueError(f"transport_mode must be local|lanes, "
                             f"got {transport_mode!r}")
        super().__init__(
            metrics_writer=metrics_writer, tenancy=tenancy, slo=slo,
            shed_burn_threshold=shed_burn_threshold,
            paid_burn_headroom=paid_burn_headroom,
            default_token_latency_ms=default_token_latency_ms)
        self.prefill_workers: List[PrefillWorker] = list(prefill_workers)
        self.decode_workers: List[DecodeWorker] = list(decode_workers)
        names = [w.name for w in self.prefill_workers] \
            + [w.name for w in self.decode_workers]
        if len(set(names)) != len(names):
            raise ValueError(f"worker names must be unique: {names}")
        self.plane = plane or KvTransferPlane()
        self.transport_mode = transport_mode
        self.max_transfer_attempts = int(max_transfer_attempts)
        self.bundle_dir = bundle_dir
        self.lane_timeout_s = float(lane_timeout_s)
        self._rr = 0
        self._dispatched = 0
        self._dispatched_by: Dict[str, int] = {
            w.name: 0 for w in self.prefill_workers}
        self._transfers = 0
        self._requeues = 0
        self._shed_inflight = 0   # sheds of ALREADY-dispatched requests
        self._transfer_ms = ReservoirSample(1024)
        self._threads: List[Any] = []
        self._stop_flag = False
        _flight.register_provider("disagg_router", self.introspect_state)
        _flight.register_provider("disagg_prefill", self._prefill_state)
        _flight.register_provider("disagg_decode", self._decode_state)

    # ---- submission (prompts → prefill workers) ----
    def submit(self, prompt, max_new_tokens: int, *,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               on_token=None, temperature: float = 0.0,
               rng=None, tenant: Optional[str] = None,
               priority: Optional[str] = None) -> RequestHandle:
        """Dispatch to the least-loaded live prefill worker or raise
        :class:`AdmissionError` with the uniform machine-readable
        payload (reason + ``retry_after_ms`` + ``queue_depth``).
        ``tenant``/``priority`` bill the request to a tenant class
        (ISSUE 11)."""
        trace_id = self._mint_trace_id()
        now = time.monotonic()
        t0_us = obs.now_us()
        t_submit = time.monotonic()
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        temperature = float(temperature)
        if temperature > 0.0 and rng is None:
            raise ValueError(
                "temperature > 0 samples tokens and needs an explicit "
                "rng: pass jax.random.PRNGKey(...) (the lm_generate "
                "contract)")
        key = (None if rng is None
               else np.asarray(rng, np.uint32).reshape(2))

        live = [w for w in self.prefill_workers if not w.dead]
        loads = [w.load() for w in live]
        fleet_depth = sum(ld["queue_depth"] for ld in loads)
        fleet_cap = sum(ld["queue_capacity"] for ld in loads)
        if not live:
            self._reject(
                "worker_lost", trace_id,
                f"all {len(self.prefill_workers)} prefill workers are "
                f"dead", retry_after_ms=1.0, queue_depth=0,
                tenant=tenant)
        # tenant plane, then the shared SLO-burn gate (best-effort at
        # the base threshold, paid with paid_burn_headroom× more room)
        tenant, max_new_tokens, capped = self._admit_tenant(
            trace_id, tenant, priority, max_new_tokens,
            queue_depth=fleet_depth, queue_capacity=fleet_cap,
            retry_after_ms=self._retry_after_ms)
        self._maybe_shed_slo(trace_id, fleet_depth,
                             self._retry_after_ms, tenant)
        if deadline_s is not None:
            # feasibility against the DECODE side: the generation must
            # fit behind the least-loaded decode worker's backlog
            waits = [self._est_wait_ms(dw) for dw in self.decode_workers]
            if min(waits) / 1e3 >= deadline_s:
                self._reject(
                    "shed_slo", trace_id,
                    "no decode worker can start before the request "
                    f"deadline (deadline_s={deadline_s})",
                    retry_after_ms=self._retry_after_ms(),
                    queue_depth=fleet_depth, tenant=tenant)

        candidates = [
            (ld["backlog_tokens"], ld["queue_depth"],
             (i - self._rr) % len(live), w)
            for i, (w, ld) in enumerate(zip(live, loads))
            if ld["queue_depth"] < ld["queue_capacity"]]
        if not candidates:
            self._reject(
                "queue_full", trace_id,
                f"all {len(live)} live prefill-worker queues at capacity",
                retry_after_ms=self._retry_after_ms(),
                queue_depth=fleet_depth, tenant=tenant)
        _, _, _, pw = min(candidates)
        self._rr = (self._rr + 1) % max(len(live), 1)

        if self.tenancy is not None and tenant is not None:
            # per-tenant TTFT/goodput attribution rides the stream (the
            # decode worker's engine owns it after the transfer hop)
            on_token = self.tenancy.wrap_on_token(tenant, t_submit,
                                                  on_token)
        req = Request(prompt, max_new_tokens, eos_id=eos_id,
                      deadline_t=(now + deadline_s
                                  if deadline_s is not None else None),
                      on_token=on_token, trace_id=trace_id,
                      temperature=temperature, rng=key, tenant=tenant)
        self._stamp_tenant_meta(req, tenant)
        req.trace_us = {"submitted": obs.now_us()}
        obs.async_event("b", "request", trace_id, cat="serving_request",
                        request=req.id, prompt_len=req.prompt_len)
        try:
            pw.submit_request(req, now)
        except AdmissionError as e:
            obs.async_event("e", "request", trace_id,
                            cat="serving_request", reason="rejected",
                            admission_reason=e.reason)
            self._reject(e.reason, trace_id, str(e),
                         retry_after_ms=self._retry_after_ms(),
                         queue_depth=fleet_depth, tenant=tenant)
        if self.tenancy is not None and tenant is not None:
            self.tenancy.on_admit(self.tenancy.resolve(tenant), req,
                                  capped=capped)
        with self._lock:
            self._dispatched += 1
            self._dispatched_by[pw.name] += 1
        obs.complete_event(
            "disagg/dispatch", t0_us, obs.now_us() - t0_us,
            cat="serving_request", trace_id=trace_id, worker=pw.name,
            fleet_queue_depth=fleet_depth)
        _flight.note("disagg", event="dispatched", trace_id=trace_id,
                     worker=pw.name)
        return RequestHandle(req)

    def _est_wait_ms(self, dw: DecodeWorker, load=None) -> float:
        """Estimated ms before ``dw`` can start new work: its decode
        backlog priced at its measured token latency — THE feasibility
        estimate (one definition; admission, dispatch, and back-off
        hints must never disagree on it)."""
        ld = load if load is not None else dw.load()
        return float(ld["backlog_tokens"] * dw.token_latency_ms(
            self.default_token_latency_ms))

    def _retry_after_ms(self) -> float:
        """Drain-aware back-off hint (ISSUE 11): the least-loaded
        decode worker's queued tokens priced at the fleet's MEASURED
        recent tokens/s (clamped + jittered in
        ``derive_retry_after_ms``; zero-throughput edges fall back to
        ``default_token_latency_ms``)."""
        backlog = min(dw.load()["backlog_tokens"]
                      for dw in self.decode_workers)
        tokens_total = sum(dw.engine._tokens_emitted
                           for dw in self.decode_workers)
        return self._derive_retry_ms(backlog, tokens_total)

    # ---- the transfer hop (slabs → decode workers) ----
    def decode_free_slots(self) -> int:
        """Fleet-wide transferable capacity: free slots AFTER in-flight
        reservations (the allocator keeps them disjoint)."""
        return sum(dw.engine.pool.free_count for dw in self.decode_workers)

    def _choose_decode(self, req: Request) -> Optional[DecodeWorker]:
        """Most-free decode worker that can still meet the request's
        deadline; None when no worker has a free slot (the caller
        re-queues) or none is feasible."""
        best, best_key = None, None
        for dw in self.decode_workers:
            ld = dw.load()
            if ld["free_slots"] < 1:
                continue
            if req.deadline_t is not None:
                wait_s = self._est_wait_ms(dw, ld) / 1e3
                if time.monotonic() + wait_s >= req.deadline_t:
                    continue
            key = (-ld["free_slots"], ld["backlog_tokens"])
            if best_key is None or key < best_key:
                best, best_key = dw, key
        return best

    def _deadline_feasible(self, req: Request) -> bool:
        """Whether ANY decode worker could still meet ``req``'s
        deadline, ignoring slot availability (slots free up; a blown
        deadline never does)."""
        now = time.monotonic()
        return any(
            now + self._est_wait_ms(dw) / 1e3 < req.deadline_t
            for dw in self.decode_workers)

    def transfer_out(self, pw: PrefillWorker, req: Request,
                     src_slot: int, first_tok: int) -> bool:
        """PREFILL-side half of a transfer: pick a destination, reserve
        its slot, publish the slab, and hand the landing to the decode
        worker's inbox.  Called from the prefill worker's step; this
        method takes OWNERSHIP of the staging slot — lanes mode packs
        and releases it here, local mode keeps it busy until
        :meth:`_land_transfer` copies the rows out on the decode side.
        On a lane fault: reservation cancelled, victim marked dead +
        bundle dumped, request re-queued on a survivor or shed
        machine-readably.  Returns True when the slab is in flight."""
        length = int(pw.pool.pos[src_slot])
        dw = self._choose_decode(req)
        if dw is None:
            pw.pool.release(src_slot)
            if req.deadline_t is not None and not self._deadline_feasible(req):
                # no decode worker can meet the deadline even with a
                # free slot: a head requeue would re-prefill the same
                # doomed request every round (head-of-line blocking the
                # queue) until the deadline fires — expire it now, the
                # same terminal state expire_queued gives it
                req.finish("deadline", time.monotonic())
                obs.instant("serving/request/expired", cat="serving",
                            request=req.id, trace_id=req.trace_id)
                self._finish_tracing(req, "deadline")
                return False
            # no destination right now (all slots busy/reserved):
            # retry after decode drains — at the cost of a re-prefill,
            # which the staging budget gate keeps rare
            pw.scheduler.requeue_front(req)
            with self._lock:
                self._requeues += 1
            _flight.note("disagg", event="transfer_backpressure",
                         worker=pw.name, trace_id=req.trace_id)
            return False
        dst = dw.engine.pool.reserve()
        assert dst is not None  # _choose_decode saw a free slot
        t0 = time.monotonic()
        entry = {"req": req, "src_worker": pw, "dst_slot": dst,
                 "length": length, "first_tok": int(first_tok),
                 "t0": t0, "t0_us": obs.now_us(),
                 "mode": self.transport_mode}
        if self.transport_mode == "lanes":
            tag = f"{req.trace_id}.slab"
            try:
                payload = self.plane.pack(
                    pw.pool, src_slot, length,
                    meta=request_wire(req, [first_tok]))
                self.plane.lane_put(tag, payload)
            except DcnLaneError as e:
                # wall is booked by the caller (PrefillWorker.step
                # brackets this whole method as "transfer")
                pw.pool.release(src_slot)
                dw.engine.pool.cancel_reservation(dst)
                self._on_transfer_fault(pw, req, e)
                return False
            # the slab is host bytes on the lane now: the staging slot
            # is free to recycle before the landing
            pw.pool.release(src_slot)
            entry["tag"] = tag
        else:
            # local mode: the compiled copy reads the staging rows on
            # the DECODE side, so the slot stays busy until it lands
            entry["src_slot"] = src_slot
        dw.inbox.append(entry)
        return True

    def _land_transfer(self, dw: DecodeWorker, entry: Dict[str, Any]
                       ) -> bool:
        """DECODE-side half: land one inbox entry into its reserved
        slot — lane get/unpack or the compiled local copy — commit the
        reservation, and install the request on the engine.  Runs on
        the decode worker's driver (the only thread that touches its
        pool).  A lane fault here cancels the reservation (the worker
        is never wedged) and routes through the same fault path as the
        publish side."""
        req, pw = entry["req"], entry["src_worker"]
        dst, length = entry["dst_slot"], entry["length"]
        try:
            if entry["mode"] == "lanes":
                got = self.plane.lane_get(entry["tag"],
                                          self.lane_timeout_s)
                stats = self.plane.unpack_into(got, dw.engine.pool, dst)
                # GC after a SUCCESSFUL landing is best-effort: a
                # delete fault must not kill the publisher (the slab
                # arrived — requeueing would re-prefill a request that
                # already landed) nor cancel a reservation whose slab
                # is already in the caches
                try:
                    self.plane.lane_delete(entry["tag"])
                except DcnLaneError as e:
                    _flight.note("disagg", event="gc_failed",
                                 tag=entry["tag"], lane=e.lane)
            else:
                stats = self.plane.transfer_local(
                    pw.pool, entry["src_slot"], dw.engine.pool, dst,
                    length)
                pw.pool.release(entry["src_slot"])
        except DcnLaneError as e:
            if entry["mode"] == "lanes":
                # best-effort GC: a slab whose request is about to be
                # re-queued or shed must not sit in the KV store forever
                try:
                    self.plane.lane_delete(entry["tag"])
                except DcnLaneError:
                    pass
            dw.engine.pool.cancel_reservation(dst)
            self._on_transfer_fault(pw, req, e)
            return False
        # end-to-end latency for the p50/p99 metric only — the WALL was
        # already partitioned: publish side on the prefill thread's
        # ledger ("transfer"), landing side in this worker's own
        # engine-gap attribution (no ledger is touched cross-thread)
        ms = (time.monotonic() - entry["t0"]) * 1e3
        dw.engine.pool.commit_reservation(dst)
        dw.engine.install_request(req, dst, [entry["first_tok"]])
        with self._lock:
            self._transfers += 1
            self._transfer_ms.add(ms)
        obs.complete_event(
            "serving/kv_transfer", entry["t0_us"],
            obs.now_us() - entry["t0_us"], cat="serving_request",
            request=req.id, trace_id=req.trace_id, src=pw.name,
            dst=dw.name, length=length, mode=stats["mode"])
        _flight.note("disagg", event="transfer", src=pw.name,
                     dst=dw.name, trace_id=req.trace_id, slot=dst,
                     length=length, mode=stats["mode"],
                     ledger_bytes=stats["ledger_bytes"],
                     ms=round(ms, 3))
        return True

    def _on_transfer_fault(self, pw: PrefillWorker, req: Request,
                           err: DcnLaneError) -> None:
        """A transfer lane died: the victim worker is out of the fleet,
        the evidence is on disk, and the request either retries on a
        survivor (re-prefill) or is shed in the wire shape."""
        pw.dead = True
        pw.transfer_failures += 1
        _flight.note("disagg", event="worker_lost", worker=pw.name,
                     lane=err.lane, attempts=err.attempts,
                     trace_id=req.trace_id)
        if self.bundle_dir:
            _flight.dump_bundle(self.bundle_dir, "kv_transfer_fault",
                                extra={"worker": pw.name,
                                       "lane": err.lane,
                                       "trace_id": req.trace_id})
        attempts = getattr(req, "transfer_attempts", 0) + 1
        req.transfer_attempts = attempts
        survivors = [w for w in self.prefill_workers if not w.dead]
        if survivors and attempts < self.max_transfer_attempts:
            # re-prefill on a survivor: the slab died with the lane
            survivors[0].scheduler.requeue_front(req)
            with self._lock:
                self._requeues += 1
            _flight.note("disagg", event="requeued", worker=pw.name,
                         to=survivors[0].name, trace_id=req.trace_id,
                         attempt=attempts)
            return
        self._shed_request(
            req,
            f"prefill worker {pw.name} lost mid-transfer on lane "
            f"'{err.lane}' with no retry budget "
            f"({attempts}/{self.max_transfer_attempts} attempts, "
            f"{len(survivors)} survivor(s))")

    def _shed_request(self, req: Request, detail: str) -> None:
        """Shed an ALREADY-ACCEPTED request machine-readably: the same
        ``AdmissionError.to_dict()`` wire shape a submit-time rejection
        carries, attached to the handle (``shed_payload``), streamed as
        a ``disagg_shed`` JSONL record, and counted under
        ``worker_lost``."""
        if self.tenancy is not None:
            self.tenancy.count_shed(req.tenant, "worker_lost")
        shed = AdmissionError(
            "worker_lost", detail,
            retry_after_ms=self._retry_after_ms(),
            queue_depth=sum(w.scheduler.queue_depth
                            for w in self.prefill_workers),
            tenant=req.tenant,
            rung=(None if self.tenancy is None
                  else self.tenancy.ladder.rung))
        with self._lock:
            self._rejected["worker_lost"] = \
                self._rejected.get("worker_lost", 0) + 1
            self._shed_inflight += 1
        req.shed_payload = shed.to_dict()
        req.finish("shed", time.monotonic())
        if self.metrics_writer is not None:
            self.metrics_writer.write(
                dict(reason="worker_lost", trace_id=req.trace_id,
                     **{f"disagg/{k}": v for k, v in shed.to_dict().items()
                        if not isinstance(v, str)}),
                kind="disagg_shed")
        _flight.note("disagg", event="shed", reason="worker_lost",
                     trace_id=req.trace_id, payload=req.shed_payload)
        self._finish_tracing(req, "shed")

    def _finish_tracing(self, req: Request, reason: str) -> None:
        obs.async_event("e", "request", req.trace_id,
                        cat="serving_request", reason=reason,
                        n_tokens=len(req.tokens))
        _flight.note("disagg", event="finished", request=req.id,
                     trace_id=req.trace_id, reason=reason)

    # ---- driving ----
    def step_prefill(self) -> int:
        """One PREFILL-role round: health-sweep dead workers' queues,
        then every live prefill worker with queued work prefills and
        publishes its slabs.  Returns how many workers still carry
        work."""
        # health sweep: a dead worker's queue is re-dispatched to a
        # survivor (or shed machine-readably) — never stranded
        for pw in self.prefill_workers:
            if pw.dead and pw.scheduler.queue_depth:
                survivors = [w for w in self.prefill_workers
                             if not w.dead]
                waiting = pw.scheduler.drain()
                if survivors:
                    for req in reversed(waiting):
                        survivors[0].scheduler.requeue_front(req)
                    with self._lock:
                        self._requeues += len(waiting)
                    _flight.note("disagg", event="queue_redispatched",
                                 worker=pw.name, to=survivors[0].name,
                                 n=len(waiting))
                else:
                    for req in waiting:
                        self._shed_request(
                            req, f"prefill worker {pw.name} dead with "
                                 f"no survivors")
        worked = 0
        for pw in self.prefill_workers:
            if not pw.idle:
                worked += 1 if pw.step(self) else 0
                # a worker with queued work that could not place any
                # slab still counts as busy — the fleet is not drained
                if pw.scheduler.queue_depth > 0:
                    worked += 1
        return worked

    def step_decode(self) -> int:
        """One DECODE-role round: every decode worker lands its inbox
        (reservation commit + install) and ticks its active slots.
        The only code path that touches a decode worker's pool — in
        threaded drive this IS the decode thread's loop body."""
        worked = 0
        for dw in self.decode_workers:
            while dw.inbox:
                self._land_transfer(dw, dw.inbox.popleft())
                worked += 1
            if dw.engine.pool.busy_count > 0:
                dw.step()
                worked += 1
            else:
                # an idle round breaks the tick cadence: the next gap
                # would measure slab-arrival wait, not inter-token
                # latency (mirrors the fused engine's idle-step reset —
                # without it an idle spell inflates tick_gap p99, the
                # acceptance metric, as a measurement artifact)
                dw.engine._last_tick_start = None
        return worked

    def step(self) -> int:
        """One deterministic fleet round (tests and ``run``): the
        prefill role's round, then the decode role's.  Returns how many
        workers did work (0 == drained).  ``start()`` drives the same
        two halves on separate threads instead — that is where the
        decode tick-gap collapse is actually observable."""
        return self.step_prefill() + self.step_decode()

    def run(self, steps_budget: Optional[int] = None) -> int:
        n = 0
        while steps_budget is None or n < steps_budget:
            if self.step() == 0:
                break
            n += 1
        return n

    def start(self) -> None:
        """Role-parallel drive: ONE driver thread per role.  The inbox
        handoff keeps each pool single-threaded (prefill thread: admit/
        prefill/publish + reserve destination slots; decode thread:
        land/commit/tick), so prefill wall never sits between two
        decode ticks — the disaggregation payoff the bench measures.
        A cross-process deployment runs the same two loop bodies in
        separate processes over the lane transport."""
        import threading
        if self._threads:
            return
        self._stop_flag = False

        def loop(role_step, role):
            try:
                while not self._stop_flag:
                    if role_step() == 0:
                        time.sleep(0.001)
            except BaseException as e:
                # only DcnLaneError is handled (inside the transfer
                # path); anything else escaping a role driver must die
                # LOUDLY — a silently-dead daemon thread would wedge
                # the whole fleet (the other role keeps producing work
                # nobody consumes) with zero evidence
                _flight.note("disagg", event="driver_died", role=role,
                             error=repr(e))
                if self.bundle_dir:
                    _flight.dump_bundle(
                        self.bundle_dir, "disagg_driver_death",
                        extra={"role": role, "error": repr(e)})
                self._stop_flag = True
                raise

        self._threads = [
            threading.Thread(target=loop,
                             args=(self.step_prefill, "prefill"),
                             daemon=True, name="disagg-prefill"),
            threading.Thread(target=loop,
                             args=(self.step_decode, "decode"),
                             daemon=True, name="disagg-decode"),
        ]
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop_flag = True
        alive = []
        for t in self._threads:
            t.join(timeout=10)
            if t.is_alive():
                alive.append(t)
        # keep wedged drivers ON the list: start() refuses to double-
        # drive while it is non-empty, and close() refuses to tear the
        # engines down under a thread that still owns them
        self._threads = alive
        if alive:
            # a driver is wedged past the join budget (e.g. a lane_get
            # deep in its retry window): draining its inbox from this
            # thread would put TWO threads landing into one pool
            # (last-writer-wins on the caches pytree) — leave the inbox
            # to the still-alive driver and say so loudly
            _flight.note("disagg", event="stop_timeout",
                         threads=[t.name for t in alive])
            return
        # land anything the decode thread didn't get to before seeing
        # the stop flag: a reservation must never outlive the drive
        # (runs on the caller's thread — the role threads are joined)
        for dw in self.decode_workers:
            while dw.inbox:
                self._land_transfer(dw, dw.inbox.popleft())

    def close(self) -> None:
        self.stop()
        if self._threads:
            # a wedged driver still owns its engine: closing it here
            # would be a use-after-close the moment the thread wakes —
            # the stop_timeout note above is the evidence trail
            return
        for dw in self.decode_workers:
            dw.engine.close()
        # identity-guarded: a NEWER fleet's registrations under these
        # names must survive this one's teardown (router.py discipline)
        for name, fn in (("disagg_router", self.introspect_state),
                         ("disagg_prefill", self._prefill_state),
                         ("disagg_decode", self._decode_state)):
            if _flight._PROVIDERS.get(name) == fn:
                _flight.unregister_provider(name)

    def reset_stats(self) -> None:
        with self._lock:
            self._dispatched = 0
            self._dispatched_by = {w.name: 0
                                   for w in self.prefill_workers}
            self._rejected = {r: 0 for r in self._rejected}
            self._transfers = 0
            self._requeues = 0
            self._shed_inflight = 0
            self._transfer_ms = ReservoirSample(1024)
        for pw in self.prefill_workers:
            pw.goodput.reset()
            pw.prefills = 0
        for dw in self.decode_workers:
            dw.engine.reset_stats()

    # ---- metrics / introspection ----
    def metrics(self) -> Dict[str, float]:
        """Fleet summary under ``disagg/*`` (the /metricsz
        ``extra_gauges`` payload + the bench section's source).
        ``transfer*/tick_gap*/rejected*`` keys are lower-is-better
        under the regression gate's direction inference."""
        with self._lock:
            dispatched = self._dispatched
            rejected = dict(self._rejected)
            transfers = self._transfers
            requeues = self._requeues
            shed_inflight = self._shed_inflight
            xfer_vals = self._transfer_ms.values()
        out: Dict[str, float] = {
            "disagg/prefill_workers": float(len(self.prefill_workers)),
            "disagg/decode_workers": float(len(self.decode_workers)),
            "disagg/dispatched_total": float(dispatched),
            "disagg/rejected_total": float(sum(rejected.values())),
            "disagg/transfers_total": float(transfers),
            "disagg/requeued_total": float(requeues),
            "disagg/dead_prefill_workers": float(
                sum(w.dead for w in self.prefill_workers)),
        }
        for reason, n in sorted(rejected.items()):
            out[f"disagg/rejected/{reason}"] = float(n)
        # a worker_lost shed of an already-dispatched request sits in
        # BOTH counters — subtract it once so offered counts each
        # request exactly once (the rate is gated lower-is-better; a
        # double-counted denominator would understate it)
        offered = dispatched + sum(rejected.values()) - shed_inflight
        out["disagg/shed_rate"] = (
            sum(rejected.values()) / offered if offered else 0.0)
        if xfer_vals:
            out["disagg/transfer_p50_ms"] = percentile_of(xfer_vals, 50)
            out["disagg/transfer_p99_ms"] = percentile_of(xfer_vals, 99)
        for k, v in self.plane.stats().items():
            out[f"disagg/plane/{k}"] = v
        # decode-side roll-ups (tick gaps are THE disagg payoff metric)
        tps = 0.0
        ttft_vals: List[float] = []
        gap_vals: List[float] = []
        for dw in self.decode_workers:
            m = dw.engine.metrics()
            tps += m["serving/tokens_per_sec"]
            ttft_vals.extend(dw.engine._ttft_ms.values())
            gap_vals.extend(dw.engine._tick_gap_ms.values())
            for k, v in m.items():
                out[f"disagg/{dw.name}/{k.split('/', 1)[1]}"] = v
        out["disagg/fleet_tokens_per_sec"] = tps
        if ttft_vals:
            out["disagg/fleet_ttft_p50_ms"] = percentile_of(ttft_vals, 50)
            out["disagg/fleet_ttft_p99_ms"] = percentile_of(ttft_vals, 99)
        if gap_vals:
            out["disagg/decode_tick_gap_p50_ms"] = percentile_of(
                gap_vals, 50)
            out["disagg/decode_tick_gap_p99_ms"] = percentile_of(
                gap_vals, 99)
            mean = sum(gap_vals) / len(gap_vals)
            out["disagg/decode_tick_gap_variance_ms2"] = (
                sum((g - mean) ** 2 for g in gap_vals) / len(gap_vals))
        for pw in self.prefill_workers:
            out[f"disagg/{pw.name}/prefills"] = float(pw.prefills)
            out[f"disagg/{pw.name}/queue_depth"] = float(
                pw.scheduler.queue_depth)
            out.update(pw.goodput.gauges(f"disagg/{pw.name}/goodput"))
        if self.tenancy is not None:
            out.update(self.tenancy.metrics())
        return out

    def requests_table(self) -> Dict[str, Any]:
        tables = {dw.name: dw.engine.requests_table()
                  for dw in self.decode_workers}
        for pw in self.prefill_workers:
            tables[pw.name] = {
                "schema": "chainermn_tpu.requestz.v1",
                "queued": [_request_row(r)
                           for r in pw.scheduler.queued_requests()],
                "running": [], "recent": [],
            }
        return {"schema": "chainermn_tpu.requestz.v1",
                "disagg": True, "workers": tables}

    def _prefill_state(self) -> Dict[str, Any]:
        return {w.name: w.introspect_state()
                for w in self.prefill_workers}

    def _decode_state(self) -> Dict[str, Any]:
        return {w.name: w.introspect_state()
                for w in self.decode_workers}

    def introspect_state(self) -> Dict[str, Any]:
        with self._lock:
            state: Dict[str, Any] = {
                "prefill_workers": [w.name for w in self.prefill_workers],
                "decode_workers": [w.name for w in self.decode_workers],
                "transport_mode": self.transport_mode,
                "dispatched": self._dispatched,
                "dispatched_by": dict(self._dispatched_by),
                "rejected": dict(self._rejected),
                "transfers": self._transfers,
                "requeues": self._requeues,
            }
        state["plane"] = self.plane.stats()
        if self.slo is not None:
            state["slo"] = self.slo.status()
        if self.tenancy is not None:
            state["tenancy"] = self.tenancy.state()
        return state

    def finalize_metrics(self) -> None:
        if self.metrics_writer is not None:
            self.metrics_writer.write(self.metrics(),
                                      kind="disagg_summary")

    def write_prometheus(self, path: str) -> str:
        from ..observability.export import write_prometheus_textfile
        return write_prometheus_textfile(path, extra_gauges=self.metrics())


def build_disagg_fleet(params, n_prefill: int, n_decode: int, *,
                       head_dim: int, max_total: int = 128,
                       n_slots: int = 4, staging_slots: int = 2,
                       mesh=None, axis_name: str = "model",
                       queue_capacity: int = 16,
                       max_prefills_per_tick: int = 1,
                       prefill_bucket: int = 1,
                       transport_mode: str = "local",
                       comm=None,
                       slo: Optional[SLOTracker] = None,
                       metrics_writer=None,
                       **router_kwargs) -> DisaggRouter:
    """Stand up a P:D disaggregated fleet on one mesh — the ``serve
    --disagg P:D`` CLI face.  ``n_slots`` sizes each DECODE worker's
    pool; ``staging_slots`` each prefill worker's staging pool.

    ``comm``: a :class:`~chainermn_tpu.communicators.base
    .CommunicatorBase` whose ``kv_lane_transport()`` backs the lanes
    transport — the jax.distributed KV store on a multi-controller
    gang, the in-process loopback otherwise.  Without it, lanes mode
    runs on a private loopback store (single-process only)."""
    if mesh is None:
        from ..topology import make_mesh
        mesh = make_mesh(axis_name=axis_name)
    if comm is not None and transport_mode == "lanes" \
            and "plane" not in router_kwargs:
        router_kwargs["plane"] = KvTransferPlane(
            transport=comm.kv_lane_transport())
    prefills = [
        PrefillWorker(f"prefill{i}", params, head_dim=head_dim,
                      n_slots=staging_slots, max_total=max_total,
                      mesh=mesh, axis_name=axis_name,
                      queue_capacity=queue_capacity,
                      max_prefills_per_tick=max_prefills_per_tick,
                      prefill_bucket=prefill_bucket)
        for i in range(int(n_prefill))]
    decodes = [
        DecodeWorker(f"decode{i}", params, head_dim=head_dim,
                     n_slots=n_slots, max_total=max_total, mesh=mesh,
                     axis_name=axis_name, slo=slo)
        for i in range(int(n_decode))]
    return DisaggRouter(prefills, decodes, transport_mode=transport_mode,
                        slo=slo, metrics_writer=metrics_writer,
                        **router_kwargs)
