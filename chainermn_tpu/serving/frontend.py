"""Threaded serving API: submit → handle, streaming tokens, metrics.

:class:`ServingEngine` glues the host scheduler, the slot pool, and the
compiled per-tick programs into the loop a service actually runs::

    eng = ServingEngine(params, head_dim=8, n_slots=4, max_total=64)
    h = eng.submit([3, 1, 4], max_new_tokens=16,
                   on_token=lambda tok, req_id: print(tok))
    eng.start()            # background driver thread (or drive eng.step()
    h.wait(timeout=30)     # synchronously from a test)
    print(h.tokens, h.status, h.ttft_ms)

Each ``step()`` is one engine iteration: expire overdue queued work,
admit (prefill) up to the interleaving bound, run ONE decode tick over
the pool, stream the new tokens, evict finished sequences.  Requests
therefore join and leave between ticks — a late submit starts decoding
as soon as a slot frees, while earlier sequences keep running
(iteration-level / continuous batching).

Observability (the PR 1/2 substrate, docs/OBSERVABILITY.md):

* per-request PHASE TIMESTAMPS on the handle (``submitted``,
  ``prefill_start``, ``first_token``, ``finished``) — the span data the
  integration test asserts on — mirrored into the tracer as
  ``serving/request/*`` instants (+ a real ``serving/prefill`` /
  ``serving/tick`` span around each device call) when tracing is on;
* serving GAUGES through the tracer (``serving/queue_depth``,
  ``serving/active_slots``, ``serving/tokens_per_sec``) so
  ``observability.export.write_prometheus_textfile`` scrapes them with
  everything else, plus :meth:`ServingEngine.metrics` (TTFT p50/p99,
  per-token latency, slot occupancy) as the ``extra_gauges`` /
  bench-section payload;
* optional per-step JSONL via ``observability.export.MetricsWriter``
  (kind ``serving_step`` records + one ``serving_summary``), the
  ``scripts/check_perf_regression.py``-gateable stream.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from .. import observability as obs
from .cache_pool import CachePool
from .engine import DecodeEngine
from .scheduler import AdmissionError, Request, Scheduler


class RequestHandle:
    """Caller's view of one submitted request (thread-safe reads)."""

    def __init__(self, req: Request):
        self._req = req

    @property
    def id(self) -> int:
        return self._req.id

    @property
    def status(self) -> str:
        return self._req.status

    @property
    def finish_reason(self) -> Optional[str]:
        return self._req.finish_reason

    @property
    def tokens(self) -> List[int]:
        return list(self._req.tokens)

    @property
    def timestamps(self) -> Dict[str, float]:
        return dict(self._req.timestamps)

    @property
    def ttft_ms(self) -> Optional[float]:
        ts = self._req.timestamps
        if "submitted" in ts and "first_token" in ts:
            return (ts["first_token"] - ts["submitted"]) * 1e3
        return None

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request finishes; True iff it did."""
        return self._req.done_event.wait(timeout)


def _percentile(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    return float(np.percentile(np.asarray(values, np.float64), q))


class ServingEngine:
    """Continuous-batching inference engine over a slot-managed KV pool.

    ``params``: GLOBAL ``init_tp_transformer_lm`` arrays (greedy decode
    only — sampling needs per-request rng plumbing; see docs/SERVING.md).
    ``max_total`` bounds each slot's sequence (prompt + generated); a
    request that cannot fit is REJECTED at submit (``AdmissionError``,
    reason ``too_long``), as is any submit while the bounded queue is
    full (``queue_full``) — backpressure is explicit, never buffered.
    """

    def __init__(self, params, *, head_dim: int, n_slots: int = 4,
                 max_total: int = 128, mesh=None, axis_name: str = "model",
                 queue_capacity: int = 16, max_prefills_per_tick: int = 1,
                 prefill_bucket: int = 1, metrics_writer=None):
        from ..parallel.decode import _kv_heads

        n_kv = _kv_heads(params, head_dim)
        dtype = params["embed"].dtype
        # pool and engine share one mesh (created here when not given,
        # like make_lm_generator)
        if mesh is None:
            from ..topology import make_mesh
            mesh = make_mesh(axis_name=axis_name)
        self.pool = CachePool(n_slots, max_total, len(params["blocks"]),
                              n_kv * head_dim, dtype, mesh, axis_name)
        self.engine = DecodeEngine(params, self.pool, mesh, axis_name,
                                   head_dim=head_dim,
                                   prefill_bucket=prefill_bucket)
        self.scheduler = Scheduler(
            queue_capacity, max_total,
            max_prefills_per_tick=max_prefills_per_tick,
            max_positions=self.engine.max_positions)
        self.metrics_writer = metrics_writer
        self._running: Dict[int, Request] = {}   # slot -> request
        self._lock = threading.Lock()            # guards _running + stats
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # rolling stats (host floats only)
        self._ttft_ms: List[float] = []
        self._tok_lat_ms: List[float] = []
        self._tokens_emitted = 0
        self._ticks = 0
        self._occupancy_sum = 0.0
        self._rejected = 0
        self._t0 = time.monotonic()

    # ---- submission ----
    def submit(self, prompt, max_new_tokens: int, *,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               on_token: Optional[Callable[[int, int], None]] = None
               ) -> RequestHandle:
        """Enqueue a generation request; raises :class:`AdmissionError`
        (with ``.reason``) when the queue is full or it can never fit.
        ``on_token(token, request_id)`` streams each token from the
        driver thread as it is emitted; ``deadline_s`` is relative to
        now."""
        now = time.monotonic()
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        req = Request(prompt, max_new_tokens, eos_id=eos_id,
                      deadline_t=(now + deadline_s
                                  if deadline_s is not None else None),
                      on_token=on_token)
        try:
            # the PADDED prefill length is what must fit the slot (and
            # the learned-pos table) — the scheduler only knows raw
            # lengths, so the bucket-aware check lives here
            s_pad = self.engine.padded_len(req.prompt_len)
            cap = self.pool.max_total
            if self.engine.max_positions is not None:
                cap = min(cap, self.engine.max_positions)
            if s_pad > cap:
                raise AdmissionError(
                    "too_long",
                    f"prompt {req.prompt_len} pads to {s_pad} "
                    f"(prefill_bucket {self.engine.prefill_bucket}), "
                    f"exceeding per-slot capacity {cap}")
            self.scheduler.submit(req, now)
        except AdmissionError:
            with self._lock:
                self._rejected += 1
            raise
        obs.instant("serving/request/queued", cat="serving", request=req.id)
        obs.set_gauge("serving/queue_depth", self.scheduler.queue_depth)
        return RequestHandle(req)

    # ---- the engine iteration ----
    def step(self) -> Dict[str, float]:
        """ONE engine iteration: expire → admit/prefill → tick → evict.
        Returns host-side stats for the iteration (also streamed to the
        JSONL metrics writer when configured)."""
        now = time.monotonic()
        for req in self.scheduler.expire_queued(now):
            obs.instant("serving/request/expired", cat="serving",
                        request=req.id)

        # admit up to the interleave bound into free slots
        for req in self.scheduler.admissions(self.pool.free_count, now):
            slot = self.pool.acquire()
            assert slot is not None  # admissions() is bounded by free_count
            req.slot = slot
            req.status = "running"
            req.timestamps["prefill_start"] = now
            obs.instant("serving/request/prefill", cat="serving",
                        request=req.id, slot=slot)
            try:
                with obs.span("serving/prefill", cat="serving",
                              request=req.id):
                    first = self.engine.prefill_into_slot(req.prompt, slot)
            except Exception as e:
                # never die holding a slot: a failed prefill (engine bug,
                # OOM, ...) releases the slot and fails THIS request only
                # — with start() an escaping exception would kill the
                # background thread and stall every other request, so the
                # engine sheds the request and keeps serving
                self.pool.release(slot)
                req.finish("error", time.monotonic())
                obs.instant("serving/request/error", cat="serving",
                            request=req.id)
                print(f"chainermn_tpu.serving: prefill of request "
                      f"{req.id} failed: {e!r}", file=sys.stderr)
                continue
            self._emit(req, first, time.monotonic())
            with self._lock:
                self._running[slot] = req
            self._maybe_evict(req, time.monotonic())

        # one decode tick over the pool (skip when nothing is active)
        with self._lock:
            active = dict(self._running)
        if active:
            tokens = np.zeros(self.pool.n_slots, np.int32)
            for slot, req in active.items():
                tokens[slot] = req.tokens[-1]
            t_tick = time.monotonic()
            with obs.span("serving/tick", cat="serving",
                          active=len(active)):
                nxt = self.engine.tick(tokens)
            dt_ms = (time.monotonic() - t_tick) * 1e3
            now = time.monotonic()
            for slot, req in active.items():
                self._emit(req, int(nxt[slot]), now)
                self._tok_lat_ms.append(dt_ms / max(len(active), 1))
                self._maybe_evict(req, now)

        with self._lock:
            self._ticks += 1
            self._occupancy_sum += self.pool.busy_count / self.pool.n_slots
            stats = {
                "queue_depth": float(self.scheduler.queue_depth),
                "active_slots": float(self.pool.busy_count),
                "tokens_emitted": float(self._tokens_emitted),
            }
        obs.set_gauge("serving/queue_depth", stats["queue_depth"])
        obs.set_gauge("serving/active_slots", stats["active_slots"])
        el = time.monotonic() - self._t0
        if el > 0:
            obs.set_gauge("serving/tokens_per_sec",
                          self._tokens_emitted / el)
        if self.metrics_writer is not None:
            self.metrics_writer.write(
                {f"serving/{k}": v for k, v in stats.items()},
                kind="serving_step")
        return stats

    def _emit(self, req: Request, token: int, now: float) -> None:
        req.tokens.append(int(token))
        if "first_token" not in req.timestamps:
            req.timestamps["first_token"] = now
            ttft = (now - req.timestamps["submitted"]) * 1e3
            with self._lock:
                self._ttft_ms.append(ttft)
            obs.instant("serving/request/first_token", cat="serving",
                        request=req.id)
        with self._lock:
            self._tokens_emitted += 1
        obs.add_counter("serving/tokens_total", 1)
        if req.on_token is not None:
            req.on_token(int(token), req.id)

    def _maybe_evict(self, req: Request, now: float) -> None:
        reason = self.scheduler.eviction_reason(req, now)
        if reason is None:
            return
        slot = req.slot
        req.finish(reason, now)
        with self._lock:
            self._running.pop(slot, None)
        self.pool.release(slot)
        obs.instant("serving/request/complete", cat="serving",
                    request=req.id, reason=reason)

    # ---- driving ----
    def run(self, steps_budget: Optional[int] = None,
            drain: bool = True) -> int:
        """Drive ``step()`` until the engine is idle (queue empty, no
        active slots) or ``steps_budget`` iterations elapse; returns the
        number of iterations run.  ``drain=False`` stops at the budget
        even with work pending (the CLI's ``--steps-budget``)."""
        n = 0
        while not self._stop.is_set():
            if steps_budget is not None and n >= steps_budget:
                break
            busy = (self.scheduler.queue_depth > 0
                    or self.pool.busy_count > 0)
            if not busy:
                if drain:
                    break
                time.sleep(0.001)
                continue
            self.step()
            n += 1
        return n

    def start(self) -> None:
        """Background driver thread (idles when there is no work)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                if (self.scheduler.queue_depth == 0
                        and self.pool.busy_count == 0):
                    time.sleep(0.002)
                    continue
                self.step()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="serving-engine")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # ---- metrics ----
    def reset_stats(self) -> None:
        """Zero the rolling serving stats and restart the throughput
        clock — call after warm-up (compiles) so steady-state numbers
        don't absorb one-off costs (bench.py's serving section does)."""
        with self._lock:
            self._t0 = time.monotonic()
            self._ttft_ms = []
            self._tok_lat_ms = []
            self._tokens_emitted = 0
            self._ticks = 0
            self._occupancy_sum = 0.0
            self._rejected = 0

    def metrics(self) -> Dict[str, float]:
        """Host-side serving summary (the Prometheus ``extra_gauges`` /
        bench-section payload).  ``*_ms`` keys are lower-is-better under
        the regression gate's direction inference."""
        with self._lock:
            el = max(time.monotonic() - self._t0, 1e-9)
            out = {
                "serving/tokens_per_sec": self._tokens_emitted / el,
                "serving/tokens_total": float(self._tokens_emitted),
                "serving/ticks": float(self._ticks),
                "serving/queue_depth": float(self.scheduler.queue_depth),
                "serving/active_slots": float(self.pool.busy_count),
                "serving/rejected_total": float(self._rejected),
                "serving/slot_occupancy_pct": 100.0 * (
                    self._occupancy_sum / self._ticks if self._ticks
                    else 0.0),
            }
            for name, vals in (("ttft", self._ttft_ms),
                               ("token_latency", self._tok_lat_ms)):
                p50 = _percentile(vals, 50)
                p99 = _percentile(vals, 99)
                if p50 is not None:
                    out[f"serving/{name}_p50_ms"] = p50
                    out[f"serving/{name}_p99_ms"] = p99
        return out

    def write_prometheus(self, path: str) -> str:
        """Atomic Prometheus textfile: tracer counters/gauges + the
        serving summary as extra gauges."""
        from ..observability.export import write_prometheus_textfile
        return write_prometheus_textfile(path, extra_gauges=self.metrics())

    def finalize_metrics(self) -> None:
        """Append the ``serving_summary`` JSONL record (clean-exit
        roll-up) when a metrics writer is configured."""
        if self.metrics_writer is not None:
            self.metrics_writer.write(self.metrics(), kind="serving_summary")
