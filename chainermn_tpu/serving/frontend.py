"""Threaded serving API: submit → handle, streaming tokens, metrics.

:class:`ServingEngine` glues the host scheduler, the slot pool, and the
compiled per-tick programs into the loop a service actually runs::

    eng = ServingEngine(params, head_dim=8, n_slots=4, max_total=64)
    h = eng.submit([3, 1, 4], max_new_tokens=16,
                   on_token=lambda tok, req_id: print(tok))
    eng.start()            # background driver thread (or drive eng.step()
    h.wait(timeout=30)     # synchronously from a test)
    print(h.tokens, h.status, h.ttft_ms)

Each ``step()`` is one engine iteration: expire overdue queued work,
admit (prefill) up to the interleaving bound, run ONE decode tick over
the pool, stream the new tokens, evict finished sequences.  Requests
therefore join and leave between ticks — a late submit starts decoding
as soon as a slot frees, while earlier sequences keep running
(iteration-level / continuous batching).

Observability (the PR 1/2 substrate + the ISSUE 5 production triad,
docs/OBSERVABILITY.md):

* per-request PHASE TIMESTAMPS on the handle (``submitted``,
  ``prefill_start``, ``first_token``, ``finished``) — the span data the
  integration test asserts on — mirrored into the tracer as
  ``serving/request/*`` instants (+ a real ``serving/prefill`` /
  ``serving/tick`` span around each device call) when tracing is on;
* **per-request distributed tracing**: every request carries a
  ``trace_id``; queue-wait / prefill / each decode tick become REAL
  tracer spans carrying it, plus one Chrome async flow (``cat
  "serving_request"``, ``id`` = trace id) from submit to finish — so a
  request renders as its own lane in the PR 2 merged Perfetto doc;
* **goodput attribution**: a :class:`~chainermn_tpu.observability.slo
  .GoodputLedger` partitions the engine's wall clock into compute /
  compile / host / queue-wait / stall buckets (sums match wall within
  5% — the acceptance gate), reported via :meth:`metrics`;
* **SLO tracking**: an optional :class:`~chainermn_tpu.observability
  .slo.SLOTracker` observes every TTFT and the rolling tokens/s, firing
  multi-window burn-rate findings down the PR 2 anomaly path;
* **flight recorder**: admissions, evictions, expiries, errors, and
  engine phases tee into the ring, and the engine registers a
  ``serving`` state provider so every debug bundle / ``/statusz`` hit
  carries live queue/slot/request state;
* serving GAUGES through the tracer (``serving/queue_depth``,
  ``serving/active_slots``, ``serving/tokens_per_sec``) so
  ``observability.export.write_prometheus_textfile`` scrapes them with
  everything else, plus :meth:`ServingEngine.metrics` (TTFT p50/p99,
  per-token latency, slot occupancy — O(1)-memory reservoir samples,
  never unbounded lists) as the ``extra_gauges`` / bench-section
  payload;
* optional per-step JSONL via ``observability.export.MetricsWriter``
  (kind ``serving_step`` records + one ``serving_summary``), the
  ``scripts/check_perf_regression.py``-gateable stream.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .. import observability as obs
from ..observability import flight as _flight
from ..observability.slo import GoodputLedger, ReservoirSample, SLOTracker
from .cache_pool import CachePool
from .engine import DecodeEngine
from .prefix_cache import PrefixCache
from .scheduler import AdmissionError, Request, Scheduler


class RequestHandle:
    """Caller's view of one submitted request (thread-safe reads)."""

    def __init__(self, req: Request):
        self._req = req

    @property
    def id(self) -> int:
        return self._req.id

    @property
    def trace_id(self) -> str:
        return self._req.trace_id

    @property
    def status(self) -> str:
        return self._req.status

    @property
    def finish_reason(self) -> Optional[str]:
        return self._req.finish_reason

    @property
    def tokens(self) -> List[int]:
        return list(self._req.tokens)

    @property
    def timestamps(self) -> Dict[str, float]:
        return dict(self._req.timestamps)

    @property
    def ttft_ms(self) -> Optional[float]:
        ts = self._req.timestamps
        if "submitted" in ts and "first_token" in ts:
            return (ts["first_token"] - ts["submitted"]) * 1e3
        return None

    @property
    def shed_payload(self) -> Optional[Dict[str, Any]]:
        """The machine-readable ``AdmissionError.to_dict()`` payload
        when a fleet shed this ALREADY-ACCEPTED request (reason
        ``worker_lost``): its disagg prefill worker died mid-transfer
        with no retry budget (ISSUE 9), or its cross-process worker
        missed the lease window with no survivor / spent the failover
        budget (ISSUE 10).  Carries ``retry_after_ms`` — clients honor
        it with ``serving.fleet.submit_with_retry``.  None otherwise."""
        return getattr(self._req, "shed_payload", None)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request finishes; True iff it did."""
        return self._req.done_event.wait(timeout)


def _request_row(req: Request) -> Dict[str, Any]:
    """One JSON-able /requestz row (also the bundle's serving view)."""
    ts = dict(req.timestamps)
    row = {
        "id": req.id,
        "trace_id": req.trace_id,
        "status": req.status,
        "finish_reason": req.finish_reason,
        "slot": req.slot,
        "prompt_len": req.prompt_len,
        "max_new_tokens": req.max_new_tokens,
        "n_tokens": len(req.tokens),
        "timestamps": {k: round(v, 6) for k, v in ts.items()},
        # tenancy columns (ISSUE 11 plane, ISSUE 17 satellite): always
        # present so the table schema is stable — None means the
        # request never crossed a tenant-aware router
        "tenant": req.tenant,
        "priority": getattr(req, "priority", None),
        "rung": getattr(req, "rung", None),
    }
    if "submitted" in ts and "first_token" in ts:
        row["ttft_ms"] = round(
            (ts["first_token"] - ts["submitted"]) * 1e3, 3)
    return row


class ServingEngine:
    """Continuous-batching inference engine over a slot-managed KV pool.

    ``params``: GLOBAL ``init_tp_transformer_lm`` arrays.  Decoding is
    greedy by default; ``submit(temperature=..., rng=...)`` samples
    per-request through the shared tick under the ``lm_generate`` rng
    contract (ISSUE 9; temperature > 0 REQUIRES an explicit key — see
    docs/SERVING.md).
    ``max_total`` bounds each slot's sequence (prompt + generated); a
    request that cannot fit is REJECTED at submit (``AdmissionError``,
    reason ``too_long``), as is any submit while the bounded queue is
    full (``queue_full``) — backpressure is explicit, never buffered.
    """

    def __init__(self, params, *, head_dim: int, n_slots: int = 4,
                 max_total: int = 128, mesh=None, axis_name: str = "model",
                 queue_capacity: int = 16, max_prefills_per_tick: int = 1,
                 prefill_bucket: int = 1, metrics_writer=None,
                 stats_capacity: int = 1024,
                 slo: Optional[SLOTracker] = None,
                 recent_capacity: int = 64,
                 prefix_cache: bool = True,
                 min_prefix_len: int = 2,
                 spill_bytes: int = 32 << 20):
        from ..parallel.decode import _kv_heads

        n_kv = _kv_heads(params, head_dim)
        dtype = params["embed"].dtype
        # pool and engine share one mesh (created here when not given,
        # like make_lm_generator)
        if mesh is None:
            from ..topology import make_mesh
            mesh = make_mesh(axis_name=axis_name)
        self.pool = CachePool(n_slots, max_total, len(params["blocks"]),
                              n_kv * head_dim, dtype, mesh, axis_name)
        self.engine = DecodeEngine(params, self.pool, mesh, axis_name,
                                   head_dim=head_dim,
                                   prefill_bucket=prefill_bucket)
        self.scheduler = Scheduler(
            queue_capacity, max_total,
            max_prefills_per_tick=max_prefills_per_tick,
            max_positions=self.engine.max_positions)
        # radix-trie prefix cache (ISSUE 7): finished requests donate
        # their slot (busy -> cached, read-only, refcounted); admission
        # scavenges rc==0 entries LRU-first when the free list is empty
        self.prefix_cache: Optional[PrefixCache] = None
        if prefix_cache:
            self.prefix_cache = PrefixCache(
                retain_slot=self.pool.retain,
                release_slot=self.pool.unretain,
                evict_slot=self.pool.uncache,
                min_prefix_len=min_prefix_len,
                on_insert=self._on_prefix_insert,
                on_evict=self._on_prefix_evict)
        # host-RAM spill tier (ISSUE 12): a scavenged rc==0 prefix slot
        # spills its CRC-stamped slab into a bounded LRU host store
        # instead of vanishing; a later matching prompt restores it
        # through the pool-lifetime compiled inject program instead of
        # re-prefilling.  spill_bytes=0 disables the tier.
        self.spill = None
        self._spill_plane = None
        if prefix_cache and int(spill_bytes) > 0:
            from .spill import HostSpillStore
            from .transfer import KvTransferPlane
            self.spill = HostSpillStore(
                capacity_bytes=int(spill_bytes),
                on_evict=self._on_spill_evict)
            self._spill_plane = KvTransferPlane()
        # fleet-economy hooks (ISSUE 12): the cross-process worker
        # announces this engine's cache lifecycle over the mailbox wire
        # so the router's global index can route remote pulls here.
        # ``on_cache_insert(entry)``, ``on_cache_evict(entry, spilled)``,
        # ``on_spill_evict(seq, length)``.
        self.on_cache_insert = None
        self.on_cache_evict = None
        self.on_spill_evict = None
        self.metrics_writer = metrics_writer
        self._running: Dict[int, Request] = {}   # slot -> request
        self._lock = threading.Lock()            # guards _running + stats
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # rolling stats (host floats only).  Latency percentiles come
        # from FIXED-SIZE reservoirs, not unbounded lists: metrics() is
        # O(1) memory however long the serve loop runs (ISSUE 5).
        self.stats_capacity = int(stats_capacity)
        self._ttft_ms = ReservoirSample(self.stats_capacity)
        self._tok_lat_ms = ReservoirSample(self.stats_capacity)
        # decode tick-GAP: wall between consecutive tick starts while
        # work is active — the inter-token latency a decoding request
        # actually experiences.  In a fused engine a prefill between
        # ticks inflates it; on a disagg decode worker it stays tight —
        # the ISSUE 9 acceptance metric (tick_gap p99/p50 collapse).
        self._tick_gap_ms = ReservoirSample(self.stats_capacity)
        self._last_tick_start: Optional[float] = None
        # per-slot sampling operands (ISSUE 9): each slot's request rng
        # key + temperature ride every tick; greedy slots carry zeros
        # (their key is never consumed)
        self._slot_keys = np.zeros((self.pool.n_slots, 2), np.uint32)
        self._slot_temps = np.zeros(self.pool.n_slots, np.float32)
        self._tokens_emitted = 0
        self._ticks = 0
        self._occupancy_sum = 0.0
        self._rejected = 0
        self._t0 = time.monotonic()
        # goodput attribution: step() partitions its own wall clock, and
        # the gap between steps books as queue_wait (work was waiting)
        # or stall (engine idle) — sums reconcile against wall within 5%
        self.goodput = GoodputLedger()
        self._last_step_end: Optional[float] = None
        self.slo = slo
        # last SLO throughput observation point (tokens, monotonic t):
        # the tracker must see the RECENT rate, not the run-cumulative
        # average a long healthy history would pin above any target
        self._slo_last = (0, self._t0)
        # recently finished requests for /requestz and the debug bundle
        self._recent: deque = deque(maxlen=int(recent_capacity))
        # flight provider: every bundle / statusz hit carries live
        # queue/slot/request state (survives because dump reads it at
        # crash time, not at construction time)
        _flight.register_provider("serving", self.introspect_state)

    # ---- submission ----
    def submit(self, prompt, max_new_tokens: int, *,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               on_token: Optional[Callable[[int, int], None]] = None,
               trace_id: Optional[str] = None,
               temperature: float = 0.0,
               rng=None,
               tenant: Optional[str] = None) -> RequestHandle:
        """Enqueue a generation request; raises :class:`AdmissionError`
        (with ``.reason``) when the queue is full or it can never fit.
        ``on_token(token, request_id)`` streams each token from the
        driver thread as it is emitted; ``deadline_s`` is relative to
        now.  ``trace_id`` lets an upstream hop (the serving router)
        mint the distributed trace identity so its spans and the
        engine's merge into one Perfetto lane.  ``temperature > 0``
        samples this request's tokens through the shared tick and
        REQUIRES an explicit ``rng`` key (the ``lm_generate`` contract:
        a silent default key would draw identical sequences every
        call); greedy requests omit both.  ``tenant`` stamps the
        request's billing identity (ISSUE 11) — budgets and priority
        live at the ROUTER's tenant plane; the engine only carries the
        attribution into /requestz rows and shed payloads."""
        now = time.monotonic()
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        temperature = float(temperature)
        if temperature > 0.0 and rng is None:
            raise ValueError(
                "temperature > 0 samples tokens and needs an explicit "
                "rng: pass jax.random.PRNGKey(...) (the lm_generate "
                "contract — a silent default key would make every "
                "sampled request draw IDENTICAL token sequences)")
        key = (None if rng is None
               else np.asarray(rng, np.uint32).reshape(2))
        req = Request(prompt, max_new_tokens, eos_id=eos_id,
                      deadline_t=(now + deadline_s
                                  if deadline_s is not None else None),
                      on_token=on_token, trace_id=trace_id,
                      temperature=temperature, rng=key, tenant=tenant)
        # tracer-clock stamp + flow BEGIN before the request becomes
        # visible to the scheduler: with start()'s driver thread, a
        # request can be admitted (even finished) the instant submit()
        # publishes it, and a later 'b' event would postdate its own
        # 'n'/'e' — the queue-wait span reads trace_us at admission
        req.trace_us = {"submitted": obs.now_us()}
        obs.async_event("b", "request", req.trace_id,
                        cat="serving_request", request=req.id,
                        prompt_len=req.prompt_len)
        try:
            # the PADDED prefill length is what must fit the slot (and
            # the learned-pos table) — the scheduler only knows raw
            # lengths, so the bucket-aware check lives here
            s_pad = self.engine.padded_len(req.prompt_len)
            cap = self.pool.max_total
            if self.engine.max_positions is not None:
                cap = min(cap, self.engine.max_positions)
            if s_pad > cap:
                raise AdmissionError(
                    "too_long",
                    f"prompt {req.prompt_len} pads to {s_pad} "
                    f"(prefill_bucket {self.engine.prefill_bucket}), "
                    f"exceeding per-slot capacity {cap}")
            self.scheduler.submit(req, now)
        except AdmissionError as e:
            with self._lock:
                self._rejected += 1
            # close the flow we opened: a rejected request must not
            # leave a dangling async lane
            obs.async_event("e", "request", req.trace_id,
                            cat="serving_request", reason="rejected",
                            admission_reason=e.reason)
            _flight.note("serving", event="rejected", request=req.id,
                         trace_id=req.trace_id, reason=e.reason)
            raise
        obs.instant("serving/request/queued", cat="serving",
                    request=req.id, trace_id=req.trace_id)
        _flight.note("serving", event="queued", request=req.id,
                     trace_id=req.trace_id, prompt_len=req.prompt_len)
        obs.set_gauge("serving/queue_depth", self.scheduler.queue_depth)
        return RequestHandle(req)

    # ---- the engine iteration ----
    def step(self) -> Dict[str, float]:
        """ONE engine iteration: expire → admit/prefill → tick → evict.
        Returns host-side stats for the iteration (also streamed to the
        JSONL metrics writer when configured).

        Goodput attribution: the whole iteration's wall clock lands in
        ledger buckets — prefill/tick device calls as ``compute`` (or
        ``compile`` on a call that built a new program), everything
        around them as ``host``, and the gap since the previous step as
        ``queue_wait`` (work was waiting) or ``stall`` (idle)."""
        t_step0 = time.monotonic()
        # the gap since the previous step — or, on the FIRST step, since
        # construction/reset: a fleet replica can idle a long time while
        # a sibling compiles, and leaving that window unattributed would
        # swamp its ledger's coverage (ISSUE 7)
        last = (self._last_step_end if self._last_step_end is not None
                else self._t0)
        gap = t_step0 - last
        if gap > 0:
            had_work = (self.scheduler.queue_depth > 0
                        or self.pool.busy_count > 0)
            self.goodput.add("queue_wait" if had_work else "stall", gap)
        t_host = t_step0                       # start of current host segment

        now = time.monotonic()
        for req in self.scheduler.expire_queued(now):
            obs.instant("serving/request/expired", cat="serving",
                        request=req.id, trace_id=req.trace_id)
            self._finish_tracing(req, "deadline")

        # admit up to the interleave bound into free slots; rc==0 cached
        # prefix slots count as free-after-eviction (scavengeable)
        avail = self.pool.free_count
        if self.prefix_cache is not None:
            avail += self.prefix_cache.evictable_count()
        admitted_batch = self.scheduler.admissions(avail, now)
        for batch_i, req in enumerate(admitted_batch):
            # match-and-PIN the radix trie BEFORE taking a slot: the
            # acquire below may scavenge an rc==0 cached slot, and an
            # unpinned match would be its own eviction victim — under a
            # saturated pool every donation would be scavenged by the
            # next admission and the cache could never produce a hit
            entry = None
            mlen = 0
            if self.prefix_cache is not None:
                entry, mlen = self.prefix_cache.match(req.prompt)
                if entry is not None:
                    self.prefix_cache.retain(entry)
                    req.prefix_entry, req.prefix_len = entry, mlen
            slot = self._acquire_slot()
            if slot is None and entry is not None:
                # OUR OWN match is the only scavengeable slot: with no
                # busy slots nothing else will ever free one, so give
                # up the hit rather than stall the pool — unpin and
                # scavenge it like any other cold entry (and back the
                # counters out: this became a miss)
                self.prefix_cache.release(entry)
                self.prefix_cache.hits -= 1
                self.prefix_cache.misses += 1
                self.prefix_cache.tokens_reused -= mlen
                req.prefix_entry, req.prefix_len = None, 0
                entry, mlen = None, 0
                slot = self._acquire_slot()
            if slot is None:
                # every scavengeable slot is pinned by EARLIER
                # admissions in this batch — put THIS request AND every
                # later one admissions() already popped back at the
                # queue head (reverse order keeps FIFO; dropping them
                # would strand their handles un-done forever); a
                # finishing request unblocks the next step
                for later in reversed(admitted_batch[batch_i:]):
                    self.scheduler.requeue_front(later)
                break
            req.slot = slot
            req.status = "running"
            t_admit = time.monotonic()
            req.timestamps["prefill_start"] = t_admit
            # the queue-wait span, retrospectively: submit → this admit
            t_us = getattr(req, "trace_us", None)
            if t_us is not None:
                now_us = obs.now_us()
                obs.complete_event(
                    "request/queue_wait", t_us["submitted"],
                    now_us - t_us["submitted"], cat="serving_request",
                    trace_id=req.trace_id, request=req.id)
            obs.instant("serving/request/prefill", cat="serving",
                        request=req.id, slot=slot, trace_id=req.trace_id)
            _flight.note("serving", event="admitted", request=req.id,
                         trace_id=req.trace_id, slot=slot)
            # prefix HIT (matched above): copy the cached slot's K/V
            # instead of re-prefilling the shared prefix; the un-cached
            # suffix feeds through the shared decode tick one token per
            # iteration (``req.forced``)
            if entry is not None:
                req.forced.extend(req.prompt[mlen:])
                self._set_slot_sampling(slot, req)
                self.goodput.add("host", t_admit - t_host)
                t_cp = time.monotonic()
                try:
                    with obs.span("serving/prefix_copy",
                                  cat="serving_request", request=req.id,
                                  trace_id=req.trace_id, slot=slot,
                                  src_slot=entry.slot, prefix_len=mlen):
                        self.engine.copy_prefix(entry.slot, slot, mlen)
                    t_host = time.monotonic()
                    self.goodput.add("compute", t_host - t_cp)
                except Exception as e:
                    t_host = time.monotonic()
                    self.goodput.add("compute", t_host - t_cp)
                    self._abort_slot(req, slot)
                    req.finish("error", time.monotonic())
                    obs.instant("serving/request/error", cat="serving",
                                request=req.id, trace_id=req.trace_id)
                    _flight.note("serving", event="error",
                                 request=req.id, trace_id=req.trace_id,
                                 error=repr(e))
                    self._finish_tracing(req, "error")
                    print(f"chainermn_tpu.serving: prefix copy for "
                          f"request {req.id} failed: {e!r}",
                          file=sys.stderr)
                    continue
                obs.instant("serving/request/prefix_hit", cat="serving",
                            request=req.id, slot=slot,
                            trace_id=req.trace_id, prefix_len=mlen,
                            src_slot=entry.slot)
                _flight.note("serving", event="prefix_hit",
                             request=req.id, trace_id=req.trace_id,
                             slot=slot, prefix_len=mlen)
                with self._lock:
                    self._running[slot] = req
                # no token yet: the suffix's LAST tick emits the first
                # one; only the deadline can evict before that
                self._maybe_evict(req, time.monotonic())
                continue
            # device-cache miss: the host spill tier may still hold the
            # prefix (ISSUE 12) — restore lands the CRC-verified slab
            # straight into THIS request's slot and feeds the suffix
            # through the shared tick, exactly the copy-on-extend shape
            if self.spill is not None:
                t_rs = time.monotonic()
                self.goodput.add("host", t_rs - t_host)
                with obs.span("serving/spill_restore",
                              cat="serving_request", request=req.id,
                              trace_id=req.trace_id, slot=slot):
                    rlen = self._try_restore(req, slot)
                t_host = time.monotonic()
                self.goodput.add("compute" if rlen else "host",
                                 t_host - t_rs)
                if rlen:
                    req.forced.extend(req.prompt[rlen:])
                    self._set_slot_sampling(slot, req)
                    obs.instant("serving/request/spill_restore",
                                cat="serving", request=req.id,
                                slot=slot, trace_id=req.trace_id,
                                prefix_len=rlen)
                    _flight.note("serving", event="restore",
                                 request=req.id, trace_id=req.trace_id,
                                 slot=slot, prefix_len=rlen)
                    with self._lock:
                        self._running[slot] = req
                    self._maybe_evict(req, time.monotonic())
                    continue
            try:
                # a failed restore attempt above already booked its own
                # wall and advanced t_host past t_admit — never book a
                # negative host segment
                self.goodput.add("host", max(t_admit - t_host, 0.0))
                compiles_before = self.engine.prefill_compiles
                t_pf = time.monotonic()
                with obs.span("serving/prefill", cat="serving_request",
                              request=req.id, trace_id=req.trace_id,
                              slot=slot):
                    first = self.engine.prefill_into_slot(
                        req.prompt, slot, rng=req.rng,
                        temperature=req.temperature)
                self._set_slot_sampling(slot, req)
                t_host = time.monotonic()
                # the engine's own counter says whether THIS call built
                # a new program — no probing of its cache internals
                self.goodput.add(
                    "compile" if self.engine.prefill_compiles
                    > compiles_before else "compute", t_host - t_pf)
            except Exception as e:
                t_host = time.monotonic()
                self.goodput.add("compute", t_host - t_pf)
                # never die holding a slot: a failed prefill (engine bug,
                # OOM, ...) releases the slot and fails THIS request only
                # — with start() an escaping exception would kill the
                # background thread and stall every other request, so the
                # engine sheds the request and keeps serving
                self._abort_slot(req, slot)
                req.finish("error", time.monotonic())
                obs.instant("serving/request/error", cat="serving",
                            request=req.id, trace_id=req.trace_id)
                _flight.note("serving", event="error", request=req.id,
                             trace_id=req.trace_id, error=repr(e))
                self._finish_tracing(req, "error")
                print(f"chainermn_tpu.serving: prefill of request "
                      f"{req.id} failed: {e!r}", file=sys.stderr)
                continue
            self._emit(req, first, time.monotonic())
            with self._lock:
                self._running[slot] = req
            self._maybe_evict(req, time.monotonic())

        # one decode tick over the pool (skip when nothing is active)
        with self._lock:
            active = dict(self._running)
        if active:
            tokens = np.zeros(self.pool.n_slots, np.int32)
            for slot, req in active.items():
                # a prefix-hit request still owing suffix tokens feeds
                # the next PROMPT token (its K/V row gets written; the
                # prediction is known and discarded until the last one)
                tokens[slot] = (req.forced[0] if req.forced
                                else req.tokens[-1])
            t_tick = time.monotonic()
            self.goodput.add("host", t_tick - t_host)
            # inter-tick gap: what a decoding request waits between its
            # tokens — includes any prefill that ran above (the fused
            # engine's tail; see the disagg bench section, ISSUE 9).
            # Locked with reset_stats: a bench warm-up reset racing this
            # read-modify-write could book one warm-up gap into the
            # gated window (the unguarded-shared-write lint class)
            with self._lock:
                if self._last_tick_start is not None:
                    self._tick_gap_ms.add(
                        (t_tick - self._last_tick_start) * 1e3)
                self._last_tick_start = t_tick
            tick_bucket = ("compile" if self.engine.tick_calls == 0
                           else "compute")
            t_tick_us = obs.now_us()
            with obs.span("serving/tick", cat="serving",
                          active=len(active)):
                with self.goodput.measure(tick_bucket):
                    nxt = self.engine.tick(tokens, self._slot_keys,
                                           self._slot_temps)
            t_host = time.monotonic()
            dt_ms = (t_host - t_tick) * 1e3
            dt_us = obs.now_us() - t_tick_us
            now = time.monotonic()
            for slot, req in active.items():
                # per-request decode-tick span, nested under the engine
                # tick on the timeline and keyed by the trace id
                obs.complete_event(
                    "request/decode_tick", t_tick_us, dt_us,
                    cat="serving_request", trace_id=req.trace_id,
                    request=req.id, slot=slot, active=len(active))
                still_forced = False
                if req.forced:
                    req.forced.popleft()
                    still_forced = bool(req.forced)
                if not still_forced:
                    # miss path, or the suffix's last prompt token just
                    # ran: the tick's prediction IS the next real token
                    self._emit(req, int(nxt[slot]), now)
                self._tok_lat_ms.add(dt_ms / max(len(active), 1))
                self._maybe_evict(req, now)
        else:
            # an idle step breaks the tick cadence: the next gap would
            # measure stall, not inter-token latency — restart the clock
            with self._lock:
                self._last_tick_start = None

        with self._lock:
            self._ticks += 1
            self._occupancy_sum += self.pool.busy_count / self.pool.n_slots
            stats = {
                "queue_depth": float(self.scheduler.queue_depth),
                "active_slots": float(self.pool.busy_count),
                "tokens_emitted": float(self._tokens_emitted),
            }
        obs.set_gauge("serving/queue_depth", stats["queue_depth"])
        obs.set_gauge("serving/active_slots", stats["active_slots"])
        el = time.monotonic() - self._t0
        if el > 0:
            obs.set_gauge("serving/tokens_per_sec",
                          self._tokens_emitted / el)
        if self.slo is not None and active:
            # per-step instantaneous rate: tokens since the previous
            # observation over the elapsed gap (idle steps don't count
            # — zero demand is not an SLO violation).  The read-modify-
            # write of _slo_last is atomic vs reset_stats; the SLO
            # observation happens OUTSIDE the lock (SLOTracker has its
            # own — nesting them would order the two locks)
            now_t = time.monotonic()
            with self._lock:
                last_tok, last_t = self._slo_last
                emitted = self._tokens_emitted
                self._slo_last = (emitted, now_t)
            dt = now_t - last_t
            if dt > 0:
                self.slo.observe_throughput((emitted - last_tok) / dt)
        if self.metrics_writer is not None:
            self.metrics_writer.write(
                {f"serving/{k}": v for k, v in stats.items()},
                kind="serving_step")
        t_end = time.monotonic()
        self.goodput.add("host", t_end - t_host)
        with self._lock:
            self._last_step_end = t_end
        # phase stamp: the ring's "last completed unit of work" marker
        # (what explain_bundle names when a serve loop dies mid-flight)
        _flight.note("phase", name="serving/step", tick=self._ticks,
                     active=int(stats["active_slots"]))
        return stats

    def _emit(self, req: Request, token: int, now: float) -> None:
        req.tokens.append(int(token))
        if "first_token" not in req.timestamps:
            req.timestamps["first_token"] = now
            ttft = (now - req.timestamps["submitted"]) * 1e3
            with self._lock:
                self._ttft_ms.add(ttft)
            if self.slo is not None:
                self.slo.observe_ttft(ttft)
            obs.instant("serving/request/first_token", cat="serving",
                        request=req.id, trace_id=req.trace_id)
            obs.async_event("n", "first_token", req.trace_id,
                            cat="serving_request",
                            ttft_ms=round(ttft, 3))
        with self._lock:
            self._tokens_emitted += 1
        obs.add_counter("serving/tokens_total", 1)
        if req.on_token is not None:
            req.on_token(int(token), req.id)

    def _set_slot_sampling(self, slot: int, req: Request) -> None:
        """Install the occupant's rng key + temperature as the slot's
        tick operands (zeros for greedy — the key is never consumed)."""
        self._slot_keys[slot] = (req.rng if req.rng is not None
                                 else np.zeros(2, np.uint32))
        self._slot_temps[slot] = np.float32(req.temperature)

    # ---- disaggregation inject face (ISSUE 9) ----
    def install_request(self, req: Request, slot: int,
                        tokens) -> None:
        """Adopt an already-prefilled request whose KV slab the
        transfer plane just landed in ``slot`` (reservation committed
        and ``pool.pos[slot]`` set by the caller): install sampling
        operands, emit the tokens the prefill side already produced
        (the first one stamps TTFT and streams), and start ticking it
        next step.  The decode half of the disaggregated fleet — this
        engine never ran a prefill for ``req``."""
        req.slot = slot
        req.status = "running"
        now = time.monotonic()
        req.timestamps.setdefault("prefill_start", now)
        self._set_slot_sampling(slot, req)
        obs.instant("serving/request/installed", cat="serving",
                    request=req.id, slot=slot, trace_id=req.trace_id)
        _flight.note("serving", event="installed", request=req.id,
                     trace_id=req.trace_id, slot=slot,
                     pos=int(self.pool.pos[slot]))
        for tok in tokens:
            self._emit(req, int(tok), now)
        with self._lock:
            self._running[slot] = req
        self._maybe_evict(req, now)

    def _finish_tracing(self, req: Request, reason: str) -> None:
        """Close the request's async flow + tee the terminal event."""
        obs.async_event("e", "request", req.trace_id,
                        cat="serving_request", reason=reason,
                        n_tokens=len(req.tokens))
        _flight.note("serving", event="finished", request=req.id,
                     trace_id=req.trace_id, reason=reason,
                     n_tokens=len(req.tokens))
        with self._lock:
            self._recent.append(req)

    def _maybe_evict(self, req: Request, now: float) -> None:
        reason = self.scheduler.eviction_reason(req, now)
        if reason is None:
            return
        slot = req.slot
        req.finish(reason, now)
        with self._lock:
            self._running.pop(slot, None)
        self._retire_slot(req, slot)
        obs.instant("serving/request/complete", cat="serving",
                    request=req.id, reason=reason, trace_id=req.trace_id)
        self._finish_tracing(req, reason)

    # ---- slot lifecycle (prefix-cache aware; ISSUE 7) ----
    def _acquire_slot(self) -> Optional[int]:
        """Free slot, scavenging the LRU unpinned prefix entry when the
        free list is empty — the cache borrows capacity, never owns it."""
        slot = self.pool.acquire()
        if slot is None and self.prefix_cache is not None:
            if self.prefix_cache.evict_lru() is not None:
                slot = self.pool.acquire()
        return slot

    def _abort_slot(self, req: Request, slot: int) -> None:
        """Failed admission: unpin the request's prefix source (if any)
        and return the slot to the free list — never donate K/V that
        was only partially written."""
        if req.prefix_entry is not None and self.prefix_cache is not None:
            self.prefix_cache.release(req.prefix_entry)
            req.prefix_entry = None
        self._slot_temps[slot] = 0.0
        self.pool.release(slot)

    def _retire_slot(self, req: Request, slot: int) -> None:
        """Finished request: unpin its prefix source, then DONATE the
        slot to the prefix cache (busy → cached, rc=0) keyed by every
        K/V row actually written — ``prompt + generated[:-1]`` clipped
        to the slot's position — falling back to a plain release when
        the cache dedups the donation or is disabled."""
        cache = self.prefix_cache
        if req.prefix_entry is not None and cache is not None:
            cache.release(req.prefix_entry)
            req.prefix_entry = None
        # a freed/cached slot keeps ticking (one fixed program): force
        # its discarded garbage row back to the cheap greedy path
        self._slot_temps[slot] = 0.0
        if cache is not None:
            length = int(self.pool.pos[slot])
            seq = list(req.prompt) + list(req.tokens[:-1])
            if length >= cache.min_prefix_len \
                    and cache.insert(seq[:length], slot, length) is not None:
                self.pool.cache(slot)
                return
        self.pool.release(slot)

    # ---- KV-economy lifecycle (ISSUE 12): spill tier + fleet hooks ----
    def _on_prefix_insert(self, entry) -> None:
        if self.on_cache_insert is not None:
            self.on_cache_insert(entry)

    def _on_prefix_evict(self, entry) -> None:
        """Fires BEFORE the evicted entry's slot returns to the free
        list: pack its K/V into the host spill tier (so the prefix
        stays restorable), then tell the fleet layer whether the
        eviction demoted (spilled) or dropped the prefix."""
        spilled = self._maybe_spill(entry)
        if self.on_cache_evict is not None:
            self.on_cache_evict(entry, spilled)

    def _maybe_spill(self, entry) -> bool:
        if self.spill is None:
            return False
        try:
            payload = self._spill_plane.pack(
                self.pool, entry.slot, entry.length,
                meta={"seq": list(entry.seq), "length": entry.length})
            ok = self.spill.put(entry.seq, entry.length, payload)
        except Exception as e:  # noqa: BLE001 — a failed spill must
            # never break the eviction it rides on; the prefix just
            # re-prefills like it always did
            _flight.note("serving", event="spill_failed",
                         slot=entry.slot, error=repr(e))
            return False
        if ok:
            _flight.note("serving", event="spill", slot=entry.slot,
                         prefix_len=entry.length,
                         bytes=len(payload),
                         store_bytes=self.spill.bytes_held)
            obs.instant("serving/spill", cat="serving",
                        prefix_len=entry.length, bytes=len(payload))
        return ok

    def _on_spill_evict(self, seq, length) -> None:
        if self.on_spill_evict is not None:
            self.on_spill_evict(seq, length)

    def _try_restore(self, req: Request, slot: int) -> int:
        """Restore a spilled prefix directly into the request's own
        slot through the compiled inject program; returns the restored
        prefix length (0 = no usable spill, or the payload failed its
        CRC and the request falls back to a normal prefill).

        The payload may hold MORE rows than the prompt shares with the
        spilled sequence: every row is injected (the program takes no
        length operand), then ``pos`` is clamped to the matched length
        — rows above it are stale-but-unreachable by the standard
        masking argument (the occupant rewrites row ``p`` before its
        own ``pos`` reaches ``p``)."""
        from .transfer import SPILL_AXIS, SPILL_OP

        min_len = (self.prefix_cache.min_prefix_len
                   if self.prefix_cache is not None else 2)
        hit = self.spill.match(req.prompt, min_len=min_len)
        if hit is None:
            return 0
        seq, mlen = hit
        payload = self.spill.get(seq)
        if payload is None:
            return 0
        try:
            self._spill_plane.unpack_into(
                payload, self.pool, slot,
                ledger_op=SPILL_OP, ledger_axis=SPILL_AXIS)
        except ValueError as e:
            # CRC/schema/shape refusal: corrupt spill state is dropped
            # and counted, and the request re-prefills — wrong KV is
            # never served (the ISSUE 12 acceptance)
            self.spill.crc_refusals += 1
            self.spill.drop(seq)
            _flight.note("serving", event="spill_crc_refused",
                         request=req.id, trace_id=req.trace_id,
                         error=str(e))
            obs.instant("serving/spill_crc_refused", cat="serving",
                        request=req.id, trace_id=req.trace_id)
            return 0
        except Exception as e:  # noqa: BLE001 — inject failure: the
            # pool is unchanged (functional update never assigned);
            # fall back to the normal prefill
            _flight.note("serving", event="restore_failed",
                         request=req.id, trace_id=req.trace_id,
                         error=repr(e))
            return 0
        self.pool.pos[slot] = int(mlen)
        self.spill.restores += 1
        return int(mlen)

    # ---- driving ----
    def run(self, steps_budget: Optional[int] = None,
            drain: bool = True) -> int:
        """Drive ``step()`` until the engine is idle (queue empty, no
        active slots) or ``steps_budget`` iterations elapse; returns the
        number of iterations run.  ``drain=False`` stops at the budget
        even with work pending (the CLI's ``--steps-budget``)."""
        n = 0
        while not self._stop.is_set():
            if steps_budget is not None and n >= steps_budget:
                break
            busy = (self.scheduler.queue_depth > 0
                    or self.pool.busy_count > 0)
            if not busy:
                if drain:
                    break
                time.sleep(0.001)
                continue
            self.step()
            n += 1
        return n

    def start(self) -> None:
        """Background driver thread (idles when there is no work)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                if (self.scheduler.queue_depth == 0
                        and self.pool.busy_count == 0):
                    time.sleep(0.002)
                    continue
                self.step()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="serving-engine")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def close(self) -> None:
        """Retire the engine: stop the driver thread and drop the
        flight/statusz provider registration (which otherwise pins the
        engine — params + KV pool — for the process lifetime and would
        report this dead engine's state as live)."""
        self.stop()
        if _flight._PROVIDERS.get("serving") == self.introspect_state:
            _flight.unregister_provider("serving")

    # ---- metrics ----
    def reset_stats(self) -> None:
        """Zero the rolling serving stats and restart the throughput
        clock — call after warm-up (compiles) so steady-state numbers
        don't absorb one-off costs (bench.py's serving section does)."""
        with self._lock:
            self._t0 = time.monotonic()
            self._ttft_ms = ReservoirSample(self.stats_capacity)
            self._tok_lat_ms = ReservoirSample(self.stats_capacity)
            self._tick_gap_ms = ReservoirSample(self.stats_capacity)
            self._last_tick_start = None
            self._tokens_emitted = 0
            self._ticks = 0
            self._occupancy_sum = 0.0
            self._rejected = 0
            self.goodput.reset()
            self._last_step_end = None
            self._slo_last = (0, self._t0)
            if self.prefix_cache is not None:
                # zero the cumulative counters; entries/pins stay (the
                # warm cache IS the steady state bench measures)
                pc = self.prefix_cache
                pc.hits = pc.misses = pc.tokens_reused = 0
                pc.insertions = pc.rejected_insertions = 0
                pc.evictions = 0
            if self.spill is not None:
                # same discipline: counters reset, spilled payloads stay
                sp = self.spill
                sp.spills = sp.restores = sp.hits = sp.misses = 0
                sp.crc_refusals = sp.evictions = 0
                sp.rejected_oversize = 0

    def metrics(self) -> Dict[str, float]:
        """Host-side serving summary (the Prometheus ``extra_gauges`` /
        bench-section payload).  ``*_ms`` keys are lower-is-better under
        the regression gate's direction inference."""
        with self._lock:
            el = max(time.monotonic() - self._t0, 1e-9)
            out = {
                "serving/tokens_per_sec": self._tokens_emitted / el,
                "serving/tokens_total": float(self._tokens_emitted),
                "serving/ticks": float(self._ticks),
                "serving/queue_depth": float(self.scheduler.queue_depth),
                "serving/active_slots": float(self.pool.busy_count),
                "serving/rejected_total": float(self._rejected),
                "serving/slot_occupancy_pct": 100.0 * (
                    self._occupancy_sum / self._ticks if self._ticks
                    else 0.0),
            }
            for name, res in (("ttft", self._ttft_ms),
                              ("token_latency", self._tok_lat_ms),
                              ("tick_gap", self._tick_gap_ms)):
                p50 = res.percentile(50)
                p99 = res.percentile(99)
                if p50 is not None:
                    out[f"serving/{name}_p50_ms"] = p50
                    out[f"serving/{name}_p99_ms"] = p99
            gaps = self._tick_gap_ms.values()
            if len(gaps) >= 2:
                mean = sum(gaps) / len(gaps)
                out["serving/tick_gap_variance_ms2"] = (
                    sum((g - mean) ** 2 for g in gaps) / len(gaps))
        if self.prefix_cache is not None:
            for k, v in self.prefix_cache.stats().items():
                out[f"serving/prefix/{k}"] = v
            out["serving/prefix/cached_slots"] = float(
                self.pool.cached_count)
        if self.spill is not None:
            for k, v in self.spill.stats().items():
                out[f"serving/spill/{k}"] = v
        out.update(self.goodput.gauges("serving/goodput"))
        return out

    # ---- live introspection (/requestz, /statusz, debug bundles) ----
    def requests_table(self) -> Dict[str, Any]:
        """Queued + running + recently finished requests with their
        trace ids and phase timestamps (the /requestz payload)."""
        with self._lock:
            running = [_request_row(r) for r in self._running.values()]
            recent = [_request_row(r) for r in self._recent]
        return {
            "schema": "chainermn_tpu.requestz.v1",
            "queued": [_request_row(r)
                       for r in self.scheduler.queued_requests()],
            "running": running,
            "recent": list(reversed(recent)),  # newest first
        }

    def introspect_state(self) -> Dict[str, Any]:
        """The ``serving`` flight/statusz provider: engine config, slot
        and queue occupancy, compile counts, goodput, SLO state, and
        the request table — everything a postmortem asks first."""
        state: Dict[str, Any] = {
            "n_slots": self.pool.n_slots,
            "max_total": self.pool.max_total,
            "busy_slots": self.pool.busy_count,
            "free_slots": self.pool.free_count,
            "reserved_slots": self.pool.reserved_count,
            "queue_depth": self.scheduler.queue_depth,
            "queue_capacity": self.scheduler.queue_capacity,
            "ticks": self._ticks,
            "tokens_emitted": self._tokens_emitted,
            "rejected": self._rejected,
            "prefill_compiles": self.engine.prefill_compiles,
            "tick_calls": self.engine.tick_calls,
            "prefix_copies": self.engine.prefix_copies,
            "goodput": self.goodput.report(),
            "requests": self.requests_table(),
        }
        if self.prefix_cache is not None:
            state["prefix_cache"] = dict(
                self.prefix_cache.stats(),
                cached_slots=self.pool.cached_count,
                total_refcount=self.prefix_cache.total_refcount())
        if self.spill is not None:
            state["spill"] = self.spill.state()
        if self.slo is not None:
            state["slo"] = self.slo.status()
        return state

    def write_prometheus(self, path: str) -> str:
        """Atomic Prometheus textfile: tracer counters/gauges + the
        serving summary as extra gauges."""
        from ..observability.export import write_prometheus_textfile
        return write_prometheus_textfile(path, extra_gauges=self.metrics())

    def finalize_metrics(self) -> None:
        """Append the ``serving_summary`` JSONL record (clean-exit
        roll-up) when a metrics writer is configured."""
        if self.metrics_writer is not None:
            self.metrics_writer.write(self.metrics(), kind="serving_summary")
