"""Serving fleet router: dispatch, prefix affinity, SLO admission.

PR 3's :class:`~chainermn_tpu.serving.frontend.ServingEngine` is one
pool on one mesh; "millions of users" (ROADMAP item 3) needs the layer
above — the ChainerMN hierarchy lesson applied to serving: N engines as
the fast lane, this host-side router as the slow lane composing them
into ONE service.  Three policies, each deliberately inspectable:

**Dispatch** (least-loaded, deadline-aware, prefix-affine).  Every
candidate replica is scored in TOKEN units::

    score = prefix_match_len − backlog_tokens

``prefix_match_len`` (the replica's radix-trie peek) is compute the
replica does NOT have to do; ``backlog_tokens`` (queued prompt+decode
work plus running remainders) is compute it must do first.  One
currency, so affinity and load balance trade off without magic weights;
ties break to the emptier queue, then round-robin.  A request carrying
a deadline skips replicas whose estimated start delay
(``backlog_tokens × measured token-latency``) already overruns it.

**Admission control** (shed BEFORE the pager fires).  The router owns
the fleet :class:`~chainermn_tpu.observability.slo.SLOTracker` (every
replica feeds TTFT/throughput observations into it) and sheds load
with machine-readable rejections while the pages are still
*approaching*: when the short-window burn rate crosses
``shed_burn_threshold`` (default 1.0× budget — the level that, held,
eventually pages at ``burn_threshold``×) and the fleet has backlog, new
work is refused with ``AdmissionError(reason="shed_slo")`` carrying
``retry_after_ms`` and the fleet queue depth.  Deadline-infeasible
requests (no replica can start in time) shed the same way — a request
that will blow its deadline in the queue only burns budget.  Full
queues everywhere reject ``queue_full`` with the same payload.
Degradation is therefore by EXPLICIT REJECTION, never by queue
collapse: admitted requests' TTFT stays bounded by the queues the
router refused to overfill (the overload acceptance test in
tests/test_serving_router.py asserts this via the goodput ledger's
queue-wait split).

**Observability** (the ISSUE 5 triad, fleet-wide).  The router MINTS
each request's ``trace_id`` before dispatch and passes it through the
replica hop, so one merged Perfetto doc shows ``router/dispatch`` →
queue-wait → prefill/prefix-copy → per-tick spans under a single id.
Rejections are counted per reason in :meth:`metrics` (→ ``/metricsz``)
and streamed as ``router_rejection`` records in the serving JSONL;
``/statusz`` aggregates every replica's ``introspect_state()`` under
the ``router`` flight provider.  See docs/SERVING.md "Router, prefix
cache & admission".
"""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from .. import observability as obs
from ..observability import flight as _flight
from ..observability.slo import RateMeter, SLOTracker
from .autoscale import derive_retry_after_ms
from .frontend import RequestHandle
from .replica import Replica
from .scheduler import AdmissionError
from .tenancy import TenantTable

#: Rejection reasons a router can emit: PR 3's two, ISSUE 7's
#: ``shed_slo``, ISSUE 9's ``worker_lost`` (a disaggregated transfer's
#: source worker died and no survivor could re-run the prefill — the
#: request is shed with the same machine-readable shape), and ISSUE
#: 11's ``shed_tenant_budget`` (per-tenant admission budget exhausted
#: or best-effort admission paused at the top degradation rung — the
#: payload names the tenant and the rung).
REJECT_REASONS = ("queue_full", "too_long", "shed_slo", "worker_lost",
                  "shed_tenant_budget")


class RouterBase:
    """Shared router machinery (ISSUE 9 refactor, grown by ISSUE 11):
    trace-id minting, uniformly-shaped machine-readable rejections, the
    SLO-burn shed gate, the drain-aware ``retry_after_ms`` derivation,
    and the tenant plane — one implementation behind the replica fleet
    (:class:`ServingRouter`), the disaggregated fleet
    (``serving/disagg.py::DisaggRouter``), and the cross-process fleet
    (``serving/fleet.py::FleetRouter``), so every rejection anywhere in
    the serving stack carries the same ``AdmissionError.to_dict()``
    wire shape, per-reason counters, and JSONL/flight/tracer emissions.

    ``tenancy`` (a :class:`~chainermn_tpu.serving.tenancy.TenantTable`)
    turns on multi-tenant QoS: ``submit(tenant=, priority=)`` bills the
    request, per-tenant admission budgets refuse with
    ``shed_tenant_budget``, the degradation ladder walks best-effort
    service down before any paid tenant sheds, and the SLO gate gives
    paid tenants ``paid_burn_headroom``× more burn room than
    best-effort traffic.
    """

    #: flight/metrics namespace ("router" / "disagg") — subclasses set.
    ROLE = "router"

    def __init__(self, metrics_writer=None, *,
                 tenancy: Optional[TenantTable] = None,
                 slo: Optional[SLOTracker] = None,
                 shed_burn_threshold: float = 1.0,
                 paid_burn_headroom: float = 2.0,
                 default_token_latency_ms: float = 20.0):
        self.metrics_writer = metrics_writer
        self.tenancy = tenancy
        self.slo = slo
        self.shed_burn_threshold = float(shed_burn_threshold)
        self.paid_burn_headroom = float(paid_burn_headroom)
        self.default_token_latency_ms = float(default_token_latency_ms)
        self._lock = threading.Lock()
        self._ids = itertools.count()
        self._rejected: Dict[str, int] = {r: 0 for r in REJECT_REASONS}
        # drain-aware retry hints (ISSUE 11 satellite): recent fleet
        # tokens/s over a sliding window; deterministic jitter source
        self._tps_meter = RateMeter(window_s=5.0)
        self._retry_rng = random.Random(0xC0FFEE)

    def _mint_trace_id(self) -> str:
        return f"req-{os.getpid():x}-rt{next(self._ids):08x}"

    def rejection_counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._rejected)

    def _reject(self, reason: str, trace_id: str, detail: str, *,
                retry_after_ms: float, queue_depth: int,
                tenant: Optional[str] = None):
        rung = None
        if self.tenancy is not None:
            rung = self.tenancy.ladder.rung
            if tenant is not None:
                t = self.tenancy.get(tenant)
                if t is not None and t.priority == "best_effort":
                    # the ladder's throttle rung: best-effort clients
                    # back off harder than congestion alone implies
                    retry_after_ms *= \
                        self.tenancy.ladder.retry_multiplier()
                self.tenancy.count_shed(tenant, reason)
        with self._lock:
            self._rejected[reason] = self._rejected.get(reason, 0) + 1
        err = AdmissionError(reason, detail,
                             retry_after_ms=retry_after_ms,
                             queue_depth=queue_depth,
                             tenant=tenant, rung=rung)
        obs.instant(f"{self.ROLE}/rejected", cat="serving", reason=reason,
                    trace_id=trace_id, queue_depth=queue_depth)
        _flight.note(self.ROLE, event="rejected", reason=reason,
                     trace_id=trace_id, detail=detail,
                     **({"tenant": tenant} if tenant else {}))
        if self.metrics_writer is not None:
            record = dict({f"{self.ROLE}/{k}": v
                           for k, v in err.to_dict().items()
                           if not isinstance(v, str)},
                          reason=reason, trace_id=trace_id)
            if tenant is not None:
                record["tenant"] = tenant
            self.metrics_writer.write(record,
                                      kind=f"{self.ROLE}_rejection")
        raise err

    # ---- drain-aware back-off hints (ISSUE 11 satellite) ----
    def _derive_retry_ms(self, backlog_tokens: float,
                         tokens_total: float) -> float:
        """``retry_after_ms`` from the MEASURED backlog drain rate:
        feed the cumulative token counter into the sliding-window
        meter, then price the queued tokens at the recent rate
        (``autoscale.derive_retry_after_ms`` owns the clamped/jittered
        formula and its zero-throughput edges)."""
        self._tps_meter.observe(float(tokens_total))
        return derive_retry_after_ms(
            backlog_tokens, self._tps_meter.rate(),
            default_token_latency_ms=self.default_token_latency_ms,
            rng=self._retry_rng)

    @staticmethod
    def _lazy_ms(retry_after_ms) -> float:
        """Rejection helpers take the back-off hint as a VALUE or a
        zero-arg callable — callable lets the submit hot path defer the
        (per-worker-lock-taking) estimate to the reject branch."""
        return float(retry_after_ms() if callable(retry_after_ms)
                     else retry_after_ms)

    # ---- the shared SLO-burn shed gate (ISSUE 7 → 11) ----
    def _maybe_shed_slo(self, trace_id: str, queue_depth: int,
                        retry_after_ms,
                        tenant: Optional[str] = None) -> None:
        """Shed BEFORE the pager fires: when the short-window burn rate
        crosses ``shed_burn_threshold`` with backlog, refuse new work
        machine-readably.  A paid tenant's threshold is multiplied by
        ``paid_burn_headroom`` — best-effort traffic sheds first, and
        the paid tenant only sheds when the burn keeps climbing anyway
        (still below the 2-window pager when headroom < the tracker's
        ``burn_threshold``)."""
        if self.slo is None or queue_depth <= 0:
            return
        threshold = self.shed_burn_threshold
        if tenant is not None and self.tenancy is not None:
            t = self.tenancy.get(tenant)
            if t is not None and t.priority == "paid":
                threshold *= self.paid_burn_headroom
        burn = self.slo.short_window_burn()
        if burn is not None and burn > threshold:
            self._reject(
                "shed_slo", trace_id,
                f"short-window burn rate {burn:.2f}x exceeds "
                f"shed threshold {threshold}x with "
                f"{queue_depth} queued",
                retry_after_ms=self._lazy_ms(retry_after_ms),
                queue_depth=queue_depth, tenant=tenant)

    # ---- the tenant admission plane (ISSUE 11) ----
    def _overload_pressure(self, queue_depth: int,
                           queue_capacity: int) -> float:
        """The scalar the degradation ladder climbs on: how close the
        fleet is to shedding, as max(burn/shed-threshold, fleet queue
        fill fraction).  ``queue_capacity <= 0`` means UNKNOWN (a
        cross-process fleet whose workers have not published a lease
        yet) — unknown is not full: the fill term is skipped rather
        than dividing a raw depth by zero-ish and spuriously pausing
        best-effort admission during boot."""
        pressure = 0.0
        if queue_capacity > 0:
            pressure = float(queue_depth) / float(queue_capacity)
        if self.slo is not None:
            burn = self.slo.short_window_burn()
            if burn is not None:
                pressure = max(pressure,
                               burn / max(self.shed_burn_threshold,
                                          1e-9))
        return pressure

    def _admit_tenant(self, trace_id: str, tenant: Optional[str],
                      priority: Optional[str], max_new_tokens: int, *,
                      queue_depth: int, queue_capacity: int,
                      retry_after_ms):
        """The submit-path tenant gate: resolve/auto-register, advance
        the degradation ladder on the current overload pressure, refuse
        over-budget or paused best-effort work (``shed_tenant_budget``
        with tenant + rung), and clamp best-effort ``max_new_tokens``
        at the ``tight`` rung.  Returns ``(tenant_name, capped
        max_new_tokens, capped?)``; untagged traffic with no table
        passes through untouched."""
        if self.tenancy is None:
            return tenant, int(max_new_tokens), False
        tab = self.tenancy
        tab.ladder.update(
            self._overload_pressure(queue_depth, queue_capacity))
        if tenant is None:
            return None, int(max_new_tokens), False
        t = tab.resolve(tenant, priority)
        refused = tab.admission_check(t)
        if refused is not None:
            reason, detail = refused
            self._reject(reason, trace_id, detail,
                         retry_after_ms=self._lazy_ms(retry_after_ms),
                         queue_depth=queue_depth, tenant=t.name)
        capped = int(max_new_tokens)
        if t.priority == "best_effort":
            capped = tab.ladder.cap_max_tokens(capped)
        return t.name, capped, capped < int(max_new_tokens)

    def _stamp_tenant_meta(self, req, tenant: Optional[str]) -> None:
        """Stamp the admitted request with its resolved priority and
        the degradation rung it was admitted under — the stable
        /requestz tenancy columns (``_request_row`` always emits
        ``tenant``/``priority``/``rung``; None means the request never
        crossed a tenant-aware router)."""
        if self.tenancy is None:
            return
        req.rung = self.tenancy.ladder.rung
        if tenant is not None:
            req.priority = self.tenancy.resolve(tenant).priority


class ServingRouter(RouterBase):
    """Process-level router fronting N :class:`Replica` engines.

    ``slo``: the FLEET tracker (shared by every replica's engine so all
    TTFT/throughput observations land in one burn-rate budget); when
    None, ``shed_slo`` only fires on deadline infeasibility.
    ``shed_burn_threshold``: short-window burn rate above which new
    work is shed while backlog exists — set BELOW the tracker's paging
    ``burn_threshold`` so shedding starts before the page.
    """

    def __init__(self, replicas: Sequence[Replica], *,
                 slo: Optional[SLOTracker] = None,
                 shed_burn_threshold: float = 1.0,
                 default_token_latency_ms: float = 20.0,
                 metrics_writer=None,
                 tenancy: Optional[TenantTable] = None,
                 paid_burn_headroom: float = 2.0,
                 clock: Callable[[], float] = time.monotonic):
        if not replicas:
            raise ValueError("need at least one replica")
        super().__init__(
            metrics_writer=metrics_writer, tenancy=tenancy, slo=slo,
            shed_burn_threshold=shed_burn_threshold,
            paid_burn_headroom=paid_burn_headroom,
            default_token_latency_ms=default_token_latency_ms)
        self.replicas: List[Replica] = list(replicas)
        names = [r.name for r in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique: {names}")
        self._clock = clock
        self._rr = 0                      # round-robin tie-breaker
        self._dispatched = 0
        self._dispatched_by: Dict[str, int] = {n: 0 for n in names}
        self._affinity_hits = 0           # dispatches won by prefix len
        _flight.register_provider("router", self.introspect_state)

    # ---- submission ----
    def submit(self, prompt, max_new_tokens: int, *,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               on_token=None, temperature: float = 0.0,
               rng=None, tenant: Optional[str] = None,
               priority: Optional[str] = None) -> RequestHandle:
        """Dispatch to the best replica or raise :class:`AdmissionError`
        with a machine-readable reason + ``retry_after_ms`` +
        ``queue_depth`` (the shape ``.to_dict()`` serializes for 429
        bodies and the JSONL stream).  ``temperature``/``rng`` ride the
        hop unchanged (the engine enforces the sampling contract).
        ``tenant``/``priority`` bill the request to a tenant class
        (ISSUE 11): per-tenant budgets, degradation-ladder clamping of
        best-effort ``max_new_tokens``, and paid-first SLO protection
        all key off them (docs/SERVING.md "Multi-tenant QoS")."""
        trace_id = self._mint_trace_id()
        t0_us = obs.now_us()
        t_submit = time.monotonic()
        loads = [r.load() for r in self.replicas]
        fleet_depth = sum(ld["queue_depth"] for ld in loads)
        fleet_cap = sum(ld["queue_capacity"] for ld in loads)

        # tenant plane first (budgets/pause are cheaper than the SLO
        # math and independent of fleet state), then the shared
        # SLO-burn gate — best-effort sheds at the base threshold,
        # paid with paid_burn_headroom× more room
        tenant, max_new_tokens, capped = self._admit_tenant(
            trace_id, tenant, priority, max_new_tokens,
            queue_depth=fleet_depth, queue_capacity=fleet_cap,
            retry_after_ms=lambda: self._retry_after_ms(loads))
        self._maybe_shed_slo(trace_id, fleet_depth,
                             lambda: self._retry_after_ms(loads), tenant)

        candidates = []
        for i, (rep, ld) in enumerate(zip(self.replicas, loads)):
            if ld["queue_depth"] >= ld["queue_capacity"]:
                continue   # full: submitting would be rejected anyway
            wait_ms = ld["backlog_tokens"] * rep.token_latency_ms(
                self.default_token_latency_ms)
            if deadline_s is not None and wait_ms / 1e3 >= deadline_s:
                continue   # cannot start before the deadline
            match_len = rep.peek_prefix_len(prompt)
            score = match_len - ld["backlog_tokens"]
            candidates.append((score, -ld["queue_depth"], i, rep,
                               match_len))
        if not candidates:
            if all(ld["queue_depth"] >= ld["queue_capacity"]
                   for ld in loads):
                self._reject(
                    "queue_full", trace_id,
                    f"all {len(self.replicas)} replica queues at "
                    f"capacity",
                    retry_after_ms=self._retry_after_ms(loads),
                    queue_depth=fleet_depth, tenant=tenant)
            # queues have room but no replica can meet the deadline:
            # starting it anyway would only burn SLO budget
            self._reject(
                "shed_slo", trace_id,
                "no replica can start before the request deadline "
                f"(deadline_s={deadline_s})",
                retry_after_ms=self._retry_after_ms(loads),
                queue_depth=fleet_depth, tenant=tenant)

        # max score, then emptier queue, then round-robin (the i-index
        # rotation keeps a tied fleet evenly loaded)
        rr = self._rr
        best = max(candidates,
                   key=lambda c: (c[0], c[1], -((c[2] - rr)
                                                % len(self.replicas))))
        _, _, idx, rep, match_len = best
        self._rr = (idx + 1) % len(self.replicas)
        if self.tenancy is not None and tenant is not None:
            # per-tenant TTFT/goodput attribution rides the token
            # stream (the engine owns it; the router only sees submit)
            on_token = self.tenancy.wrap_on_token(tenant, t_submit,
                                                  on_token)
        try:
            handle = rep.submit(prompt, max_new_tokens, eos_id=eos_id,
                                deadline_s=deadline_s, on_token=on_token,
                                trace_id=trace_id, temperature=temperature,
                                rng=rng, tenant=tenant)
        except AdmissionError as e:
            # per-request races (another thread filled the queue) and
            # too_long both surface here; re-raise with the router's
            # payload attached so every rejection is uniformly shaped
            self._reject(e.reason, trace_id, str(e),
                         retry_after_ms=self._retry_after_ms(loads),
                         queue_depth=fleet_depth, tenant=tenant)
        self._stamp_tenant_meta(handle._req, tenant)
        if self.tenancy is not None and tenant is not None:
            self.tenancy.on_admit(self.tenancy.resolve(tenant),
                                  handle._req, capped=capped)
        with self._lock:
            self._dispatched += 1
            self._dispatched_by[rep.name] += 1
            if match_len > 0:
                self._affinity_hits += 1
        obs.complete_event(
            "router/dispatch", t0_us, obs.now_us() - t0_us,
            cat="serving_request", trace_id=trace_id, replica=rep.name,
            prefix_match_len=match_len, fleet_queue_depth=fleet_depth)
        _flight.note("router", event="dispatched", trace_id=trace_id,
                     replica=rep.name, prefix_match_len=match_len)
        return handle

    def _retry_after_ms(self, loads) -> float:
        """Back-off hint from the MEASURED drain rate (ISSUE 11): the
        least-loaded replica's queued tokens priced at the fleet's
        recent tokens-per-second — clamped and jittered by
        ``derive_retry_after_ms`` so retrying clients back off
        proportionally to real congestion and never re-arrive as a
        synchronized herd."""
        backlog = min(ld["backlog_tokens"] for ld in loads)
        tokens_total = sum(rep.engine._tokens_emitted
                           for rep in self.replicas)
        return self._derive_retry_ms(backlog, tokens_total)

    # ---- driving ----
    def step(self) -> int:
        """ONE fleet scheduling round: step every replica that has
        work; returns how many did (0 == drained).  The deterministic
        single-thread driver the tests and bench use; production runs
        :meth:`start` instead."""
        stepped = 0
        for rep in self.replicas:
            if not rep.idle:
                rep.step()
                stepped += 1
        return stepped

    def run(self, steps_budget: Optional[int] = None) -> int:
        """Drive :meth:`step` until the fleet drains or the budget
        runs out; returns rounds run."""
        n = 0
        while steps_budget is None or n < steps_budget:
            if self.step() == 0:
                break
            n += 1
        return n

    def start(self) -> None:
        for rep in self.replicas:
            rep.start()

    def stop(self) -> None:
        for rep in self.replicas:
            rep.stop()

    def close(self) -> None:
        for rep in self.replicas:
            rep.close()
        if _flight._PROVIDERS.get("router") == self.introspect_state:
            _flight.unregister_provider("router")

    def reset_stats(self) -> None:
        """Zero router counters AND every replica's rolling stats —
        call after warm-up so steady-state numbers don't absorb the
        one-off compiles (bench.py's serving_router section does)."""
        with self._lock:
            self._dispatched = 0
            self._dispatched_by = {n: 0 for n in self._dispatched_by}
            self._rejected = {r: 0 for r in REJECT_REASONS}
            self._affinity_hits = 0
        for rep in self.replicas:
            rep.engine.reset_stats()

    # ---- metrics / introspection ----
    def metrics(self) -> Dict[str, float]:
        """Fleet summary + per-reason rejection counters (the
        ``/metricsz`` ``extra_gauges`` payload and the bench section's
        source).  ``shed``/``rejected`` keys are lower-is-better under
        the regression gate's direction inference."""
        with self._lock:
            dispatched = self._dispatched
            rejected = dict(self._rejected)
            affinity = self._affinity_hits
        out: Dict[str, float] = {
            "router/replicas": float(len(self.replicas)),
            "router/dispatched_total": float(dispatched),
            "router/affinity_dispatches_total": float(affinity),
            "router/rejected_total": float(sum(rejected.values())),
        }
        for reason in REJECT_REASONS:
            out[f"router/rejected/{reason}"] = float(
                rejected.get(reason, 0))
        offered = dispatched + sum(rejected.values())
        out["router/shed_rate"] = (
            sum(rejected.values()) / offered if offered else 0.0)
        # fleet roll-ups from the engines' own metrics (one source of
        # truth); TTFT percentiles merge the replica reservoirs
        tps = occ = 0.0
        ttft_vals: List[float] = []
        for rep in self.replicas:
            m = rep.engine.metrics()
            tps += m["serving/tokens_per_sec"]
            occ += m["serving/slot_occupancy_pct"]
            ttft_vals.extend(rep.engine._ttft_ms.values())
            for k, v in m.items():
                out[f"router/{rep.name}/{k.split('/', 1)[1]}"] = v
        out["router/fleet_tokens_per_sec"] = tps
        out["router/fleet_slot_occupancy_pct"] = occ / len(self.replicas)
        if ttft_vals:
            from ..observability.slo import percentile_of
            out["router/fleet_ttft_p50_ms"] = percentile_of(ttft_vals, 50)
            out["router/fleet_ttft_p99_ms"] = percentile_of(ttft_vals, 99)
        if self.tenancy is not None:
            out.update(self.tenancy.metrics())
        return out

    def requests_table(self) -> Dict[str, Any]:
        """Merged /requestz payload: every replica's table, tagged."""
        tables = {rep.name: rep.engine.requests_table()
                  for rep in self.replicas}
        return {"schema": "chainermn_tpu.requestz.v1",
                "fleet": True, "replicas": tables}

    def introspect_state(self) -> Dict[str, Any]:
        """The ``router`` flight/statusz provider: dispatch + rejection
        counters and EVERY replica's ``introspect_state()`` — the
        fleet-wide "what is it doing right now"."""
        with self._lock:
            state: Dict[str, Any] = {
                "replicas": [rep.name for rep in self.replicas],
                "dispatched": self._dispatched,
                "dispatched_by": dict(self._dispatched_by),
                "rejected": dict(self._rejected),
                "affinity_dispatches": self._affinity_hits,
            }
        state["replica_state"] = {
            rep.name: rep.engine.introspect_state()
            for rep in self.replicas}
        if self.slo is not None:
            state["slo"] = self.slo.status()
        if self.tenancy is not None:
            state["tenancy"] = self.tenancy.state()
        return state

    def finalize_metrics(self) -> None:
        """Append the ``router_summary`` JSONL record (per-reason
        rejection counters ride the serving stream; satellite 1)."""
        if self.metrics_writer is not None:
            self.metrics_writer.write(self.metrics(),
                                      kind="router_summary")

    def write_prometheus(self, path: str) -> str:
        from ..observability.export import write_prometheus_textfile
        return write_prometheus_textfile(path, extra_gauges=self.metrics())


def build_fleet(params, n_replicas: int, *,
                slo: Optional[SLOTracker] = None,
                metrics_writer=None,
                shed_burn_threshold: float = 1.0,
                tenancy: Optional[TenantTable] = None,
                **engine_kwargs) -> ServingRouter:
    """Stand up N identically-configured replicas behind one router —
    the ``serve --replicas N`` CLI face.  The fleet SLO tracker is
    shared into every engine so all observations burn one budget;
    ``tenancy`` threads the multi-tenant QoS plane through the shed
    gate (ISSUE 11)."""
    replicas = [
        Replica.build(params, f"replica{i}", slo=slo, **engine_kwargs)
        for i in range(int(n_replicas))]
    return ServingRouter(replicas, slo=slo,
                         shed_burn_threshold=shed_burn_threshold,
                         tenancy=tenancy,
                         metrics_writer=metrics_writer)
