"""Serving fleet router: dispatch, prefix affinity, SLO admission.

PR 3's :class:`~chainermn_tpu.serving.frontend.ServingEngine` is one
pool on one mesh; "millions of users" (ROADMAP item 3) needs the layer
above — the ChainerMN hierarchy lesson applied to serving: N engines as
the fast lane, this host-side router as the slow lane composing them
into ONE service.  Three policies, each deliberately inspectable:

**Dispatch** (least-loaded, deadline-aware, prefix-affine).  Every
candidate replica is scored in TOKEN units::

    score = prefix_match_len − backlog_tokens

``prefix_match_len`` (the replica's radix-trie peek) is compute the
replica does NOT have to do; ``backlog_tokens`` (queued prompt+decode
work plus running remainders) is compute it must do first.  One
currency, so affinity and load balance trade off without magic weights;
ties break to the emptier queue, then round-robin.  A request carrying
a deadline skips replicas whose estimated start delay
(``backlog_tokens × measured token-latency``) already overruns it.

**Admission control** (shed BEFORE the pager fires).  The router owns
the fleet :class:`~chainermn_tpu.observability.slo.SLOTracker` (every
replica feeds TTFT/throughput observations into it) and sheds load
with machine-readable rejections while the pages are still
*approaching*: when the short-window burn rate crosses
``shed_burn_threshold`` (default 1.0× budget — the level that, held,
eventually pages at ``burn_threshold``×) and the fleet has backlog, new
work is refused with ``AdmissionError(reason="shed_slo")`` carrying
``retry_after_ms`` and the fleet queue depth.  Deadline-infeasible
requests (no replica can start in time) shed the same way — a request
that will blow its deadline in the queue only burns budget.  Full
queues everywhere reject ``queue_full`` with the same payload.
Degradation is therefore by EXPLICIT REJECTION, never by queue
collapse: admitted requests' TTFT stays bounded by the queues the
router refused to overfill (the overload acceptance test in
tests/test_serving_router.py asserts this via the goodput ledger's
queue-wait split).

**Observability** (the ISSUE 5 triad, fleet-wide).  The router MINTS
each request's ``trace_id`` before dispatch and passes it through the
replica hop, so one merged Perfetto doc shows ``router/dispatch`` →
queue-wait → prefill/prefix-copy → per-tick spans under a single id.
Rejections are counted per reason in :meth:`metrics` (→ ``/metricsz``)
and streamed as ``router_rejection`` records in the serving JSONL;
``/statusz`` aggregates every replica's ``introspect_state()`` under
the ``router`` flight provider.  See docs/SERVING.md "Router, prefix
cache & admission".
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from .. import observability as obs
from ..observability import flight as _flight
from ..observability.slo import SLOTracker
from .frontend import RequestHandle
from .replica import Replica
from .scheduler import AdmissionError

#: Rejection reasons a router can emit: PR 3's two, ISSUE 7's
#: ``shed_slo``, and ISSUE 9's ``worker_lost`` (a disaggregated
#: transfer's source worker died and no survivor could re-run the
#: prefill — the request is shed with the same machine-readable shape).
REJECT_REASONS = ("queue_full", "too_long", "shed_slo", "worker_lost")


class RouterBase:
    """Shared router machinery (ISSUE 9 refactor): trace-id minting and
    uniformly-shaped machine-readable rejections — one implementation
    behind both the replica fleet (:class:`ServingRouter`) and the
    disaggregated fleet (``serving/disagg.py::DisaggRouter``), so every
    rejection anywhere in the serving stack carries the same
    ``AdmissionError.to_dict()`` wire shape, per-reason counters, and
    JSONL/flight/tracer emissions."""

    #: flight/metrics namespace ("router" / "disagg") — subclasses set.
    ROLE = "router"

    def __init__(self, metrics_writer=None):
        self.metrics_writer = metrics_writer
        self._lock = threading.Lock()
        self._ids = itertools.count()
        self._rejected: Dict[str, int] = {r: 0 for r in REJECT_REASONS}

    def _mint_trace_id(self) -> str:
        return f"req-{os.getpid():x}-rt{next(self._ids):08x}"

    def rejection_counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._rejected)

    def _reject(self, reason: str, trace_id: str, detail: str, *,
                retry_after_ms: float, queue_depth: int):
        with self._lock:
            self._rejected[reason] = self._rejected.get(reason, 0) + 1
        err = AdmissionError(reason, detail,
                             retry_after_ms=retry_after_ms,
                             queue_depth=queue_depth)
        obs.instant(f"{self.ROLE}/rejected", cat="serving", reason=reason,
                    trace_id=trace_id, queue_depth=queue_depth)
        _flight.note(self.ROLE, event="rejected", reason=reason,
                     trace_id=trace_id, detail=detail)
        if self.metrics_writer is not None:
            self.metrics_writer.write(
                dict({f"{self.ROLE}/{k}": v
                      for k, v in err.to_dict().items()
                      if not isinstance(v, str)},
                     reason=reason, trace_id=trace_id),
                kind=f"{self.ROLE}_rejection")
        raise err


class ServingRouter(RouterBase):
    """Process-level router fronting N :class:`Replica` engines.

    ``slo``: the FLEET tracker (shared by every replica's engine so all
    TTFT/throughput observations land in one burn-rate budget); when
    None, ``shed_slo`` only fires on deadline infeasibility.
    ``shed_burn_threshold``: short-window burn rate above which new
    work is shed while backlog exists — set BELOW the tracker's paging
    ``burn_threshold`` so shedding starts before the page.
    """

    def __init__(self, replicas: Sequence[Replica], *,
                 slo: Optional[SLOTracker] = None,
                 shed_burn_threshold: float = 1.0,
                 default_token_latency_ms: float = 20.0,
                 metrics_writer=None,
                 clock: Callable[[], float] = time.monotonic):
        if not replicas:
            raise ValueError("need at least one replica")
        super().__init__(metrics_writer=metrics_writer)
        self.replicas: List[Replica] = list(replicas)
        names = [r.name for r in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique: {names}")
        self.slo = slo
        self.shed_burn_threshold = float(shed_burn_threshold)
        self.default_token_latency_ms = float(default_token_latency_ms)
        self._clock = clock
        self._rr = 0                      # round-robin tie-breaker
        self._dispatched = 0
        self._dispatched_by: Dict[str, int] = {n: 0 for n in names}
        self._affinity_hits = 0           # dispatches won by prefix len
        _flight.register_provider("router", self.introspect_state)

    # ---- submission ----
    def submit(self, prompt, max_new_tokens: int, *,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               on_token=None, temperature: float = 0.0,
               rng=None) -> RequestHandle:
        """Dispatch to the best replica or raise :class:`AdmissionError`
        with a machine-readable reason + ``retry_after_ms`` +
        ``queue_depth`` (the shape ``.to_dict()`` serializes for 429
        bodies and the JSONL stream).  ``temperature``/``rng`` ride the
        hop unchanged (the engine enforces the sampling contract)."""
        trace_id = self._mint_trace_id()
        t0_us = obs.now_us()
        loads = [r.load() for r in self.replicas]
        fleet_depth = sum(ld["queue_depth"] for ld in loads)

        # SLO-aware shedding: refuse while the burn rate is climbing
        # and a backlog exists — BEFORE the multi-window pager fires
        if self.slo is not None and fleet_depth > 0:
            burns = [self.slo.burn_rate(m, self.slo.windows_s[0])
                     for m in ("ttft", "throughput")]
            burning = [b for b in burns if b is not None
                       and b > self.shed_burn_threshold]
            if burning:
                self._reject(
                    "shed_slo", trace_id,
                    f"short-window burn rate {max(burning):.2f}x exceeds "
                    f"shed threshold {self.shed_burn_threshold}x with "
                    f"{fleet_depth} queued",
                    retry_after_ms=self._retry_after_ms(loads),
                    queue_depth=fleet_depth)

        candidates = []
        for i, (rep, ld) in enumerate(zip(self.replicas, loads)):
            if ld["queue_depth"] >= ld["queue_capacity"]:
                continue   # full: submitting would be rejected anyway
            wait_ms = ld["backlog_tokens"] * rep.token_latency_ms(
                self.default_token_latency_ms)
            if deadline_s is not None and wait_ms / 1e3 >= deadline_s:
                continue   # cannot start before the deadline
            match_len = rep.peek_prefix_len(prompt)
            score = match_len - ld["backlog_tokens"]
            candidates.append((score, -ld["queue_depth"], i, rep,
                               match_len))
        if not candidates:
            if all(ld["queue_depth"] >= ld["queue_capacity"]
                   for ld in loads):
                self._reject(
                    "queue_full", trace_id,
                    f"all {len(self.replicas)} replica queues at "
                    f"capacity",
                    retry_after_ms=self._retry_after_ms(loads),
                    queue_depth=fleet_depth)
            # queues have room but no replica can meet the deadline:
            # starting it anyway would only burn SLO budget
            self._reject(
                "shed_slo", trace_id,
                "no replica can start before the request deadline "
                f"(deadline_s={deadline_s})",
                retry_after_ms=self._retry_after_ms(loads),
                queue_depth=fleet_depth)

        # max score, then emptier queue, then round-robin (the i-index
        # rotation keeps a tied fleet evenly loaded)
        rr = self._rr
        best = max(candidates,
                   key=lambda c: (c[0], c[1], -((c[2] - rr)
                                                % len(self.replicas))))
        _, _, idx, rep, match_len = best
        self._rr = (idx + 1) % len(self.replicas)
        try:
            handle = rep.submit(prompt, max_new_tokens, eos_id=eos_id,
                                deadline_s=deadline_s, on_token=on_token,
                                trace_id=trace_id, temperature=temperature,
                                rng=rng)
        except AdmissionError as e:
            # per-request races (another thread filled the queue) and
            # too_long both surface here; re-raise with the router's
            # payload attached so every rejection is uniformly shaped
            self._reject(e.reason, trace_id, str(e),
                         retry_after_ms=self._retry_after_ms(loads),
                         queue_depth=fleet_depth)
        with self._lock:
            self._dispatched += 1
            self._dispatched_by[rep.name] += 1
            if match_len > 0:
                self._affinity_hits += 1
        obs.complete_event(
            "router/dispatch", t0_us, obs.now_us() - t0_us,
            cat="serving_request", trace_id=trace_id, replica=rep.name,
            prefix_match_len=match_len, fleet_queue_depth=fleet_depth)
        _flight.note("router", event="dispatched", trace_id=trace_id,
                     replica=rep.name, prefix_match_len=match_len)
        return handle

    def _retry_after_ms(self, loads) -> float:
        """Back-off hint: the LEAST-loaded replica's estimated time to
        drain one queue slot — clients retrying after it land exactly
        when capacity plausibly exists (floor 1ms keeps it truthy)."""
        per_tok = [r.token_latency_ms(self.default_token_latency_ms)
                   for r in self.replicas]
        est = min(ld["backlog_tokens"] * ms
                  for ld, ms in zip(loads, per_tok))
        return max(float(est), 1.0)

    # ---- driving ----
    def step(self) -> int:
        """ONE fleet scheduling round: step every replica that has
        work; returns how many did (0 == drained).  The deterministic
        single-thread driver the tests and bench use; production runs
        :meth:`start` instead."""
        stepped = 0
        for rep in self.replicas:
            if not rep.idle:
                rep.step()
                stepped += 1
        return stepped

    def run(self, steps_budget: Optional[int] = None) -> int:
        """Drive :meth:`step` until the fleet drains or the budget
        runs out; returns rounds run."""
        n = 0
        while steps_budget is None or n < steps_budget:
            if self.step() == 0:
                break
            n += 1
        return n

    def start(self) -> None:
        for rep in self.replicas:
            rep.start()

    def stop(self) -> None:
        for rep in self.replicas:
            rep.stop()

    def close(self) -> None:
        for rep in self.replicas:
            rep.close()
        if _flight._PROVIDERS.get("router") == self.introspect_state:
            _flight.unregister_provider("router")

    def reset_stats(self) -> None:
        """Zero router counters AND every replica's rolling stats —
        call after warm-up so steady-state numbers don't absorb the
        one-off compiles (bench.py's serving_router section does)."""
        with self._lock:
            self._dispatched = 0
            self._dispatched_by = {n: 0 for n in self._dispatched_by}
            self._rejected = {r: 0 for r in REJECT_REASONS}
            self._affinity_hits = 0
        for rep in self.replicas:
            rep.engine.reset_stats()

    # ---- metrics / introspection ----
    def metrics(self) -> Dict[str, float]:
        """Fleet summary + per-reason rejection counters (the
        ``/metricsz`` ``extra_gauges`` payload and the bench section's
        source).  ``shed``/``rejected`` keys are lower-is-better under
        the regression gate's direction inference."""
        with self._lock:
            dispatched = self._dispatched
            rejected = dict(self._rejected)
            affinity = self._affinity_hits
        out: Dict[str, float] = {
            "router/replicas": float(len(self.replicas)),
            "router/dispatched_total": float(dispatched),
            "router/affinity_dispatches_total": float(affinity),
            "router/rejected_total": float(sum(rejected.values())),
        }
        for reason in REJECT_REASONS:
            out[f"router/rejected/{reason}"] = float(
                rejected.get(reason, 0))
        offered = dispatched + sum(rejected.values())
        out["router/shed_rate"] = (
            sum(rejected.values()) / offered if offered else 0.0)
        # fleet roll-ups from the engines' own metrics (one source of
        # truth); TTFT percentiles merge the replica reservoirs
        tps = occ = 0.0
        ttft_vals: List[float] = []
        for rep in self.replicas:
            m = rep.engine.metrics()
            tps += m["serving/tokens_per_sec"]
            occ += m["serving/slot_occupancy_pct"]
            ttft_vals.extend(rep.engine._ttft_ms.values())
            for k, v in m.items():
                out[f"router/{rep.name}/{k.split('/', 1)[1]}"] = v
        out["router/fleet_tokens_per_sec"] = tps
        out["router/fleet_slot_occupancy_pct"] = occ / len(self.replicas)
        if ttft_vals:
            from ..observability.slo import percentile_of
            out["router/fleet_ttft_p50_ms"] = percentile_of(ttft_vals, 50)
            out["router/fleet_ttft_p99_ms"] = percentile_of(ttft_vals, 99)
        return out

    def requests_table(self) -> Dict[str, Any]:
        """Merged /requestz payload: every replica's table, tagged."""
        tables = {rep.name: rep.engine.requests_table()
                  for rep in self.replicas}
        return {"schema": "chainermn_tpu.requestz.v1",
                "fleet": True, "replicas": tables}

    def introspect_state(self) -> Dict[str, Any]:
        """The ``router`` flight/statusz provider: dispatch + rejection
        counters and EVERY replica's ``introspect_state()`` — the
        fleet-wide "what is it doing right now"."""
        with self._lock:
            state: Dict[str, Any] = {
                "replicas": [rep.name for rep in self.replicas],
                "dispatched": self._dispatched,
                "dispatched_by": dict(self._dispatched_by),
                "rejected": dict(self._rejected),
                "affinity_dispatches": self._affinity_hits,
            }
        state["replica_state"] = {
            rep.name: rep.engine.introspect_state()
            for rep in self.replicas}
        if self.slo is not None:
            state["slo"] = self.slo.status()
        return state

    def finalize_metrics(self) -> None:
        """Append the ``router_summary`` JSONL record (per-reason
        rejection counters ride the serving stream; satellite 1)."""
        if self.metrics_writer is not None:
            self.metrics_writer.write(self.metrics(),
                                      kind="router_summary")

    def write_prometheus(self, path: str) -> str:
        from ..observability.export import write_prometheus_textfile
        return write_prometheus_textfile(path, extra_gauges=self.metrics())


def build_fleet(params, n_replicas: int, *,
                slo: Optional[SLOTracker] = None,
                metrics_writer=None,
                shed_burn_threshold: float = 1.0,
                **engine_kwargs) -> ServingRouter:
    """Stand up N identically-configured replicas behind one router —
    the ``serve --replicas N`` CLI face.  The fleet SLO tracker is
    shared into every engine so all observations burn one budget."""
    replicas = [
        Replica.build(params, f"replica{i}", slo=slo, **engine_kwargs)
        for i in range(int(n_replicas))]
    return ServingRouter(replicas, slo=slo,
                         shed_burn_threshold=shed_burn_threshold,
                         metrics_writer=metrics_writer)
