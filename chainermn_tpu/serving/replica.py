"""One serving replica: a named :class:`ServingEngine` + its driver.

The router (``router.py``) composes N of these into one service — the
ChainerMN ``hierarchical``-communicator lesson applied to serving: a
fast intra-replica lane (the engine's compiled tick over its own slot
pool) under a slower inter-replica lane (host-side dispatch).  The
wrapper is deliberately thin: the engine already owns scheduling,
observability, and metrics; the replica adds only what the ROUTER needs
to make a dispatch decision without reaching into engine internals —

* a stable ``name`` (trace spans, metrics key prefixes, /statusz keys);
* :meth:`load` — the backlog estimate the least-loaded scorer ranks
  (queued + running work in TOKEN units, so prefix-affinity savings
  compare against backlog costs in one currency);
* :meth:`peek_prefix_len` — how much of a prompt this replica's radix
  trie already holds, via the non-mutating peek (probing losers must
  not distort hit rates or LRU order).

In-process replicas each run their own engine (own pool, own compiled
programs); the DCN object lanes (``allgather_obj``) extend the same
shape across processes later (ROADMAP item 4's KV-transfer plane).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .frontend import RequestHandle, ServingEngine


class Replica:
    """Named wrapper around one :class:`ServingEngine`."""

    def __init__(self, name: str, engine: ServingEngine):
        self.name = str(name)
        self.engine = engine

    @classmethod
    def build(cls, params, name: str, **engine_kwargs) -> "Replica":
        return cls(name, ServingEngine(params, **engine_kwargs))

    # ---- dispatch inputs ----
    def load(self) -> Dict[str, Any]:
        """Host-side load snapshot in token units.

        ``backlog_tokens`` = work admitted but not yet delivered: every
        queued request's full cost (prompt prefill + generation) plus
        every running request's remaining generation.  The router's
        score subtracts it from the prefix-affinity credit, and its
        deadline feasibility check multiplies it by the replica's
        measured per-token latency.
        """
        eng = self.engine
        queued = eng.scheduler.queued_requests()
        backlog = sum(r.prompt_len + r.max_new_tokens for r in queued)
        with eng._lock:
            running = list(eng._running.values())
        backlog += sum(max(r.max_new_tokens - len(r.tokens), 0)
                       + len(r.forced) for r in running)
        return {
            "name": self.name,
            "queue_depth": len(queued),
            "queue_capacity": eng.scheduler.queue_capacity,
            "busy_slots": eng.pool.busy_count,
            "free_slots": eng.pool.free_count,
            "cached_slots": eng.pool.cached_count,
            "backlog_tokens": int(backlog),
        }

    def peek_prefix_len(self, prompt) -> int:
        if self.engine.prefix_cache is None:
            return 0
        return self.engine.prefix_cache.peek_len(prompt)

    def token_latency_ms(self, default: float = 20.0) -> float:
        """Measured per-token latency p50 (ms), or ``default`` before
        any tick has been sampled — the deadline estimator's clock."""
        p50 = self.engine._tok_lat_ms.percentile(50)
        return float(p50) if p50 else float(default)

    # ---- pass-throughs ----
    def submit(self, prompt, max_new_tokens: int, *,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               on_token=None,
               trace_id: Optional[str] = None,
               temperature: float = 0.0, rng=None,
               tenant: Optional[str] = None) -> RequestHandle:
        return self.engine.submit(
            prompt, max_new_tokens, eos_id=eos_id, deadline_s=deadline_s,
            on_token=on_token, trace_id=trace_id, temperature=temperature,
            rng=rng, tenant=tenant)

    def step(self):
        return self.engine.step()

    def start(self) -> None:
        self.engine.start()

    def stop(self) -> None:
        self.engine.stop()

    def close(self) -> None:
        self.engine.close()

    @property
    def idle(self) -> bool:
        return (self.engine.scheduler.queue_depth == 0
                and self.engine.pool.busy_count == 0)
