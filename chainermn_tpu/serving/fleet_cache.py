"""Fleet-global prefix-cache index: the router's soft-state radix trie
over every worker's announced KV prefixes (ISSUE 12).

ChainerMN's thesis — distributed state movement as a first-class,
accounted primitive — applied to the serving fleet's hottest state:
each replica's radix-trie prefix cache was PRIVATE, so a 4-worker fleet
re-prefilled the same shared system prompt 4 times.  This index makes
the cache a fleet asset: workers announce every prefix-cache insert /
eviction / spill over the existing mailbox wire (``cache_announce``
messages, epoch-stamped), and the router keeps one compressed radix
trie mapping prefixes → (worker, epoch, slab geometry, tier).  On a
local miss with a remote hit the router can then PULL the slab over the
KV-transfer plane instead of re-prefilling — priced in token units, the
same currency as its affinity score.

Soft-state discipline (the robustness contract):

* the index is a HINT, never ground truth: the owning worker holds the
  slab, and an entry that turns out stale at pull time (evicted since
  the announce) degrades to a counted re-prefill — the index can cost
  a wasted round trip, never a wrong token or a wedge;
* every record carries the announcing worker's EPOCH; the router's
  death/fence path (``supervisor_tick``) drops every record of a fenced
  worker in one call (:meth:`drop_worker`), and a fenced worker's
  buffered announces are refused upstream by the
  :class:`~chainermn_tpu.serving.health.EpochFence` before they ever
  reach the trie;
* a re-admitted worker's state is REBUILT, not patched: the ``hello``
  handshake triggers a full ``snapshot`` announce that replaces
  whatever the index believed about that worker (:meth:`snapshot`);
* records have a ``tier``: ``"hot"`` (device slot) or ``"spill"`` (the
  worker's host-RAM spill store) — a spilled prefix is still pullable,
  it just restores through the CRC-verified payload instead of a fresh
  pack.

Pure host Python, jax-free — fuzzable standalone against per-worker
ground truth (tests/test_kv_economy.py).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

#: Record tiers, best first: a device-resident slab packs fresher than
#: a spilled payload (tie-broken by recency within a tier).
TIERS = ("hot", "spill")


class IndexRecord:
    """One worker's claim: ``seq[:length]``'s K/V is pullable from
    ``worker`` (announced under ``epoch``, with the slab ``geom`` the
    router needs to price the transfer).  ``model_id`` rides the geom
    (ISSUE 18): in a heterogeneous fleet a claim is only pullable into
    a worker serving the SAME variant — K/V from a different model is
    geometry-compatible garbage at best."""

    __slots__ = ("worker", "seq", "length", "epoch", "geom", "tier",
                 "model_id", "last_used")

    def __init__(self, worker: str, seq: Tuple[int, ...], length: int,
                 epoch: int, geom: Optional[Dict[str, Any]],
                 tier: str = "hot"):
        if tier not in TIERS:
            raise ValueError(f"tier must be one of {TIERS}, got {tier!r}")
        self.worker = str(worker)
        self.seq = tuple(int(t) for t in seq)[: int(length)]
        self.length = int(length)
        self.epoch = int(epoch)
        self.geom = dict(geom) if geom else None
        self.tier = tier
        self.model_id = (self.geom or {}).get("model_id")
        self.last_used = 0

    def __repr__(self):
        return (f"IndexRecord({self.worker!r}, len={self.length}, "
                f"epoch={self.epoch}, tier={self.tier}, "
                f"model={self.model_id})")


class _Node:
    """Compressed-trie node; a terminal node can host ONE record per
    worker (several workers may hold the same prefix)."""

    __slots__ = ("edges", "recs", "parent")

    def __init__(self, parent: Optional["_Node"] = None):
        self.edges: Dict[int, Tuple[Tuple[int, ...], "_Node"]] = {}
        self.recs: Dict[str, IndexRecord] = {}
        self.parent = parent


def _common_len(a, b) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class FleetCacheIndex:
    """The router-side half of the fleet KV economy: announce-driven
    radix trie + per-worker reverse map, one lock (host microseconds;
    announces and lookups come from the router thread and submit
    threads)."""

    def __init__(self, min_prefix_len: int = 2):
        self._lock = threading.Lock()
        self._root = _Node()
        # worker -> {seq tuple -> record} (the drop/snapshot face)
        self._by_worker: Dict[str, Dict[Tuple[int, ...], IndexRecord]] = {}
        self._clock = 0
        self.min_prefix_len = max(int(min_prefix_len), 1)
        # counters (the fleet_health provider block + /metricsz)
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.evicts = 0
        self.demotions = 0
        self.snapshots = 0
        self.dropped_workers = 0
        self.stale_fallbacks: Dict[str, int] = {}

    # ------------------------------------------------------------------
    # announces (the router's pump feeds these, already fence-gated)
    # ------------------------------------------------------------------
    def insert(self, worker: str, epoch: int, seq, length: int,
               geom: Optional[Dict[str, Any]] = None,
               tier: str = "hot") -> IndexRecord:
        rec = IndexRecord(worker, tuple(seq), length, epoch, geom, tier)
        if len(rec.seq) < self.min_prefix_len:
            return rec    # unusably short: never index it
        with self._lock:
            self._clock += 1
            rec.last_used = self._clock
            old = self._by_worker.get(rec.worker, {}).get(rec.seq)
            if old is not None:
                self._remove_locked(old)
            node = self._insert_node(rec.seq)
            node.recs[rec.worker] = rec
            self._by_worker.setdefault(rec.worker, {})[rec.seq] = rec
            self.inserts += 1
        return rec

    def evict(self, worker: str, seq, tier: Optional[str] = None
              ) -> bool:
        """A worker announced it no longer holds ``seq`` (device slot
        scavenged AND not spilled, or the spill store dropped it).
        ``tier`` scopes the removal: a SPILL-store eviction must only
        drop a ``spill``-tier record — the worker may have re-donated
        the same sequence to its device trie since (the record is
        ``hot`` again), and deleting that claim would silently stop
        the router pulling a prefix the worker still holds."""
        seq = tuple(int(t) for t in seq)
        with self._lock:
            rec = self._by_worker.get(str(worker), {}).get(seq)
            if rec is None or (tier is not None and rec.tier != tier):
                return False
            self._remove_locked(rec)
            self.evicts += 1
            return True

    def demote(self, worker: str, seq, tier: str = "spill") -> bool:
        """Device slot scavenged but the slab SPILLED: the prefix is
        still pullable from the worker's host tier."""
        seq = tuple(int(t) for t in seq)
        with self._lock:
            rec = self._by_worker.get(str(worker), {}).get(seq)
            if rec is None:
                return False
            rec.tier = tier
            self.demotions += 1
            return True

    def snapshot(self, worker: str, epoch: int, entries,
                 geom: Optional[Dict[str, Any]] = None) -> int:
        """Full rebuild of one worker's view — rides the ``hello``
        re-admission handshake: whatever the index believed about the
        worker is REPLACED by what the worker says it holds now."""
        self.drop_worker(worker, count=False)
        n = 0
        for ent in entries:
            self.insert(worker, epoch, ent["seq"], ent["length"],
                        geom=ent.get("geom", geom),
                        tier=ent.get("tier", "hot"))
            n += 1
        with self._lock:
            self.snapshots += 1
        return n

    def drop_worker(self, worker: str, count: bool = True) -> int:
        """The death/fence/drain path: every record of ``worker`` is
        soft state of a corpse — drop them all in one sweep."""
        with self._lock:
            recs = list(self._by_worker.get(str(worker), {}).values())
            for rec in recs:
                self._remove_locked(rec)
            if count and recs:
                self.dropped_workers += 1
            return len(recs)

    def reset_counters(self) -> None:
        """Zero the rate counters (hits/misses/stale fallbacks) while
        keeping the structure and its structural counters — the bench
        warm-up must not leak into the measured window
        (``FleetRouter.reset_stats`` calls this)."""
        with self._lock:
            self.hits = 0
            self.misses = 0
            self.stale_fallbacks = {}

    def count_stale(self, reason: str) -> None:
        """A claim this index advertised turned out wrong at pull time
        — the counted degrade-to-re-prefill outcome, per reason."""
        with self._lock:
            self.stale_fallbacks[reason] = \
                self.stale_fallbacks.get(reason, 0) + 1

    # ------------------------------------------------------------------
    # trie plumbing
    # ------------------------------------------------------------------
    def _insert_node(self, seq: Tuple[int, ...]) -> "_Node":
        node, depth = self._root, 0
        while True:
            if depth == len(seq):
                return node
            edge = node.edges.get(seq[depth])
            if edge is None:
                child = _Node(parent=node)
                node.edges[seq[depth]] = (seq[depth:], child)
                return child
            label, child = edge
            k = _common_len(label, seq[depth:])
            if k == len(label):
                node, depth = child, depth + k
                continue
            mid = _Node(parent=node)
            node.edges[seq[depth]] = (label[:k], mid)
            mid.edges[label[k]] = (label[k:], child)
            child.parent = mid
            node, depth = mid, depth + k

    def _remove_locked(self, rec: IndexRecord) -> None:
        by = self._by_worker.get(rec.worker)
        if by is not None:
            by.pop(rec.seq, None)
            if not by:
                self._by_worker.pop(rec.worker, None)
        node, depth, partial = self._walk(rec.seq)
        if depth == len(rec.seq) and partial is None \
                and node.recs.get(rec.worker) is rec:
            del node.recs[rec.worker]
            self._prune(node)

    def _walk(self, seq) -> Tuple["_Node", int, Optional["_Node"]]:
        node, depth = self._root, 0
        while depth < len(seq):
            edge = node.edges.get(seq[depth])
            if edge is None:
                return node, depth, None
            label, child = edge
            k = _common_len(label, seq[depth:])
            depth += k
            if k < len(label):
                return node, depth, child
            node = child
        return node, depth, None

    def _prune(self, node: "_Node") -> None:
        while node is not None and node is not self._root \
                and not node.recs and not node.edges:
            parent = node.parent
            for tok, (label, child) in list(parent.edges.items()):
                if child is node:
                    del parent.edges[tok]
                    break
            node = parent

    def _subtree_best(self, node: "_Node", workers=None,
                      model_id: Optional[str] = None
                      ) -> Optional[IndexRecord]:
        """Best record in the subtree: hot beats spill, recent beats
        old (record count is bounded by slots × workers — cheap DFS).
        ``model_id`` pins the variant: an unlabeled record (no geom)
        is REFUSED under a pinned query — conservative, because a
        cross-model pull is silent garbage, a re-prefill is just
        tokens."""
        best: Optional[IndexRecord] = None
        stack = [node]
        while stack:
            n = stack.pop()
            for rec in n.recs.values():
                if workers is not None and rec.worker not in workers:
                    continue
                if model_id is not None and rec.model_id != model_id:
                    continue
                if best is None or (
                        (TIERS.index(rec.tier), -rec.last_used)
                        < (TIERS.index(best.tier), -best.last_used)):
                    best = rec
            stack.extend(child for _, child in n.edges.values())
        return best

    # ------------------------------------------------------------------
    # lookup
    # ------------------------------------------------------------------
    def match(self, prompt, workers=None, count: bool = True,
              model_id: Optional[str] = None
              ) -> Tuple[Optional[IndexRecord], int]:
        """Longest indexed prefix of ``prompt`` among ``workers`` (None
        = any): ``(record, match_len)`` with the trie-cache semantics —
        capped at ``len(prompt) - 1`` and the record's own length — or
        ``(None, 0)``.  ``count=False`` is the peek face (per-worker
        probes must not distort the hit/miss counters).  ``model_id``
        keys the claim (ISSUE 18): only same-variant records match; a
        prefix that WOULD have hit another variant's slab is a counted
        ``model_mismatch`` stale fallback, never a cross-model pull."""
        prompt = tuple(int(t) for t in prompt)
        if len(prompt) < 2:
            if count:
                with self._lock:
                    self.misses += 1
            return None, 0
        with self._lock:
            node, depth, partial = self._walk(prompt[: len(prompt) - 1])
            sub = partial if partial is not None else node
            rec = self._subtree_best(sub, workers, model_id)
            if rec is None and model_id is not None \
                    and depth >= self.min_prefix_len and count \
                    and self._subtree_best(sub, workers) is not None:
                # the ONLY claims on this prefix belong to a different
                # variant — the heterogeneous-fleet near-miss, counted
                # under the existing stale-fallback discipline
                self.stale_fallbacks["model_mismatch"] = \
                    self.stale_fallbacks.get("model_mismatch", 0) + 1
            if rec is None or depth < self.min_prefix_len:
                if count:
                    self.misses += 1
                return None, 0
            match_len = min(depth, rec.length, len(prompt) - 1)
            if match_len < self.min_prefix_len:
                if count:
                    self.misses += 1
                return None, 0
            if count:
                self.hits += 1
                self._clock += 1
                rec.last_used = self._clock
            return rec, match_len

    def match_for(self, worker: str, prompt) -> int:
        """Longest indexed prefix ``worker`` itself claims (the LOCAL
        half of the pull decision) — peek semantics, no counters."""
        _, mlen = self.match(prompt, workers={str(worker)}, count=False)
        return mlen

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def entries_for(self, worker: str
                    ) -> Dict[Tuple[int, ...], Tuple[int, str]]:
        with self._lock:
            return {seq: (rec.length, rec.tier)
                    for seq, rec in
                    self._by_worker.get(str(worker), {}).items()}

    def workers(self) -> List[str]:
        with self._lock:
            return sorted(self._by_worker)

    @property
    def n_entries(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._by_worker.values())

    def stats(self) -> Dict[str, float]:
        with self._lock:
            per_worker = {w: float(len(v))
                          for w, v in self._by_worker.items()}
            return {
                "entries": float(sum(len(v)
                                     for v in self._by_worker.values())),
                "workers": float(len(self._by_worker)),
                "hits": float(self.hits),
                "misses": float(self.misses),
                "inserts": float(self.inserts),
                "evicts": float(self.evicts),
                "demotions": float(self.demotions),
                "snapshots": float(self.snapshots),
                "dropped_workers": float(self.dropped_workers),
                "stale_fallbacks": float(
                    sum(self.stale_fallbacks.values())),
                **{f"entries/{w}": n for w, n in sorted(
                    per_worker.items())},
            }

    def state(self) -> Dict[str, Any]:
        """The ``fleet_health`` provider's cache-index block."""
        with self._lock:
            return {
                "entries": sum(len(v)
                               for v in self._by_worker.values()),
                "per_worker": {
                    w: [{"len": rec.length, "tier": rec.tier,
                         "epoch": rec.epoch, "model": rec.model_id,
                         "seq_head": list(rec.seq[:8])}
                        for rec in sorted(v.values(),
                                          key=lambda r: -r.last_used)]
                    for w, v in sorted(self._by_worker.items())},
                "hits": self.hits,
                "misses": self.misses,
                "inserts": self.inserts,
                "evicts": self.evicts,
                "demotions": self.demotions,
                "snapshots": self.snapshots,
                "stale_fallbacks": dict(self.stale_fallbacks),
            }

    def check_invariants(self) -> None:
        """Trie/reverse-map agreement: every reverse-map record sits at
        its terminal node, every node record is reverse-mapped."""
        with self._lock:
            for worker, by in self._by_worker.items():
                for seq, rec in by.items():
                    node, depth, partial = self._walk(seq)
                    assert depth == len(seq) and partial is None, rec
                    assert node.recs.get(worker) is rec, rec
            stack = [self._root]
            seen = 0
            while stack:
                n = stack.pop()
                for rec in n.recs.values():
                    assert self._by_worker.get(rec.worker, {}).get(
                        rec.seq) is rec, rec
                    seen += 1
                stack.extend(child for _, child in n.edges.values())
            assert seen == sum(len(v) for v in self._by_worker.values())
